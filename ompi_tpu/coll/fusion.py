"""Small-message collective fusion/coalescing: the device fast path.

Round-5 measurement (BENCH_NOTES.md) showed every device collective
pays a ~150-600 us size-independent tunnel-dispatch round-trip, so the
4-64 KiB band loses to the host seg path even though the op itself is
nearly free there.  The fix is the reference's message-coalescing idea
applied at the XLA layer: when a rank has several small collectives
pending (surfaced through the nonblocking coll surface, coll/nbc),
pack their payloads into ONE flattened buffer per (reducer, dtype)
group — offset table from datatype/device.py — and issue a SINGLE
fused XLA call (one psum over the concatenation, bcasts joining the
SUM group as masked summands), then slice results back out.  One
dispatch amortized over N collectives.

Surface: ``comm.iallreduce_arr`` / ``comm.ibcast_arr`` return a
``FusedRequest``; pending ops coalesce until an explicit
``comm.flush_arr()``, a ``wait()``/``test()`` on any request of the
batch, the ``coll_device_fusion_max_ops`` bound, or MPI_Finalize
(dispatcher-drain hook) flushes them.  Ineligible ops (big payloads,
host-only comms, exotic ops) execute immediately through the blocking
vtable and return an already-complete request — callers never branch.

Batch symmetry: the flush is one rendezvous per batch, so every member
rank must enqueue the SAME sequence of collectives between flushes
(the usual SPMD discipline MPI already requires for collective
ordering).  The fused signature is validated at the meeting point —
a divergent batch raises a clear error on every rank instead of
deadlocking.
"""

from __future__ import annotations

import numpy as np

from ompi_tpu import obs as _obs
from ompi_tpu import trace as _trace
from ompi_tpu.obs import integrity as _ig
from ompi_tpu.mca.params import registry
from ompi_tpu.op.op import Op
from ompi_tpu.pml.request import Request

_fusion_var = registry.register(
    "coll", "device", "fusion", True, bool,
    help="Coalesce pending small nonblocking device collectives "
         "(iallreduce_arr/ibcast_arr) into one fused XLA call per "
         "batch, amortizing the per-op dispatch constant")
_threshold_var = registry.register(
    "coll", "device", "fusion_threshold", 65536, int,
    help="Per-op payload bound (bytes) for fusion eligibility; larger "
         "payloads are bandwidth-dominated and run unfused "
         "immediately")
_max_ops_var = registry.register(
    "coll", "device", "fusion_max_ops", 32, int,
    help="Auto-flush a pending fusion batch at this many collectives "
         "(bounds result latency and fused-executable arity)")

# session-banded (ompi_tpu/obs): on a resident pool each flush
# belongs to exactly one session (the engine is per-comm, the comm's
# state carries cid_band), so attribution is a band index away.
# Global reads through the registry are untouched.
_pv_batches = _obs.scoped_pvar(
    "coll", "device", "fused_batches",
    help="Fused device-collective batches dispatched")
_pv_colls = _obs.scoped_pvar(
    "coll", "device", "fused_collectives",
    help="Individual collectives that rode in a fused batch")
_pv_bytes = _obs.scoped_pvar(
    "coll", "device", "fused_bytes",
    help="Payload bytes carried by fused batches")

# -- cross-session batching (the DVM serve plane, tools/dvm) ---------------
# Concurrently-resident sessions are independent worlds multiplexed
# over the SAME device mesh, so their fused batches — each already one
# dispatch — can share a single XLA call when they land within a short
# window of each other.  The window only opens while the pool reports
# >1 resident session (set_xsession_hint), so solo jobs never pay it.
_xwin_var = registry.register(
    "dvm", "", "batch_window_us", 0, int,
    help="Cross-session fusion window (microseconds): a fused batch "
         "dispatched from a DVM-resident session waits this long for "
         "compatible batches from OTHER resident sessions and rides "
         "one combined XLA dispatch with them.  0 disables.  Only "
         "consulted while more than one session is resident "
         "(tpu-dvm --batch-window-us sets it pool-wide)")
_pv_xbatches = registry.register_pvar(
    "dvm", "", "xsession_batches",
    help="Combined dispatches that carried fused batches from 2+ "
         "concurrently-resident DVM sessions")
_pv_xcolls = registry.register_pvar(
    "dvm", "", "xsession_collectives",
    help="Individual collectives that rode a cross-session combined "
         "dispatch")

_xsession_hint = 0  # resident-session count, maintained by tools/dvm


def set_xsession_hint(n: int) -> None:
    """The DVM pool reports its resident-session count here on every
    attach/detach; the cross-session window opens only above 1."""
    global _xsession_hint
    _xsession_hint = n


class FusedRequest(Request):
    """Request handle for a (possibly) coalesced device collective.

    ``result`` is the output array once complete.  Completion requires
    running the fused batch — a bare progress sweep cannot do that, so
    ``wait()`` AND ``test()`` both flush the owning engine's pending
    batch (the batch rendezvous blocks on peers; under the SPMD batch
    discipline they are flushing too)."""

    def __init__(self, progress, engine) -> None:
        super().__init__(progress)
        self._engine = engine
        self._error = None
        self.result = None

    def _deliver(self, value) -> None:
        self.result = value
        self._complete()

    def _fail(self, exc: BaseException) -> None:
        self._error = exc
        self._complete()

    def test(self) -> bool:
        if not self.complete and self._engine is not None:
            self._engine.flush()
        return self.complete

    def wait(self, timeout=None):
        if not self.complete and self._engine is not None:
            self._engine.flush()
        st = super().wait(timeout)
        if self._error is not None:
            from ompi_tpu.errhandler import MPIException
            if isinstance(self._error, MPIException):
                # ULFM classes (PROC_FAILED/REVOKED) must surface
                # unchanged so the app's recovery logic can match on
                # the error class
                raise self._error
            raise RuntimeError(
                f"fused device collective failed: {self._error}"
            ) from self._error
        return st


class _Pending:
    __slots__ = ("kind", "x", "extra", "was_scalar", "nbytes", "req")

    def __init__(self, kind, x, extra, was_scalar, nbytes, req) -> None:
        self.kind = kind            # "allreduce" | "bcast"
        self.x = x                  # normalized payload (ndim >= 1)
        self.extra = extra          # opname (allreduce) or root (bcast)
        self.was_scalar = was_scalar
        self.nbytes = nbytes
        self.req = req


def _nbytes_of(x) -> int:
    """Payload bytes from shape x itemsize — the ``.nbytes`` property
    on device arrays walks the aval and costs microseconds; this runs
    on every nonblocking enqueue."""
    n = 1
    for s in getattr(x, "shape", ()):
        n *= s
    return n * x.dtype.itemsize


_RED_OPS = ("MPI_SUM", "MPI_MAX", "MPI_MIN")


def _group_plan(sig):
    """Static fusion plan, a pure function of the batch signature (so
    every rank and every cache layer derives the same plan): slots
    grouped by (reducer opname, dtype) — bcast joins the SUM group of
    its dtype as a root-masked summand — plus the gather-fold slots
    that keep per-slot all_gathers inside the same dispatch."""
    groups = {}
    folds = []
    for i, (kind, _shape, dt, extra) in enumerate(sig):
        if kind == "bcast":
            groups.setdefault(("MPI_SUM", dt), []).append(i)
        elif extra in _RED_OPS:
            groups.setdefault((extra, dt), []).append(i)
        else:
            folds.append(i)
    return (tuple((opname, dt, tuple(slots))
                  for (opname, dt), slots in groups.items()),
            tuple(folds))


def _fused_ck(mode, sig):
    """Integrity spec for one fused batch (DESIGN.md §25): one claim
    per deposit buffer — mesh mode digests each packed group buffer
    (claim index = group index), hbm mode digests each slot array.
    Returns None when any slot falls outside the checkable algebra
    (gather folds, non-native reducers, unsupported dtypes): a partly
    checked batch could not attribute a mismatch to one rank, so the
    whole batch runs unchecked instead."""
    ents = []
    if mode == "hbm":
        for i, (kind, _shape, dt, extra) in enumerate(sig):
            if kind == "bcast":
                s = _ig.spec_static("bcast", "", np.empty(0, dt), extra)
                if s is None:
                    return None
                ents.append(("b", s[1], i, int(extra), s[2]))
            elif kind == "allreduce":
                s = _ig.spec_static("allreduce", extra, np.empty(0, dt))
                if s is None:
                    return None
                ents.append(("g", s[1], i, (i,), s[2]))
            else:
                return None
    else:
        groups, folds = _group_plan(sig)
        if folds:
            return None
        for gi, (opname, dt, slots) in enumerate(groups):
            # bcast slots ride SUM groups root-masked to the identity,
            # so the group conservation sum covers them exactly.
            s = _ig.spec_static("allreduce", opname, np.empty(0, dt))
            if s is None:
                return None
            ents.append(("g", s[1], gi, slots, s[2]))
    return ("fused", tuple(ents))


def _build_pack(dev, sig, slots, roots):
    """Per-rank group pack: flatten + concatenate this rank's pending
    payloads of one (reducer, dtype) group into ONE buffer (offset
    table from datatype/device), masking non-root bcast slots to the
    reducer identity, with the output committed to the rank's own mesh
    device.  Packing on the owning rank's thread is what keeps the
    batch meeting point cheap: the last arriver assembles G committed
    group buffers instead of moving N stray slot arrays."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import SingleDeviceSharding

    from ompi_tpu.datatype.device import pack_segments

    def body(*xs):
        flats = []
        for j in range(len(slots)):
            f = xs[j].reshape(-1)
            if roots[j] is False:  # non-root bcast: contribute zeros
                f = jnp.zeros_like(f)
            flats.append(f)
        return pack_segments(flats)

    return jax.jit(body, out_shardings=SingleDeviceSharding(dev))


def _mesh_slot_outs(sig, xs):
    """Traced body of one session's mesh-mode batch: ``xs`` is its
    packed group buffers followed by its raw gather-fold slots;
    returns the per-slot outputs.  Shared by the single-batch and the
    cross-session combined executables so both trace the SAME ops per
    batch — the byte-identity contract of the serve plane."""
    from jax import lax

    from ompi_tpu.coll import device
    from ompi_tpu.datatype.device import segment_offsets

    red_map = {"MPI_SUM": lax.psum, "MPI_MAX": lax.pmax,
               "MPI_MIN": lax.pmin}
    groups, folds = _group_plan(sig)
    outs = [None] * len(sig)
    for gi, (opname, _dt, slots) in enumerate(groups):
        shapes = [sig[i][1] for i in slots]
        offs, lens, _total = segment_offsets(shapes)
        red = red_map[opname](xs[gi], "r")
        for j, i in enumerate(slots):
            outs[i] = red[offs[j]:offs[j] + lens[j]].reshape(shapes[j])
    for fi, i in enumerate(folds):
        fold = device._fold_fn(sig[i][3])
        outs[i] = fold(lax.all_gather(xs[len(groups) + fi], "r",
                                      tiled=False))
    return outs


def _mesh_nin(sig) -> int:
    groups, folds = _group_plan(sig)
    return len(groups) + len(folds)


def _build_fused_mesh(mesh, sig):
    """One jitted shard_map running a whole fused batch on the comm
    mesh.  Inputs are the per-rank packed group buffers (one per
    (reducer, dtype) group, already masked and concatenated by
    _build_pack) followed by the raw gather-fold slots; each group is
    reduced with ONE psum/pmax/pmin over the concatenation and sliced
    back out at the static offsets."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ompi_tpu.coll import device

    def body(*xs):
        return tuple(_mesh_slot_outs(sig, xs))

    nin = _mesh_nin(sig)
    return jax.jit(device.shard_map_compat(
        body, mesh, (P("r"),) * nin, (P(None),) * len(sig)))


def _build_fused_mesh_multi(mesh, sigs):
    """Cross-session combined dispatch (mesh mode): one shard_map
    carrying several sessions' fused batches back to back.  Each
    session's segment is computed exactly as its solo executable
    would — the combination only amortizes the dispatch."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ompi_tpu.coll import device

    nins = [_mesh_nin(s) for s in sigs]

    def body(*xs):
        outs = []
        off = 0
        for s, nin in zip(sigs, nins):
            outs.extend(_mesh_slot_outs(s, xs[off:off + nin]))
            off += nin
        return tuple(outs)

    nout = sum(len(s) for s in sigs)
    return jax.jit(device.shard_map_compat(
        body, mesh, (P("r"),) * sum(nins), (P(None),) * nout))


def _hbm_slot_outs(size, sig, xs):
    """Traced body of one session's hbm-mode batch over its slot-major
    ``len(sig)*size`` shards (shared by solo and cross-session
    combined executables — see _mesh_slot_outs)."""
    import jax.numpy as jnp

    from ompi_tpu.coll import device

    outs = []
    for i, (kind, _shape, _dt, extra) in enumerate(sig):
        shards = xs[i * size:(i + 1) * size]
        if kind == "bcast":
            outs.append(shards[extra])
        elif extra == "MPI_SUM":
            outs.append(jnp.sum(jnp.stack(shards), axis=0))
        elif extra == "MPI_MAX":
            outs.append(jnp.max(jnp.stack(shards), axis=0))
        elif extra == "MPI_MIN":
            outs.append(jnp.min(jnp.stack(shards), axis=0))
        else:
            outs.append(device._fold_fn(extra)(jnp.stack(shards)))
    return outs


def _build_fused_hbm(size, sig):
    """Fused batch for single-chip comms (coll/hbm): one jit taking
    slot-major ``n*size`` shards; each slot stacks + reduces (or picks
    the root shard for bcast).  The win is the single dispatch."""
    import jax

    def body(*xs):
        return tuple(_hbm_slot_outs(size, sig, xs))

    return jax.jit(body)


def _build_fused_hbm_multi(size, sigs):
    """Cross-session combined dispatch (hbm mode): several sessions'
    slot-major shard lists concatenated into one jit call."""
    import jax

    def body(*xs):
        outs = []
        off = 0
        for s in sigs:
            n = len(s) * size
            outs.extend(_hbm_slot_outs(size, s, xs[off:off + n]))
            off += n
        return tuple(outs)

    return jax.jit(body)


class _XEntry:
    __slots__ = ("sig", "args", "outs", "err", "event")

    def __init__(self, sig, args) -> None:
        import threading
        self.sig = sig
        self.args = args
        self.outs = None
        self.err = None
        self.event = threading.Event()


class _XBatcher:
    """Process-global meeting point for cross-session batch
    coalescing.  Callers are the last-arriver threads of independent
    sessions' batch rendezvous (device.meet fn) — one thread per
    session batch.  The first arriver under a compatibility key
    becomes the leader: it holds the window open, then runs ONE
    combined executable over every batch that joined and hands each
    follower its slice.  Entries are sorted by signature before
    combining so the compiled-executable cache key is arrival-order
    independent."""

    def __init__(self) -> None:
        import threading
        self.lock = threading.Lock()
        self.groups = {}  # key -> list of _XEntry (open window)

    def run(self, key, sig, args, single_fn, multi_key, multi_build):
        import time as _time

        win_s = max(0, _xwin_var.value) / 1e6
        e = _XEntry(sig, args)
        with self.lock:
            grp = self.groups.get(key)
            leader = grp is None
            if leader:
                self.groups[key] = [e]
            else:
                grp.append(e)
        if leader:
            _time.sleep(win_s)
            with self.lock:
                entries = self.groups.pop(key)
            self._dispatch(entries, single_fn, multi_key, multi_build)
        if not e.event.wait(timeout=120.0):
            raise RuntimeError(
                "cross-session batch leader did not dispatch within "
                "120s (dvm_batch_window_us misconfigured or leader "
                "session died mid-window)")
        if e.err is not None:
            raise RuntimeError(
                f"cross-session combined dispatch failed: {e.err}"
            ) from e.err
        return e.outs

    def _dispatch(self, entries, single_fn, multi_key,
                  multi_build) -> None:
        from ompi_tpu.coll import device
        try:
            if len(entries) == 1:
                entries[0].outs = single_fn(entries[0].args)
            else:
                order = sorted(range(len(entries)),
                               key=lambda i: repr(entries[i].sig))
                sigs = tuple(entries[i].sig for i in order)
                jfn = device.compile_cache.get(
                    multi_key(sigs), lambda: multi_build(sigs))
                flat = [a for i in order for a in entries[i].args]
                outs = jfn(*flat)
                off = 0
                for i in order:
                    n = len(entries[i].sig)
                    entries[i].outs = tuple(outs[off:off + n])
                    off += n
                _pv_xbatches.add(1)
                _pv_xcolls.add(off)
        except BaseException as exc:  # noqa: BLE001
            for e in entries:
                e.err = exc
        finally:
            for e in entries:
                e.event.set()


_xbatcher = _XBatcher()


def _xdispatch(key, sig, args, single_fn, multi_key, multi_build):
    """Run one session's prepared fused batch: straight through when
    the cross-session window is closed (knob 0, or the pool reports
    <2 resident sessions), else through the batcher."""
    if _xwin_var.value <= 0 or _xsession_hint < 2:
        return single_fn(args)
    return _xbatcher.run(key, sig, args, single_fn, multi_key,
                         multi_build)


class _FusionEngine:
    """Per-comm, per-rank staging area for pending fusible collectives.
    Single-threaded (each rank owns its comm object); flush runs the
    whole batch through ONE device.meet rendezvous."""

    def __init__(self, comm) -> None:
        from ompi_tpu.coll import device
        self.comm = comm
        prov = getattr(comm.coll, "providers", None) or {}
        m = prov.get("allreduce_arr")
        self.mode = m if m in ("tpu", "hbm") else None
        self.pending = []
        self._abort_check = device.TpuCollModule._abort_check(None, comm)
        # finalize hook registration happens HERE, not first meet():
        # a batch enqueued and never waited on must still flush at
        # MPI_Finalize, even if no blocking collective ever ran
        device.track_state(comm.state)

    def enqueue(self, kind, x, extra, nbytes) -> FusedRequest:
        if getattr(x, "ndim", None) == 0:
            x, was_scalar = x.reshape(1), True
        else:
            was_scalar = False
        req = FusedRequest(self.comm.state.progress, self)
        self.pending.append(
            _Pending(kind, x, extra, was_scalar, nbytes, req))
        if len(self.pending) >= max(1, _max_ops_var.value):
            self.flush()
        return req

    def flush(self) -> None:
        if not self.pending:
            return
        batch, self.pending = self.pending, []
        tr = self.comm.state.tracer
        t0 = tr.start_sampled(_trace.CAT_COLL) if tr is not None else 0
        try:
            outs = self._run(batch)
        except BaseException as e:  # noqa: BLE001
            for p in batch:
                p.req._fail(e)
            raise
        if t0:
            tr.end(t0, _trace.NAME_FUSED_FLUSH, _trace.CAT_COLL,
                   self.comm.cid, len(batch))
        nbytes = 0
        for p, out in zip(batch, outs):
            nbytes += p.nbytes
            p.req._deliver(out.reshape(()) if p.was_scalar else out)
        band = self.comm.state.cid_band
        _pv_batches.add(1, band)
        _pv_colls.add(len(batch), band)
        _pv_bytes.add(nbytes, band)

    def _pack_groups(self, sig, batch):
        """Mesh-mode deposit payload: this rank's slots packed into one
        committed buffer per (reducer, dtype) group (masked for bcast)
        followed by the raw gather-fold slots.  Runs on the owning
        rank's thread BEFORE the rendezvous, so the batch meeting point
        only assembles G pre-placed group buffers — the placement cost
        that used to serialize on the last arriver."""
        import jax

        from ompi_tpu.coll import device

        comm = self.comm
        tr = comm.state.tracer
        t0 = tr.start_sampled(_trace.CAT_COLL) if tr is not None else 0
        # phase profiler (docs/DESIGN.md §18): the fused pack is the
        # host-pack phase of the op the following meet() dispatches —
        # comm._dev_seq is exactly the seq that meet will record
        tp = tr.start_sampled(_trace.CAT_PHASE) \
            if tr is not None and tr.phase else 0
        mesh = comm.mesh()
        my_dev = mesh.devices.reshape(-1)[comm.rank]
        groups, folds = _group_plan(sig)
        deposit = []
        for gi, (opname, dt, slots) in enumerate(groups):
            roots = tuple(
                (sig[i][3] == comm.rank) if sig[i][0] == "bcast"
                else None for i in slots)
            packfn = device.compile_cache.get(
                ("fusedpack", my_dev.id, sig, gi, roots),
                lambda d=my_dev, s=slots, r=roots:
                    _build_pack(d, sig, s, r))
            args = [batch[i].x for i in slots]
            try:
                deposit.append(packfn(*args))
            except ValueError:
                # inputs committed to clashing devices: canonicalize
                deposit.append(packfn(*[jax.device_put(a, my_dev)
                                        for a in args]))
        deposit.extend(batch[i].x for i in folds)
        if tp:
            tr.end(tp, _trace.NAME_PH_PACK, _trace.CAT_PHASE,
                   comm.cid, comm._dev_seq, 0)
        if t0:
            tr.end(t0, _trace.NAME_FUSED_PACK, _trace.CAT_COLL,
                   comm.cid, len(groups), len(sig))
        return deposit

    def _run(self, batch):
        from ompi_tpu.coll import device

        comm = self.comm
        size = comm.size
        sig = tuple(
            (p.kind, tuple(p.x.shape), np.dtype(p.x.dtype).str, p.extra)
            for p in batch)
        if self.mode == "hbm":
            import jax
            arrays = [p.x if device._is_jax_array(p.x)
                      else jax.device_put(np.asarray(p.x),
                                          comm.state.device)
                      for p in batch]
        else:
            arrays = self._pack_groups(sig, batch)
        mode = self.mode

        def fn(shards):
            sig0 = shards[0][0]
            for r, (s, _a) in enumerate(shards):
                if s != sig0:
                    raise RuntimeError(
                        f"fused-collective batch mismatch: rank {r} "
                        f"enqueued {s} but rank 0 enqueued {sig0}; "
                        "every member must issue the same nonblocking "
                        "device collectives between flushes")
            nslots = len(sig0)
            if mode == "hbm":
                args = [shards[r][1][i]
                        for i in range(nslots) for r in range(size)]

                def single_hbm(a):
                    jfn = device.compile_cache.get(
                        ("fused_hbm", size, sig0),
                        lambda: _build_fused_hbm(size, sig0))
                    return jfn(*a)

                outs = _xdispatch(
                    ("hbm", size), sig0, args, single_hbm,
                    lambda sigs: ("fusedx_hbm", size, sigs),
                    lambda sigs: _build_fused_hbm_multi(size, sigs))
            else:
                mesh = comm.mesh()
                dev_key = tuple(
                    d.id for d in mesh.devices.reshape(-1))
                nin = _mesh_nin(sig0)
                ins = [
                    device._assemble(
                        mesh, [shards[r][1][j] for r in range(size)])
                    for j in range(nin)]

                def single_mesh(a):
                    jfn = device.compile_cache.get(
                        ("fused", dev_key, sig0),
                        lambda: _build_fused_mesh(mesh, sig0))
                    return jfn(*a)

                outs = _xdispatch(
                    ("mesh", dev_key), sig0, ins, single_mesh,
                    lambda sigs: ("fusedx", dev_key, sigs),
                    lambda sigs: _build_fused_mesh_multi(mesh, sigs))
            # every output is replicated (psum/root-pick): all ranks
            # read the same arrays
            return [list(outs)] * size

        ck = _fused_ck(mode, sig) if _ig.on else None
        return device.meet(comm, (sig, arrays), fn, self._abort_check,
                           ck)


def _engine(comm) -> _FusionEngine:
    eng = comm.__dict__.get("_fusion_engine")
    if eng is None:
        eng = comm.__dict__["_fusion_engine"] = _FusionEngine(comm)
    return eng


def _as_arr(x):
    return x if hasattr(x, "dtype") and hasattr(x, "reshape") \
        else np.asarray(x)


def _eligible(comm, kind: str, x, opname, nbytes: int) -> bool:
    """Comm-consistent fusion gate: depends only on comm properties,
    the MCA knobs (process-wide), and dtype/op/nbytes — all of which
    MPI requires to match across members."""
    from ompi_tpu.coll import device
    if not _fusion_var.value or comm.size == 1:
        return False
    if _engine(comm).mode is None:
        return False
    if device._dtype_of(x).fields is not None:
        return False
    if kind == "allreduce" and opname not in device._XLA_REDUCERS \
            and opname not in device._GATHER_FOLD:
        return False
    return 0 < nbytes <= max(0, _threshold_var.value)


def _immediate(comm, value) -> FusedRequest:
    req = FusedRequest(comm.state.progress, None)
    req._deliver(value)
    return req


def iallreduce_arr(comm, x, op: Op) -> FusedRequest:
    """Nonblocking device-array allreduce; small payloads coalesce
    into the comm's pending fusion batch."""
    x = _as_arr(x)
    nbytes = _nbytes_of(x)
    if _eligible(comm, "allreduce", x, op.name, nbytes):
        return _engine(comm).enqueue("allreduce", x, op.name, nbytes)
    return _immediate(comm, comm.coll.allreduce_arr(comm, x, op))


def ibcast_arr(comm, x, root: int = 0) -> FusedRequest:
    """Nonblocking device-array broadcast; small payloads coalesce
    into the comm's pending fusion batch (masked-psum slot of the
    fused call)."""
    x = _as_arr(x)
    nbytes = _nbytes_of(x)
    if _eligible(comm, "bcast", x, None, nbytes):
        return _engine(comm).enqueue("bcast", x, int(root), nbytes)
    return _immediate(comm, comm.coll.bcast_arr(comm, x, root))


def flush_comm(comm) -> None:
    """Run this comm's pending fusion batch now (collective over the
    comm: all members must flush)."""
    eng = comm.__dict__.get("_fusion_engine")
    if eng is not None:
        eng.flush()


def flush_state(state) -> None:
    """Finalize hook: flush every comm's pending batch for this rank
    so no enqueued collective dies with the process (runs before the
    finalize fence — peers are still alive to rendezvous)."""
    first = None
    for comm in list(getattr(state, "comms", {}).values()):
        if comm is None:  # freed comm leaves its cid slot behind
            continue
        try:
            flush_comm(comm)
        except BaseException as e:  # noqa: BLE001
            if first is None:
                first = e
    if first is not None:
        raise first
