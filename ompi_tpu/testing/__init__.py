"""Thread-rank world harness.

Runs N MPI ranks as threads in one process — the TPU-host execution
model (one process drives all local chips; ranks map to devices) and
the fast path for exercising the full stack in tests, mirroring how
the reference tests mapping logic without a cluster via ras/simulator
(ref: orte/mca/ras/simulator/ras_sim_module.c:67-91).
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Callable, List, Optional

from ompi_tpu.runtime.init import mpi_finalize, mpi_init
from ompi_tpu.runtime.rte import InprocWorld
from ompi_tpu.runtime.state import ProcState


class RankError(RuntimeError):
    def __init__(self, rank: int, exc: BaseException, tb: str) -> None:
        super().__init__(f"rank {rank} failed: {exc}\n{tb}")
        self.rank = rank
        self.exc = exc


def run_ranks(n: int, fn: Callable, devices: bool = False,
              timeout: float = 120.0, device_map=None,
              allow_failures: bool = False,
              respawn: bool = False) -> List[Any]:
    """Run fn(comm_world) on n thread-ranks; returns per-rank results.

    devices=True maps rank i to jax.devices()[i % ndev] so coll/tpu
    and coll/hbm become eligible.  device_map overrides: a callable
    rank -> jax device (e.g. lambda r: jax.devices()[0] to co-locate
    every rank on one chip and exercise coll/hbm).

    allow_failures=True treats a rank dying with ulfm.RankKilled as
    the scenario, not an error: its failure is published ULFM-style
    (survivors get ERR_PROC_FAILED and may revoke/agree/shrink), its
    result slot stays None, and only survivor errors raise.

    respawn=True is the thread-world analog of mpirun's respawn
    policy (ft/respawn): a RankKilled death is published like
    allow_failures, then this driver waits for the survivors' rejoin
    decision (respawn.thread_decision) and starts a REPLACEMENT
    thread under the same world rank — fresh ProcState flagged
    respawn_joining at the failure's epoch.  fn runs again on the
    replacement (applications branch on respawn.joining(state) to
    rejoin + restore instead of starting over) and its return value
    fills the rank's result slot.  Kills reaped in the same window are
    replaced in ONE rejoin epoch (the decision's failed set), so
    correlated multi-kill scenarios — a rank plus all its buddy
    partners — exercise a single batched recovery; kills that land
    later degrade to sequential epochs.
    """
    world = InprocWorld(n)
    results: List[Any] = [None] * n
    errors: List[Optional[RankError]] = [None] * n
    devs = None
    if devices or device_map is not None:
        import jax
        devs = jax.devices()
    respawn_cv = threading.Condition()
    respawn_q: List[int] = []  # killed ranks awaiting replacement

    def runner(rank: int, joining_epoch: Optional[int] = None) -> None:
        try:
            rte = world.make_rte(rank)
            state = ProcState(rank, n, rte)
            if joining_epoch is not None:
                # replacement rank: mpi_init must not re-arm the fault
                # that killed the predecessor, and the app must see
                # respawn.joining(state) truthy (threads share the
                # environment, so the TPUMPI_RESPAWN env signal used
                # by process jobs cannot work here)
                state.respawn_joining = True
                state.respawn_epoch = joining_epoch - 1
            world.states[rank] = state
            if device_map is not None:
                dev = device_map(rank)
            else:
                dev = devs[rank % len(devs)] if devs else None
            mpi_init(state, device=dev)

            def _abort_check() -> int:
                if world.aborted and world.aborted[0] != rank:
                    raise RuntimeError(
                        f"peer rank {world.aborted[0]} aborted: "
                        f"{world.aborted[2]}")
                return 0

            state.progress.register(_abort_check, low_priority=True)
            results[rank] = fn(state.comm_world)
            # finalize only on success: its fence would deadlock
            # against peers that died before reaching it
            mpi_finalize(state)
        except BaseException as e:  # noqa: BLE001
            if allow_failures or respawn:
                from ompi_tpu.ft import ulfm as _ulfm
                if isinstance(e, _ulfm.RankKilled):
                    # the injected death IS the test scenario: the
                    # rank is gone, survivors mitigate via ULFM.
                    # Mark the corpse for process-wide accounting
                    # (coll.device last-rank dispatcher drain) —
                    # whatever raised RankKilled, this incarnation
                    # will never run mpi_finalize
                    try:
                        state.ulfm_dead = True
                    except UnboundLocalError:
                        pass
                    _ulfm.publish_world_failure(world, rank)
                    if respawn:
                        with respawn_cv:
                            respawn_q.append(rank)
                            respawn_cv.notify_all()
                    return
            errors[rank] = RankError(rank, e, traceback.format_exc())
            if world.aborted is None:
                world.aborted = (rank, 1, str(e))
            try:
                world.barrier.abort()
            except Exception:
                pass
            for st in world.states:
                if st is not None:
                    st.progress.wakeup()

    def _spawn(rank: int,
               joining_epoch: Optional[int] = None) -> threading.Thread:
        t = threading.Thread(
            target=runner, args=(rank, joining_epoch), daemon=True,
            name=f"mpi-rank-{rank}" if joining_epoch is None
            else f"mpi-rank-{rank}-e{joining_epoch}")
        t.start()
        return t

    live = {r: _spawn(r) for r in range(n)}

    if respawn:
        # supervision loop (the inproc analog of mpirun's respawn
        # branch): reap kills, wait out each epoch's rejoin decision,
        # start the replacements, until every rank thread has finished.
        # Kills that land in the same reap window ride ONE epoch — the
        # rejoin decision is a set, so a correlated multi-kill (a rank
        # plus its buddy partners) is replaced in a single rejoin, the
        # way mpirun batches simultaneous child exits.  The survivors'
        # union can also decide ranks whose kill note has not reached
        # this driver yet; those are remembered in `owed` so the late
        # queue entry does not double-respawn them.
        from ompi_tpu.ft import respawn as _respawn
        deadline = time.monotonic() + timeout
        epoch = 0
        owed: set = set()
        while True:
            alive = any(t.is_alive() for t in live.values())
            with respawn_cv:
                pending, respawn_q[:] = list(respawn_q), []
            batch = [r for r in pending if r not in owed]
            owed.difference_update(pending)
            if batch:
                epoch += 1
                d = _respawn.thread_decision(
                    world, epoch,
                    timeout=max(1.0, deadline - time.monotonic()))
                decided = sorted(int(x) for x in d["failed"])
                owed.update(r for r in decided if r not in batch)
                for rank in decided:
                    live[rank] = _spawn(rank, joining_epoch=epoch)
            if not alive and not batch:
                break
            if world.aborted is not None and not pending:
                # a real error (not a kill): let the join path below
                # surface it instead of spinning to the deadline
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"respawn world did not finish within {timeout}s "
                    f"(epoch {epoch}); errors so far: "
                    f"{[e for e in errors if e]}")
            time.sleep(0.002)

    for t in live.values():
        t.join(timeout)
        if t.is_alive():
            raise TimeoutError(
                f"rank thread {t.name} did not finish within {timeout}s "
                f"(likely deadlock); errors so far: "
                f"{[e for e in errors if e]}")
    # surface the root cause: the rank that aborted first, not the
    # peers that failed reacting to the abort
    if world.aborted is not None and errors[world.aborted[0]] is not None:
        raise errors[world.aborted[0]]
    for e in errors:
        if e is not None:
            raise e
    return results


def mpirun_run(np_, prog, *args, mca=(), extra=(), timeout=120,
               job_timeout=90, cwd=None):
    """Run `prog` under our mpirun as a subprocess and return the
    CompletedProcess — the one shared recipe for integration tests
    (PYTHONPATH for children, JAX pinned to CPU so examples never
    touch the real chip, belt-and-braces timeouts)."""
    import os
    import subprocess
    import sys

    import ompi_tpu as _pkg
    repo = os.path.dirname(os.path.dirname(os.path.abspath(
        _pkg.__file__)))
    cmd = [sys.executable, "-m", "ompi_tpu.tools.mpirun",
           "-np", str(np_)]
    if job_timeout:
        cmd += ["--timeout", str(job_timeout)]
    for k, v in mca:
        cmd += ["--mca", k, v]
    cmd += [*extra, prog if os.path.isabs(prog)
            else os.path.join(repo, prog), *args]
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(cmd, capture_output=True, timeout=timeout,
                          env=env, cwd=cwd or repo)
