"""Live fault recovery: re-route + remap without a full job restart.

Re-design of the reference's resilience pair — routed/radix ft_event
(ref: orte/mca/routed/radix/routed_radix.c:58 — repair the daemon
overlay on daemon loss) and rmaps/resilient
(ref: orte/mca/rmaps/resilient/rmaps_resilient.c:76+ — remap a failed
node's procs onto survivors) — for this framework's control plane.

When a node daemon dies mid-job (policy ``errmgr_base_policy =
recover`` with --ckpt-dir), the HNP does NOT tear the job down:

  1. it relaunches the dead node's ranks on a surviving daemon with a
     bumped RECOVERY EPOCH and TPUMPI_RESTART=1;
  2. it publishes the epoch in the KV store, where every surviving
     rank's watcher thread (started by mpi init) sees it and arms a
     ``JobRecovery`` interrupt on the rank's progress engine;
  3. each survivor's next blocking wait raises JobRecovery out of
     whatever collective it was parked in; the application catches it
     and calls :func:`recover`, which performs an EPOCH RESET — the
     communication stack is rebuilt exactly the way a restarted
     rank's init builds it fresh:

       * epoch-scoped jobid (fence keys) and modex namespace (the KV
         proxies cache write-once modex keys, so changed values get
         NEW names instead of re-puts),
       * transports reset (tcp: new listener + dropped connections,
         so stale pre-epoch bytes die with their sockets; shm
         quiesced — post-recovery cross-process traffic rides tcp,
         whose reset story is complete),
       * pml matching state cleared (both sides restart sequence
         spaces at zero),
       * endpoints re-wired from the fresh modex, per-communicator
         caches dropped;

  4. every rank — restarted and surviving — then loads the latest
     complete snapshot (cr.restore) and resumes.  The cut line is
     the snapshot: survivors roll back with the restarted ranks, the
     global state is consistent, and the job finishes without paying
     a full relaunch (the r4 recovery story) or losing the warm
     processes of the surviving nodes.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional


class JobRecovery(Exception):
    """Raised out of a blocking wait when the HNP published a new
    recovery epoch: the application should call :func:`recover` and
    reload its state from the latest snapshot."""

    def __init__(self, epoch: int, info: dict) -> None:
        super().__init__(f"job recovery epoch {epoch}: "
                         f"failed ranks {info.get('failed')}")
        self.epoch = epoch
        self.info = info


def _epoch_key(epoch: int) -> str:
    return f"ft:epoch:{epoch}"


def start_watcher(state) -> None:
    """Arm the per-rank epoch watcher (called by mpi init when the
    launcher exported TPUMPI_FT_RECOVER).  A dedicated KV connection
    blocks on the next epoch key; on arrival the rank's progress
    engine gets an interrupt, so the next blocking wait raises
    JobRecovery no matter what the rank was doing."""
    from ompi_tpu.runtime.kvstore import KVClient

    addr = os.environ.get("TPUMPI_KV_ADDR")
    if not addr:
        return

    def watch() -> None:
        try:
            kv = KVClient(addr)
        except OSError:
            return
        epoch = getattr(state, "ft_epoch", 0)
        while True:
            try:
                info = kv.get(_epoch_key(epoch + 1), timeout=3600.0)
            except (RuntimeError, OSError):
                if getattr(state, "finalized", False):
                    return
                continue
            epoch += 1
            state.progress.interrupt = JobRecovery(epoch, info)
            state.progress.wakeup()

    t = threading.Thread(target=watch, daemon=True,
                         name=f"ft-watcher-r{state.rank}")
    t.start()
    state._ft_watcher = t


def pending(state) -> Optional[JobRecovery]:
    """The armed-but-not-yet-raised recovery interrupt, if any."""
    exc = state.progress.interrupt
    return exc if isinstance(exc, JobRecovery) else None


def wait_pending(comm, timeout: float = 60.0) -> JobRecovery:
    """Block until the watcher arms a recovery epoch.  Used by
    applications that caught a TRANSPORT error (a dead peer's
    connection can fail a send before the HNP's epoch publication
    lands) and need the epoch before they can recover."""
    import time

    deadline = time.monotonic() + timeout
    while True:
        exc = pending(comm.state)
        if exc is not None:
            return exc
        if time.monotonic() > deadline:
            raise TimeoutError(
                "no recovery epoch announced — the failure was not "
                "a recoverable daemon loss")
        time.sleep(0.01)


def _dbg(state, msg: str) -> None:
    if os.environ.get("FT_DEBUG"):
        import sys
        print(f"[ft r{state.rank}] {msg}", file=sys.stderr, flush=True)


def recover(comm, exc: JobRecovery) -> None:
    """The surviving-rank epoch reset (see module docstring).  After
    this returns, cr.restore(comm) loads the snapshot every rank —
    restarted and surviving — resumes from."""
    state = comm.state
    epoch = exc.epoch
    progress = state.progress
    progress.interrupt = None  # disarm: recovery itself must not raise
    state.ft_epoch = epoch
    rte = state.rte

    # 1. epoch-scoped control-plane namespaces: fences and modex keys
    # match what the restarted ranks' init uses (their launch env
    # carries TPUMPI_FT_EPOCH / the epoch jobid)
    base_jobid = getattr(rte, "jobid_base", None) or rte.jobid
    rte.jobid_base = base_jobid
    rte.jobid = f"{base_jobid}:e{epoch}"
    rte._fence_count = 0
    rte.modex_epoch = epoch

    # 2. transports: tcp rebuilds (new listener, fresh modex addr
    # under the epoch namespace); shm is quiesced — its rings may
    # still hold pre-epoch frames, and a drained stale frame with a
    # reset sequence space would poison matching
    keep = []
    for m in state.btls:
        ft = getattr(m, "ft_reset", None)
        if ft is not None:
            if ft(epoch):
                keep.append(m)
        else:
            keep.append(m)
    state.btls = keep

    # 3. pml: clear matching + sequence state (both ends of every
    # channel restart at zero; the snapshot line has no in-flight
    # traffic by quiesce construction)
    state.pml.ft_reset()
    # the device-rendezvous engine's tables are sequence-space state
    # too: a stale pending entry keyed by a reusable xid would satisfy
    # a post-recovery pull with pre-epoch data (ADVICE r5 #1)
    eng = getattr(state, "_tpu_rndv", None)
    if eng is not None:
        eng.ft_reset()

    # 4. re-publish identity modex under the epoch namespace and meet
    # the restarted ranks at their init fences (sync #1)
    if state.device is not None:
        rte.modex_put("device_id", int(state.device.id))
    rte.modex_put("node_id", getattr(rte, "node_id", 0))
    rte.modex_put("cores", os.cpu_count() or 1)
    if getattr(state, "_seg_modex_done", False):
        # coll/seg eligibility reads every member's (node, session)
        # under the epoch namespace too
        rte.modex_put("seg_session", rte.session_dir)
    _dbg(state, "modex re-published; entering epoch fence 1")
    rte.fence()
    _dbg(state, "epoch fence 1 passed")

    # 5. endpoints from the fresh modex; every communicator's cached
    # transport/eligibility state is stale
    from ompi_tpu.btl import base as btl_base
    endpoints = btl_base.wire_endpoints(state, state.btls)
    state.pml.add_procs(endpoints)
    for c in state.comms.values():
        if c is None:
            continue
        for k in ("_seg_eligible", "_coll_seg", "_seg_ar_plan",
                  "_hbm_one_device", "_hbm_plans", "_device_rv",
                  "_device_abort_check", "_oversub_verdict",
                  "_mesh_none"):
            # _oversub_verdict matters most: placement CHANGED (the
            # remapped ranks oversubscribe a survivor node), and a
            # survivor keeping the pre-failure verdict while the
            # restarted rank computes the new one splits the comm
            # across different collective algorithms — deadlock
            c.__dict__.pop(k, None)

    # 6. init's sync #2, then let cr.restore see the restart flag
    _dbg(state, "endpoints rewired; entering epoch fence 2")
    rte.fence()
    _dbg(state, "recover complete")
    os.environ["TPUMPI_RESTART"] = "1"
