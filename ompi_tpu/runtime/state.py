"""Per-rank process state and the thread-local current() accessor.

The TPU-native execution model (see docs/DESIGN.md): on a TPU host a
single OS process drives every local chip, so MPI ranks are *threads
mapped to devices* inside the host process, and *processes across
hosts*.  Either way each rank owns one ProcState carrying its
identity, progress engine, pml, btl endpoints and communicator table
— the analog of the per-process globals ompi_mpi_init.c sets up.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from .progress import Progress


class ProcState:
    def __init__(self, rank: int, size: int, rte: Any) -> None:
        self.rank = rank
        self.size = size
        self.rte = rte
        self.progress = Progress()
        self.pml: Any = None
        self.btls: list = []
        self.comms: Dict[int, Any] = {}  # cid -> Communicator
        self.comm_world: Any = None
        self.comm_self: Any = None
        self.device: Any = None  # jax device owned by this rank (may be None)
        # span tracer (ompi_tpu/trace); None unless trace_enable —
        # hot paths pay exactly one is-None check when tracing is off
        self.tracer: Any = None
        # ULFM failure-mitigation state (ompi_tpu/ft/ulfm); None when
        # mpi_ft_ulfm is off — same one-is-None-check hot-path contract
        self.ulfm: Any = None
        self.finalized = False
        self.initialized = False
        # self-healing respawn (ompi_tpu/ft/respawn): epoch counts
        # completed in-job rank replacements; joining marks a
        # replacement rank between its re-init and its first rejoin
        self.respawn_epoch = 0
        self.respawn_joining = False
        # DVM serve plane (tools/dvm): cid_band shifts this rank's
        # whole communicator-id space by band*SESSION_CID_STRIDE, so
        # concurrently-resident sessions in one pool process never
        # share a cid (trace spans, pvar labels and rendezvous keys
        # stay unambiguous pool-wide); serve_resident defers
        # ompi_tpu.finalize() to a flush+fence run boundary, keeping
        # the world warm for the session's next program
        self.cid_band = 0
        self.serve_resident = False
        self.extra: Dict[str, Any] = {}

    def next_cid_local(self) -> int:
        """Lower bound for CID agreement: smallest unused local cid."""
        cid = 0
        while cid in self.comms:
            cid += 1
        return cid


_tls = threading.local()
_process_state: Optional[ProcState] = None


def set_current(state: Optional[ProcState], process_wide: bool = False) -> None:
    global _process_state
    if process_wide:
        _process_state = state
    else:
        _tls.state = state


def clear_current(state: ProcState) -> None:
    """Drop `state` from both the thread-local and process-wide
    slots (finalize path): later current() calls must raise the
    clean not-initialized error, not hand out a dead state."""
    global _process_state
    if getattr(_tls, "state", None) is state:
        _tls.state = None
    if _process_state is state:
        _process_state = None


def current() -> ProcState:
    st = getattr(_tls, "state", None)
    if st is None:
        st = _process_state
    if st is None:
        raise RuntimeError(
            "MPI is not initialized in this thread (no ProcState)")
    return st


def maybe_current() -> Optional[ProcState]:
    st = getattr(_tls, "state", None)
    return st if st is not None else _process_state
