"""KV rendezvous store: the PMIx analog.

The launcher hosts one TCP server; ranks connect as clients and use
put / blocking-get (modex business-card exchange) / fence (barrier)
/ abort — the exact contract ompi_mpi_init needs from its runtime
(ref: opal/mca/pmix usage at ompi/runtime/ompi_mpi_init.c:654-661;
the modex OPAL_MODEX_SEND/RECV pattern of btl_tcp_component.c:1128).

Wire format: 4-byte big-endian length + JSON object.  Values are
JSON-serializable (byte payloads go hex-encoded; modex values are
small address blobs, never data-plane traffic).
"""

from __future__ import annotations

import hmac
import json
import os
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ompi_tpu.mca.params import registry

_kv_retry_max_var = registry.register(
    "rte", "base", "kv_retry_max", 3, int,
    help="Retries per KV op after a transient server failure "
         "(reconnect + resend; replies are re-awaited only for "
         "idempotent ops)")
_kv_retry_delay_var = registry.register(
    "rte", "base", "kv_retry_delay", 0.05, float,
    help="Base KV retry backoff (exponential, jittered, capped 2 s)")


def job_secret() -> Optional[str]:
    """The per-job control-plane secret (launcher-generated,
    env-forwarded).  The sec/basic analog (ref:
    opal/mca/sec/basic/sec_basic.c — credentials checked at
    connection acceptance): without it any local process could dial
    the rendezvous server and inject aborts or spawns."""
    return os.environ.get("TPUMPI_JOB_SECRET") or None


_DFS_REMOTE = 1 << 30  # proxy fd-namespace offset for forwarded files


def dfs_parse_uri(uri: str) -> Tuple[str, str]:
    """'file://HOST/abs/path' -> (HOST, /abs/path); a bare path is
    ('', path) — local.  (ref: orte/mca/dfs/dfs.h:50 — the uri names
    the host the file lives on.)"""
    if uri.startswith("file://"):
        rest = uri[len("file://"):]
        host, sep, path = rest.partition("/")
        return host, "/" + path if sep else ""
    return "", uri


def _dfs_serve(op: str, msg: dict, fds: Dict[int, int]) -> dict:
    """Serve one dfs request against THIS host's filesystem (the
    daemon/HNP side of orte/mca/dfs — read-only by design).  ``fds``
    is the per-connection descriptor table; the connection's close
    cleans it up."""
    try:
        if op == "dfs_open":
            _, path = dfs_parse_uri(msg["uri"])
            fd = os.open(path, os.O_RDONLY)
            fds[fd] = fd
            return {"fd": fd, "size": os.fstat(fd).st_size}
        fd = fds.get(int(msg.get("fd", -1)), -1)
        if fd < 0:
            return {"error": "bad dfs fd"}
        if op == "dfs_read":
            data = os.pread(fd, int(msg["len"]), int(msg["offset"]))
            return {"data": data.decode("latin-1")}
        if op == "dfs_size":
            return {"size": os.fstat(fd).st_size}
        if op == "dfs_close":
            fds.pop(fd, None)
            os.close(fd)
            return {"ok": True}
        return {"error": f"unknown dfs op {op}"}
    except OSError as e:
        return {"error": str(e)}


def _require_hello(conn, secret: Optional[str]) -> bool:
    """Server side of the hello frame: when a secret is configured,
    the FIRST message must be an authenticating hello.  Returns True
    when the connection may proceed."""
    if not secret:
        return True
    msg = _recv_msg(conn)
    if msg is None:
        return False
    if msg.get("op") != "hello" or not isinstance(
            msg.get("secret"), str) or not hmac.compare_digest(
            msg["secret"], secret):
        try:
            _send_msg(conn, {"error": "unauthenticated"})
        except OSError:
            pass
        try:
            conn.close()
        except OSError:
            pass
        return False
    _send_msg(conn, {"ok": True})
    return True


def _send_msg(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(data)) + data)


def _recv_msg(sock: socket.socket) -> Optional[dict]:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = struct.unpack(">I", hdr)
    data = b""
    while len(data) < n:
        chunk = sock.recv(n - len(data))
        if not chunk:
            return None
        data += chunk
    return json.loads(data)


class KVServer:
    """Runs inside the launcher (the HNP role)."""

    def __init__(self, nprocs: int, host: str = "127.0.0.1",
                 advertise: Optional[str] = None) -> None:
        """``host`` is the bind address (0.0.0.0 for multi-host jobs);
        ``advertise`` is the address clients are told to dial (the
        HNP's reachable IP when binding wildcard)."""
        self.nprocs = nprocs
        self.secret = job_secret()
        self.data: Dict[str, Any] = {}
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.counters: Dict[str, int] = {}
        self.fences: Dict[str, int] = {}
        self.fence_waiters: Dict[str, List[socket.socket]] = {}
        # per-namespace aborts (the DVM serve plane: many resident
        # sessions share ONE long-lived server, each under a key
        # namespace).  An abort carrying "ns" poisons only that
        # namespace's blocking gets/takes/fences — peer sessions keep
        # running.  The global `aborted` (no ns) still poisons all.
        self.ns_aborted: Dict[str, Tuple[int, int, str]] = {}
        # O(daemons)-vs-O(ranks) scalability diagnostic: connections
        # ever accepted (daemon KV proxies collapse per-rank traffic
        # onto one upstream connection per node)
        self.connections_served = 0
        self.aborted: Optional[Tuple[int, int, str]] = None
        # dpm: the universe rank space grows as jobs are spawned
        # (ref: ompi/dpm over the PMIx server); mpirun drains
        # spawn_requests and launches when spawn_enabled
        self.universe = nprocs
        self.spawn_enabled = False
        self.spawn_requests: List[dict] = []
        # optional event sinks (the job state machine): called OUTSIDE
        # the lock with activations only (queue puts, never blocking)
        self.on_abort = None
        self.on_spawn = None
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, 0))
        self.sock.listen(nprocs * 4)
        self.addr = (f"{advertise or host}:"
                     f"{self.sock.getsockname()[1]}")
        self._threads: List[threading.Thread] = []
        self._stop = False
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            self.connections_served += 1
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if not _require_hello(conn, self.secret):
            return
        dfs_fds: Dict[int, int] = {}
        try:
            while True:
                msg = _recv_msg(conn)
                if msg is None:
                    return
                op = msg.get("op") or ""
                if op == "hello":
                    # secretless server: ack so mixed configs work
                    _send_msg(conn, {"ok": True})
                elif op.startswith("dfs_"):
                    _send_msg(conn, _dfs_serve(op, msg, dfs_fds))
                elif op == "put":
                    with self.cv:
                        self.data[msg["key"]] = msg["value"]
                        self.cv.notify_all()
                    _send_msg(conn, {"ok": True})
                elif op == "get":
                    timeout = msg.get("timeout", 60.0)
                    ns = msg.get("ns")
                    with self.cv:
                        deadline_hit = not self.cv.wait_for(
                            lambda: msg["key"] in self.data
                            or self.aborted is not None
                            or (ns is not None
                                and ns in self.ns_aborted),
                            timeout=timeout)
                        ab = self.aborted if self.aborted is not None \
                            else (self.ns_aborted.get(ns)
                                  if ns is not None else None)
                        if ab is not None:
                            _send_msg(conn, {"abort": list(ab)})
                        elif deadline_hit:
                            _send_msg(conn, {"timeout": True})
                        else:
                            _send_msg(conn, {"value": self.data[msg["key"]]})
                elif op == "incr":
                    # atomic fetch-and-add counter, distinct namespace
                    # from put/get data (dpm cid + rendezvous sequencing)
                    with self.cv:
                        v = self.counters.get(msg["key"], 0)
                        self.counters[msg["key"]] = v + 1
                    _send_msg(conn, {"value": v})
                elif op == "uncr":
                    # compensating decrement: roll a ticket back only
                    # if no later ticket was issued meanwhile (dpm
                    # rendezvous-timeout recovery)
                    with self.cv:
                        cur = self.counters.get(msg["key"], 0)
                        ok = cur == msg["expect"] + 1
                        if ok:
                            self.counters[msg["key"]] = msg["expect"]
                    _send_msg(conn, {"ok": ok})
                elif op == "purge":
                    # prefix delete over data AND counters (including
                    # the put_once claim tickets, which live in the
                    # counter namespace as "claim:<key>"): store
                    # hygiene for ULFM notes/tickets at finalize and
                    # respawn epoch rollover
                    pfx = msg["prefix"]
                    with self.cv:
                        nd = 0
                        for k in [k for k in self.data
                                  if isinstance(k, str)
                                  and k.startswith(pfx)]:
                            del self.data[k]
                            nd += 1
                        for k in [k for k in self.counters
                                  if isinstance(k, str)
                                  and (k.startswith(pfx) or
                                       k.startswith("claim:" + pfx))]:
                            del self.counters[k]
                            nd += 1
                        # a full-namespace purge ("ns/") is session
                        # teardown: clear the poison record too so a
                        # reused server never haunts later lookups
                        if pfx.endswith("/"):
                            self.ns_aborted.pop(pfx[:-1], None)
                        self.cv.notify_all()
                    _send_msg(conn, {"ok": True, "n": nd})
                elif op == "take":
                    # blocking get that atomically deletes the record:
                    # one-shot rendezvous consumption (dpm accept/connect)
                    timeout = msg.get("timeout", 60.0)
                    ns = msg.get("ns")
                    with self.cv:
                        deadline_hit = not self.cv.wait_for(
                            lambda: msg["key"] in self.data
                            or self.aborted is not None
                            or (ns is not None
                                and ns in self.ns_aborted),
                            timeout=timeout)
                        ab = self.aborted if self.aborted is not None \
                            else (self.ns_aborted.get(ns)
                                  if ns is not None else None)
                        if ab is not None:
                            _send_msg(conn, {"abort": list(ab)})
                        elif deadline_hit:
                            _send_msg(conn, {"timeout": True})
                        else:
                            _send_msg(conn,
                                      {"value": self.data.pop(msg["key"])})
                elif op == "fence":
                    # weighted arrival: a daemon KV proxy fences ONCE
                    # on behalf of its node's ranks (weight = local
                    # rank count); the fence completes when the summed
                    # weights reach n (grpcomm aggregation analog,
                    # ref: orte/mca/grpcomm — daemons collect their
                    # local procs' contributions)
                    fid = msg["id"]
                    want = int(msg.get("n", self.nprocs))
                    weight = int(msg.get("weight", 1))
                    ns = msg.get("ns")
                    with self.cv:
                        ab = self.aborted
                        if ab is None and ns is not None:
                            ab = self.ns_aborted.get(ns)
                        if ab is None and self.ns_aborted:
                            # untagged late arrival (e.g. a proxied
                            # fence drops the ns tag): fence ids are
                            # ns-prefixed "ns/<id>" by KVClient, so
                            # recover the scope by prefix
                            for a_ns, rec in self.ns_aborted.items():
                                if fid.startswith(a_ns + "/"):
                                    ab = rec
                                    break
                        if ab is not None:
                            # the abort sweep only releases waiters
                            # already parked; a rank fencing AFTER its
                            # scope was poisoned must fail here — the
                            # aborting rank will never arrive, and
                            # re-registering the fence would park this
                            # client forever (KVClient sockets have no
                            # read timeout)
                            try:
                                _send_msg(conn, {
                                    "error": f"aborted by rank "
                                             f"{ab[0]}: {ab[2]}"})
                            except OSError:
                                pass
                            continue
                        self.fences[fid] = self.fences.get(fid, 0) + weight
                        self.fence_waiters.setdefault(fid, []).append(conn)
                        if self.fences[fid] >= want:
                            for c in self.fence_waiters[fid]:
                                try:
                                    _send_msg(c, {"fence_done": fid})
                                except OSError:
                                    pass
                            del self.fences[fid]
                            del self.fence_waiters[fid]
                            self.cv.notify_all()
                    # reply sent when fence completes (above)
                elif op == "abort":
                    ns = msg.get("ns")
                    rec = (msg["rank"], msg["code"], msg.get("msg", ""))
                    with self.cv:
                        if ns is not None:
                            first = ns not in self.ns_aborted
                            if first:
                                self.ns_aborted[ns] = rec
                            rec = self.ns_aborted[ns]
                        else:
                            first = self.aborted is None
                            if first:
                                self.aborted = rec
                            rec = self.aborted
                        # release fence waiters of the poisoned scope
                        # with an error: the aborting rank never
                        # arrives, so a parked peer must get a
                        # diagnosable failure, not a silent hang.
                        # Fence ids are ns-prefixed ("ns/<id>") by
                        # KVClient, so the scope is a prefix match;
                        # a global abort releases every fence.
                        fpfx = f"{ns}/" if ns is not None else ""
                        for fid in [f for f in self.fences
                                    if f.startswith(fpfx)]:
                            for c in self.fence_waiters.get(fid, []):
                                try:
                                    _send_msg(c, {"error":
                                                  f"aborted by rank "
                                                  f"{rec[0]}: {rec[2]}"})
                                except OSError:
                                    pass
                            self.fences.pop(fid, None)
                            self.fence_waiters.pop(fid, None)
                        self.cv.notify_all()
                    if first and ns is None and self.on_abort is not None:
                        self.on_abort(self.aborted)
                    _send_msg(conn, {"ok": True})
                elif op == "spawn":
                    # allocate a universe-rank block and hand the
                    # launch to mpirun's supervision loop.  segments =
                    # [{cmd, args, n}] — one world spanning every
                    # segment (MPI_Comm_spawn_multiple shape; plain
                    # spawn is one segment)
                    segments = msg.get("segments") or [{
                        "cmd": msg["cmd"], "args": msg.get("args") or [],
                        "n": int(msg["maxprocs"])}]
                    total = sum(int(s["n"]) for s in segments)
                    with self.cv:
                        if not self.spawn_enabled:
                            _send_msg(conn, {
                                "error": "dynamic spawn is not "
                                         "supported by this launcher"})
                            continue
                        base = self.universe
                        self.universe += total
                        self.spawn_requests.append({
                            "base": base,
                            "maxprocs": total,
                            "segments": segments,
                            "parent_root": int(msg["parent_root"]),
                        })
                        self.cv.notify_all()
                    if self.on_spawn is not None:
                        self.on_spawn()
                    _send_msg(conn, {"base": base})
        except OSError:
            return
        finally:
            # a client gone without dfs_close must not leak this
            # long-lived process's descriptors (EMFILE would take
            # down the whole control plane)
            for fd in dfs_fds.values():
                try:
                    os.close(fd)
                except OSError:
                    pass

    def close(self) -> None:
        self._stop = True
        try:
            self.sock.close()
        except OSError:
            pass


class KVClient:
    """One per rank process.  Single socket, single lock: rank
    processes are single-threaded through the rte, and every op is
    strictly request/reply.  A second thread must NOT share this
    client (a blocking fence would starve it on the lock).

    Transient-fault tolerance: ops ride ``_request``, which
    reconnects and retries with backoff against a restarted or
    partitioned server.  A failed SEND is always retryable (the
    server discards a partial frame on its read error); a lost REPLY
    is retried only for idempotent ops — resending an ``incr`` or a
    ``fence`` the server already applied would corrupt the job.

    ``ns`` scopes every key under "ns/" (put_once claim tickets under
    "claim:ns/", so the server's purge hygiene still sweeps them) and
    tags blocking ops so a namespace-scoped abort poisons only this
    client's session — the isolation contract of the DVM serve plane,
    where many resident sessions share one long-lived server.  The
    per-node KVProxy does not forward the ns abort tag; DVM sessions
    dial the shared server directly on loopback, never a proxy."""

    def __init__(self, addr: str, ns: Optional[str] = None) -> None:
        host, port = addr.rsplit(":", 1)
        self.addr = (host, int(port))
        self.ns = ns or None
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = self._connect()
        from ompi_tpu import ft_inject
        self._inj = ft_inject.kv_injector(
            int(os.environ.get("TPUMPI_RANK", "0")))

    def _connect(self) -> socket.socket:
        s = socket.create_connection(self.addr, timeout=60)
        # connect timeout only: blocking ops (fence with rank skew,
        # modex gets) must not inherit a 60s socket timeout — hang
        # protection is the server-side get timeout + mpirun --timeout
        s.settimeout(None)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        secret = job_secret()
        if secret:
            _send_msg(s, {"op": "hello", "secret": secret})
            resp = _recv_msg(s)
            if not resp or not resp.get("ok"):
                s.close()
                raise PermissionError(
                    "kv server refused the job secret "
                    "(TPUMPI_JOB_SECRET mismatch)")
        return s

    def _drop_sock(self) -> None:
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = None

    def _request(self, msg: dict, idempotent: bool = False) -> dict:
        """One request/reply with reconnect + jittered-backoff retry
        (see class docstring for the idempotency contract).
        PermissionError (an OSError subclass!) is never retried — a
        refused job secret will not improve with patience."""
        import random
        tries = 1 + max(0, _kv_retry_max_var.value)
        delay = max(0.005, _kv_retry_delay_var.value)
        last: Optional[Exception] = None
        for attempt in range(tries):
            if attempt:
                time.sleep(min(2.0, delay * (2 ** (attempt - 1)))
                           * (0.5 + random.random()))
            with self._lock:
                if self._inj is not None and self._inj.sever():
                    # injected partition: close the socket under our
                    # own feet and let the machinery below recover
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                try:
                    if self._sock is None:
                        self._sock = self._connect()
                    _send_msg(self._sock, msg)
                except PermissionError:
                    raise
                except OSError as e:
                    last = e
                    self._drop_sock()
                    continue
                try:
                    resp = _recv_msg(self._sock)
                except OSError:
                    resp = None
                if resp is None:
                    self._drop_sock()
                    if idempotent:
                        last = ConnectionError(
                            "kv server closed mid-reply")
                        continue
                    raise ConnectionError("kv server closed")
                return resp
        if isinstance(last, Exception):
            raise ConnectionError(
                f"kv server unreachable after {tries} attempts: "
                f"{last}") from last
        raise ConnectionError("kv server unreachable")

    def _k(self, key: str) -> str:
        """Apply the namespace prefix.  Claim tickets keep their
        "claim:" marker OUTSIDE the namespace ("claim:ns/rest") so the
        server's purge branch — which matches counters against both
        ``pfx`` and ``"claim:" + pfx`` — sweeps a namespaced prefix's
        tickets exactly like an un-namespaced one's."""
        if self.ns is None:
            return key
        if key.startswith("claim:"):
            return "claim:" + self.ns + "/" + key[len("claim:"):]
        return f"{self.ns}/{key}"

    def _ns_tag(self, msg: dict) -> dict:
        if self.ns is not None:
            msg["ns"] = self.ns
        return msg

    def put(self, key: str, value: Any) -> None:
        self._request({"op": "put", "key": self._k(key),
                       "value": value}, idempotent=True)

    def get(self, key: str, timeout: float = 60.0) -> Any:
        resp = self._request(self._ns_tag(
            {"op": "get", "key": self._k(key),
             "timeout": timeout}), idempotent=True)
        if "abort" in resp:
            raise RuntimeError(f"job aborted: {resp['abort']}")
        if resp.get("timeout"):
            raise TimeoutError(f"kv get({key}) timed out")
        return resp["value"]

    def incr(self, key: str) -> int:
        """Atomic fetch-and-add on a server-side counter (returns the
        pre-increment value)."""
        resp = self._request({"op": "incr", "key": self._k(key)})
        return int(resp["value"])

    def put_once(self, key: str, value: Any) -> bool:
        """First-writer-wins publish: claims ``key`` through an
        incr-ticket (pre-increment 0 == first claimant) and only the
        winner stores the value.  Losers return False and must
        ``get`` the winner's value.  Gives the ULFM agreement/shrink
        protocols a decide-once primitive without a server-side CAS
        op."""
        if self.incr("claim:" + key) == 0:
            self.put(key, value)
            return True
        return False

    def purge(self, prefix: str) -> int:
        """Delete every data key and counter (including put_once claim
        tickets) under ``prefix``; returns the number removed.
        Idempotent by construction — deleting twice deletes nothing."""
        resp = self._request({"op": "purge", "prefix": self._k(prefix)},
                             idempotent=True)
        return int(resp.get("n", 0))

    def uncr(self, key: str, expect: int) -> bool:
        """Roll back a ticket taken with incr() (which returned
        ``expect``) — succeeds only if no later ticket was issued."""
        resp = self._request({"op": "uncr", "key": self._k(key),
                              "expect": expect})
        return bool(resp["ok"])

    def take(self, key: str, timeout: float = 60.0) -> Any:
        """Blocking get that atomically removes the record — one-shot
        rendezvous consumption."""
        resp = self._request(self._ns_tag(
            {"op": "take", "key": self._k(key), "timeout": timeout}))
        if "abort" in resp:
            raise RuntimeError(f"job aborted: {resp['abort']}")
        if resp.get("timeout"):
            raise TimeoutError(f"kv take({key}) timed out")
        return resp["value"]

    def fence(self, fence_id: str, n: Optional[int] = None,
              weight: int = 1) -> None:
        msg: Dict[str, Any] = self._ns_tag(
            {"op": "fence", "id": self._k(fence_id)})
        if n is not None:
            msg["n"] = n
        if weight != 1:
            msg["weight"] = weight
        try:
            resp = self._request(msg)
        except ConnectionError as e:
            raise RuntimeError(f"fence {fence_id} failed: {e}") from e
        if "fence_done" not in resp:
            raise RuntimeError(f"fence {fence_id} failed: {resp}")

    def spawn(self, cmd: str, args: List[str], maxprocs: int,
              parent_root: int) -> int:
        """Ask the launcher for `maxprocs` new universe ranks running
        `cmd`; returns the allocated rank base."""
        return self.spawn_multiple(
            [{"cmd": cmd, "args": args, "n": maxprocs}], parent_root)

    def spawn_multiple(self, segments: List[dict],
                       parent_root: int) -> int:
        """Spawn one world made of several (cmd, args, n) segments
        (MPI_Comm_spawn_multiple)."""
        resp = self._request({"op": "spawn", "segments": segments,
                              "parent_root": parent_root})
        if "error" in resp:
            raise RuntimeError(f"MPI_Comm_spawn: {resp['error']}")
        return int(resp["base"])

    def abort(self, rank: int, code: int, msg: str = "") -> None:
        # best-effort by design: the job is going down anyway, and an
        # unreachable server must not mask the original error
        try:
            self._request(self._ns_tag(
                {"op": "abort", "rank": rank,
                 "code": code, "msg": msg}), idempotent=True)
        except (ConnectionError, OSError, RuntimeError):
            pass

    # -- dfs (orte/mca/dfs/app analog: remote read-only file access) ----
    def _dfs_req(self, msg: dict) -> dict:
        resp = self._request(msg)
        if "error" in resp:
            raise OSError(f"dfs: {resp['error']}")
        return resp

    def dfs_open(self, uri: str) -> Tuple[int, int]:
        resp = self._dfs_req({"op": "dfs_open", "uri": uri})
        return int(resp["fd"]), int(resp["size"])

    def dfs_read(self, fd: int, offset: int, n: int) -> bytes:
        resp = self._dfs_req({"op": "dfs_read", "fd": fd,
                              "offset": offset, "len": n})
        return resp["data"].encode("latin-1")

    def dfs_size(self, fd: int) -> int:
        return int(self._dfs_req({"op": "dfs_size",
                                  "fd": fd})["size"])

    def dfs_close(self, fd: int) -> None:
        self._dfs_req({"op": "dfs_close", "fd": fd})

    def close(self) -> None:
        with self._lock:
            self._drop_sock()


class KVProxy:
    """Per-node KV aggregation daemon — the grpcomm/routed analog.

    Runs inside tpud.  Local ranks speak the ordinary KV wire protocol
    to this proxy on loopback; the proxy maintains ONE upstream
    connection to the HNP's KVServer, so the central server sees
    O(daemons) connections instead of O(ranks) (ref:
    orte/mca/grpcomm/brucks — daemons aggregate their local procs'
    collective contributions; orte/mca/routed — control traffic rides
    the daemon overlay, not per-proc sockets).

    Aggregation:
      * fence  — collect ``local_expected`` arrivals, then ONE
        weighted upstream arrival (weight = local rank count); the
        server completes when summed weights reach n;
      * get    — write-once ``modex:`` keys are cached after the
        first fetch, so N local readers cost one upstream read;
        blocking upstream gets poll with short timeouts so one
        waiting rank never serializes the node's other traffic;
      * everything else (put/incr/uncr/take/abort/spawn) forwards.
    """

    def __init__(self, upstream_addr: str, local_expected: int) -> None:
        self.local_expected = max(1, local_expected)
        self.secret = job_secret()
        self.up = KVClient(upstream_addr)
        # dedicated fence channel, reused across fences (a pending
        # fence must never block ops; fences of one job are
        # sequential, so one channel suffices per node)
        self._up_fence: Optional[KVClient] = None
        self._fence_lock = threading.Lock()
        self._cache: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # fid -> [arrivals, result ('done'|'error'), waiter sockets]
        self._fences: Dict[str, list] = {}
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(64)
        self.addr = f"127.0.0.1:{self.sock.getsockname()[1]}"
        self._stop = False
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _poll_upstream(self, op: str, key: str, timeout: float):
        """Blocking get/take forwarded as short polls so the shared
        upstream channel is never held across a long wait."""
        deadline = time.monotonic() + timeout
        while True:
            left = deadline - time.monotonic()
            step = min(0.2, max(0.01, left))
            try:
                if op == "get":
                    return {"value": self.up.get(key, timeout=step)}
                return {"value": self.up.take(key, timeout=step)}
            except TimeoutError:
                if time.monotonic() >= deadline:
                    return {"timeout": True}
            except RuntimeError as e:  # job abort rides the reply
                return {"abort": str(e)}

    def _dfs_upstream(self, msg: dict) -> dict:
        with self.up._lock:
            _send_msg(self.up._sock, msg)
            resp = _recv_msg(self.up._sock)
        return resp or {"error": "upstream gone"}

    def _serve(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if not _require_hello(conn, self.secret):
            return
        dfs_fds: Dict[int, int] = {}
        dfs_owner: Dict[int, str] = {}  # forwarded fd -> remote host
        try:
            while True:
                msg = _recv_msg(conn)
                if msg is None:
                    return
                op = msg.get("op") or ""
                if op == "hello":
                    _send_msg(conn, {"ok": True})
                elif op.startswith("dfs_"):
                    # client-visible REMOTE fds are offset by _DFS_REMOTE
                    # so they live in a namespace disjoint from this
                    # node's os fds (a collision would silently route
                    # local reads to the wrong remote file)
                    fd_in = int(msg.get("fd", -1))
                    if op == "dfs_open":
                        host = dfs_parse_uri(msg.get("uri", ""))[0]
                        local = host in (
                            "", "localhost",
                            os.environ.get("TPUMPI_NODE_NAME", ""))
                        if local:
                            _send_msg(conn,
                                      _dfs_serve(op, msg, dfs_fds))
                        else:
                            resp = self._dfs_upstream(msg)
                            if "fd" in resp:
                                up = int(resp["fd"])
                                dfs_owner[_DFS_REMOTE + up] = up
                                resp["fd"] = _DFS_REMOTE + up
                            _send_msg(conn, resp)
                    elif fd_in in dfs_owner:
                        fwd = dict(msg)
                        fwd["fd"] = dfs_owner[fd_in]
                        resp = self._dfs_upstream(fwd)
                        if op == "dfs_close":
                            dfs_owner.pop(fd_in, None)
                        _send_msg(conn, resp)
                    else:
                        _send_msg(conn, _dfs_serve(op, msg, dfs_fds))
                elif op == "put":
                    self.up.put(msg["key"], msg["value"])
                    _send_msg(conn, {"ok": True})
                elif op == "get":
                    key = msg["key"]
                    with self._lock:
                        hit = self._cache.get(key)
                    if hit is not None:
                        _send_msg(conn, {"value": hit})
                        continue
                    resp = self._poll_upstream(
                        "get", key, msg.get("timeout", 60.0))
                    if "value" in resp and key.startswith("modex:"):
                        # modex keys are write-once per rank: safe to
                        # serve every later local reader from cache
                        with self._lock:
                            self._cache[key] = resp["value"]
                    _send_msg(conn, resp)
                elif op == "take":
                    _send_msg(conn, self._poll_upstream(
                        "take", msg["key"], msg.get("timeout", 60.0)))
                elif op == "incr":
                    _send_msg(conn, {"value": self.up.incr(msg["key"])})
                elif op == "uncr":
                    _send_msg(conn, {"ok": self.up.uncr(
                        msg["key"], msg["expect"])})
                elif op == "purge":
                    pfx = msg["prefix"]
                    with self._lock:
                        for k in [k for k in self._cache
                                  if k.startswith(pfx)]:
                            del self._cache[k]
                    _send_msg(conn,
                              {"ok": True,
                               "n": self.up.purge(pfx)})
                elif op == "abort":
                    try:
                        self.up.abort(msg["rank"], msg["code"],
                                      msg.get("msg", ""))
                    except (RuntimeError, OSError):
                        pass
                    _send_msg(conn, {"ok": True})
                elif op == "fence":
                    self._fence(conn, msg)
                elif op == "spawn":
                    with self.up._lock:
                        _send_msg(self.up._sock, msg)
                        resp = _recv_msg(self.up._sock)
                    _send_msg(conn, resp or {"error": "upstream gone"})
        except OSError:
            return
        finally:
            for fd in dfs_fds.values():
                try:
                    os.close(fd)
                except OSError:
                    pass
            for cfd, up in dfs_owner.items():
                try:
                    self._dfs_upstream({"op": "dfs_close", "fd": up})
                except Exception:
                    pass

    def _fence(self, conn: socket.socket, msg: dict) -> None:
        fid = msg["id"]
        release = None
        with self._cv:
            ent = self._fences.setdefault(fid, [0, None, []])
            ent[0] += 1
            ent[2].append(conn)
            if ent[0] == self.local_expected:
                release = ent
        if release is None:
            return  # reply comes when the node's last rank arrives
        # last local arrival: ONE weighted upstream fence on the
        # dedicated fence channel
        try:
            with self._fence_lock:
                if self._up_fence is None:
                    self._up_fence = KVClient(
                        f"{self.up.addr[0]}:{self.up.addr[1]}")
                self._up_fence.fence(fid, n=msg.get("n"),
                                     weight=self.local_expected)
            reply = {"fence_done": fid}
        except (RuntimeError, OSError) as e:
            reply = {"error": f"fence failed: {e}"}
        with self._cv:
            ent = self._fences.pop(fid)
        for c in ent[2]:
            try:
                _send_msg(c, reply)
            except OSError:
                pass

    def close(self) -> None:
        self._stop = True
        try:
            self.sock.close()
        except OSError:
            pass
        try:
            self.up.close()
        except OSError:
            pass
        if self._up_fence is not None:
            try:
                self._up_fence.close()
            except OSError:
                pass
