"""KV rendezvous store: the PMIx analog.

The launcher hosts one TCP server; ranks connect as clients and use
put / blocking-get (modex business-card exchange) / fence (barrier)
/ abort — the exact contract ompi_mpi_init needs from its runtime
(ref: opal/mca/pmix usage at ompi/runtime/ompi_mpi_init.c:654-661;
the modex OPAL_MODEX_SEND/RECV pattern of btl_tcp_component.c:1128).

Wire format: 4-byte big-endian length + JSON object.  Values are
JSON-serializable (byte payloads go hex-encoded; modex values are
small address blobs, never data-plane traffic).
"""

from __future__ import annotations

import hmac
import json
import os
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ompi_tpu.mca.params import registry

_kv_retry_max_var = registry.register(
    "rte", "base", "kv_retry_max", 3, int,
    help="Retries per KV op after a transient server failure "
         "(reconnect + resend; replies are re-awaited only for "
         "idempotent ops)")
_kv_retry_delay_var = registry.register(
    "rte", "base", "kv_retry_delay", 0.05, float,
    help="Base KV retry backoff (exponential, jittered, capped 2 s)")
_kv_replicas_var = registry.register(
    "rte", "base", "kv_replicas", 0, int,
    help="Hot-standby replicas behind the KV server (0 = single "
         "server, the default and the fast path; 1 = one in-process "
         "standby fed by streaming op replication, advertised through "
         "the kv2: multi-endpoint uri so clients fail over when the "
         "primary dies)")
_kv_standby_host_var = registry.register(
    "rte", "base", "kv_standby_host", -1, int,
    help="Failure-domain (host) id the hot standby is placed on.  -1 "
         "= auto: anti-affinity with the primary when the fleet has "
         "more than one host, else co-resident (the PR-15 in-process "
         "placement).  Explicit ids pin the standby for chaos runs — "
         "a standby sharing the primary's host dies WITH it on a "
         "host kill, wedging every client's endpoint rotation")

# monotonic per-process client ids: fence arrivals are cid-tagged so a
# re-sent arrival (lost reply, or failover to the promoted standby)
# re-registers the waiter without double-counting its weight
_cid_lock = threading.Lock()
_cid_next = [0]


def _next_cid() -> str:
    with _cid_lock:
        _cid_next[0] += 1
        return f"{os.getpid()}.{_cid_next[0]}"


_pv_kv = None  # lazy (retries, reconnects, failovers) scoped pvars


def _kv_pvars():
    """Client-side resilience counters, band-scoped so DVM sessions
    (ns 's<sid>') get per-session attribution.  Lazy: obs pulls in
    the MPI state module, which this leaf must not import eagerly."""
    global _pv_kv
    if _pv_kv is None:
        from ompi_tpu import obs as _obs
        _pv_kv = (
            _obs.scoped_pvar(
                "kv", "", "retries",
                help="KV ops re-sent after a transient failure"),
            _obs.scoped_pvar(
                "kv", "", "reconnects",
                help="KV client sockets re-established after a drop"),
            _obs.scoped_pvar(
                "kv", "", "failovers",
                help="KV client endpoint rotations onto a standby "
                     "after the current endpoint refused a connect"),
        )
    return _pv_kv


def job_secret() -> Optional[str]:
    """The per-job control-plane secret (launcher-generated,
    env-forwarded).  The sec/basic analog (ref:
    opal/mca/sec/basic/sec_basic.c — credentials checked at
    connection acceptance): without it any local process could dial
    the rendezvous server and inject aborts or spawns."""
    return os.environ.get("TPUMPI_JOB_SECRET") or None


_DFS_REMOTE = 1 << 30  # proxy fd-namespace offset for forwarded files


def dfs_parse_uri(uri: str) -> Tuple[str, str]:
    """'file://HOST/abs/path' -> (HOST, /abs/path); a bare path is
    ('', path) — local.  (ref: orte/mca/dfs/dfs.h:50 — the uri names
    the host the file lives on.)"""
    if uri.startswith("file://"):
        rest = uri[len("file://"):]
        host, sep, path = rest.partition("/")
        return host, "/" + path if sep else ""
    return "", uri


def _dfs_serve(op: str, msg: dict, fds: Dict[int, int]) -> dict:
    """Serve one dfs request against THIS host's filesystem (the
    daemon/HNP side of orte/mca/dfs — read-only by design).  ``fds``
    is the per-connection descriptor table; the connection's close
    cleans it up."""
    try:
        if op == "dfs_open":
            _, path = dfs_parse_uri(msg["uri"])
            fd = os.open(path, os.O_RDONLY)
            fds[fd] = fd
            return {"fd": fd, "size": os.fstat(fd).st_size}
        fd = fds.get(int(msg.get("fd", -1)), -1)
        if fd < 0:
            return {"error": "bad dfs fd"}
        if op == "dfs_read":
            data = os.pread(fd, int(msg["len"]), int(msg["offset"]))
            return {"data": data.decode("latin-1")}
        if op == "dfs_size":
            return {"size": os.fstat(fd).st_size}
        if op == "dfs_close":
            fds.pop(fd, None)
            os.close(fd)
            return {"ok": True}
        return {"error": f"unknown dfs op {op}"}
    except OSError as e:
        return {"error": str(e)}


def _require_hello(conn, secret: Optional[str]) -> bool:
    """Server side of the hello frame: when a secret is configured,
    the FIRST message must be an authenticating hello.  Returns True
    when the connection may proceed."""
    if not secret:
        return True
    msg = _recv_msg(conn)
    if msg is None:
        return False
    if msg.get("op") != "hello" or not isinstance(
            msg.get("secret"), str) or not hmac.compare_digest(
            msg["secret"], secret):
        try:
            _send_msg(conn, {"error": "unauthenticated"})
        except OSError:
            pass
        try:
            conn.close()
        except OSError:
            pass
        return False
    _send_msg(conn, {"ok": True})
    return True


def _send_msg(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(data)) + data)


def _recv_msg(sock: socket.socket) -> Optional[dict]:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = struct.unpack(">I", hdr)
    data = b""
    while len(data) < n:
        chunk = sock.recv(n - len(data))
        if not chunk:
            return None
        data += chunk
    return json.loads(data)


class KVServer:
    """Runs inside the launcher (the HNP role)."""

    def __init__(self, nprocs: int, host: str = "127.0.0.1",
                 advertise: Optional[str] = None,
                 replicas: Optional[int] = None,
                 host_id: int = 0,
                 standby_host: Optional[int] = None) -> None:
        """``host`` is the bind address (0.0.0.0 for multi-host jobs);
        ``advertise`` is the address clients are told to dial (the
        HNP's reachable IP when binding wildcard).  ``replicas``
        overrides the rte_base_kv_replicas knob (the standby itself is
        built with replicas=0 so the chain is exactly one deep).
        ``host_id`` homes this server on a fleet failure domain;
        ``standby_host`` places the standby (default: anti-affine per
        rte_base_kv_standby_host — a standby that shares the
        primary's host dies with it on a host kill)."""
        self.nprocs = nprocs
        self.host_id = host_id
        self.secret = job_secret()
        self.data: Dict[str, Any] = {}
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.counters: Dict[str, int] = {}
        self.fences: Dict[str, int] = {}
        self.fence_waiters: Dict[str, List[socket.socket]] = {}
        # fid -> {cid: weight}: which clients already arrived, so a
        # re-sent arrival (lost reply / failover onto the standby)
        # never double-counts its weight
        self.fence_cids: Dict[str, Dict[str, int]] = {}
        # completed-fence memory (bounded): a client whose fence_done
        # reply was lost retries and must get fence_done again, not a
        # fresh one-member fence that parks it forever
        self.fence_done: Dict[str, bool] = {}
        self._fence_done_order: List[str] = []
        # per-namespace aborts (the DVM serve plane: many resident
        # sessions share ONE long-lived server, each under a key
        # namespace).  An abort carrying "ns" poisons only that
        # namespace's blocking gets/takes/fences — peer sessions keep
        # running.  The global `aborted` (no ns) still poisons all.
        self.ns_aborted: Dict[str, Tuple[int, int, str]] = {}
        # O(daemons)-vs-O(ranks) scalability diagnostic: connections
        # ever accepted (daemon KV proxies collapse per-rank traffic
        # onto one upstream connection per node)
        self.connections_served = 0
        self.aborted: Optional[Tuple[int, int, str]] = None
        # dpm: the universe rank space grows as jobs are spawned
        # (ref: ompi/dpm over the PMIx server); mpirun drains
        # spawn_requests and launches when spawn_enabled
        self.universe = nprocs
        self.spawn_enabled = False
        self.spawn_requests: List[dict] = []
        # optional event sinks (the job state machine): called OUTSIDE
        # the lock with activations only (queue puts, never blocking)
        self.on_abort = None
        self.on_spawn = None
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, 0))
        self.sock.listen(nprocs * 4)
        self.addr = (f"{advertise or host}:"
                     f"{self.sock.getsockname()[1]}")
        self._threads: List[threading.Thread] = []
        self._conns: set = set()  # accepted sockets, for crash()
        self._stop = False
        # replication: the standby is a second KVServer fed a stream
        # of normalized mutation records over one socket, applied in
        # arrival order.  Replicate-before-reply: the record is in the
        # standby's TCP receive buffer before the client sees its ack,
        # so a promoted standby can only be MISSING ops the client
        # never saw acknowledged (and will therefore retry).
        self.standby: Optional["KVServer"] = None
        self._repl: Optional[socket.socket] = None
        self.repl_degraded = False
        want_repl = _kv_replicas_var.value if replicas is None \
            else replicas
        if want_repl > 0:
            sb_host = _kv_standby_host_var.value
            if sb_host < 0:  # auto placement
                sb_host = host_id if standby_host is None \
                    else standby_host
            self.standby = KVServer(nprocs, host=host,
                                    advertise=advertise, replicas=0,
                                    host_id=sb_host)
            peer = ("127.0.0.1" if host in ("127.0.0.1", "0.0.0.0")
                    else host, self.standby.sock.getsockname()[1])
            self._repl = socket.create_connection(peer, timeout=10)
            self._repl.setsockopt(socket.IPPROTO_TCP,
                                  socket.TCP_NODELAY, 1)
            if self.secret:
                _send_msg(self._repl, {"op": "hello",
                                       "secret": self.secret})
                if not (_recv_msg(self._repl) or {}).get("ok"):
                    raise ConnectionError("standby refused hello")
            _send_msg(self._repl, {"op": "repl_stream"})
        # chaos: kv_kill arms a deterministic op-count trigger that
        # hard-crashes THIS server (the primary) mid-traffic
        from ompi_tpu import ft_inject
        self._kill = ft_inject.kv_kill_injector() if replicas != 0 \
            else None
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    @property
    def uri(self) -> str:
        """The address doc clients dial: the plain 'host:port' when
        unreplicated, else the versioned multi-endpoint form
        'kv2:<primary>,<standby>' (KVClient rotates through it)."""
        if self.standby is not None:
            return f"kv2:{self.addr},{self.standby.addr}"
        return self.addr

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            if self._stop:
                # crash()/close() raced our in-flight accept(): the
                # kernel kept the listener alive through the syscall
                # and handed us one more connection — a dead server
                # must not serve it
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
                return
            self.connections_served += 1
            self._conns.add(conn)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _replicate(self, rec: dict) -> None:
        """Stream one mutation record to the standby.  Called UNDER
        self.cv so records hit the wire in apply order.  A dead
        standby degrades the server to single mode permanently (no
        failback: the standby's state is stale the moment the stream
        breaks)."""
        if self._repl is None:
            return
        try:
            _send_msg(self._repl, rec)
        except OSError:
            self.repl_degraded = True
            try:
                self._repl.close()
            except OSError:
                pass
            self._repl = None

    def _apply_repl(self, conn: socket.socket) -> None:
        """Standby side of the stream: apply records until EOF.  No
        per-record replies — the ack domain is TCP delivery, and the
        primary never waits on us."""
        while True:
            rec = _recv_msg(conn)
            if rec is None:
                return
            op = rec.get("op")
            with self.cv:
                if op == "put":
                    self.data[rec["key"]] = rec["value"]
                elif op == "ctr":
                    self.counters[rec["key"]] = rec["value"]
                elif op == "del":
                    self.data.pop(rec["key"], None)
                elif op == "purge":
                    self._purge_locked(rec["prefix"])
                elif op == "fence":
                    self._fence_arrive_locked(rec, None)
                elif op == "abort":
                    self._abort_locked(rec)
                elif op == "spawn_state":
                    self.universe = rec["universe"]
                    self.spawn_requests.append(rec["req"])
                self.cv.notify_all()

    def _purge_locked(self, pfx: str) -> int:
        nd = 0
        for k in [k for k in self.data
                  if isinstance(k, str) and k.startswith(pfx)]:
            del self.data[k]
            nd += 1
        for k in [k for k in self.counters
                  if isinstance(k, str)
                  and (k.startswith(pfx) or
                       k.startswith("claim:" + pfx))]:
            del self.counters[k]
            nd += 1
        # a full-namespace purge ("ns/") is session teardown: clear
        # the poison record and the completed-fence memory too so a
        # reused server never haunts later lookups
        if pfx.endswith("/"):
            self.ns_aborted.pop(pfx[:-1], None)
        for f in [f for f in self.fence_done if f.startswith(pfx)]:
            del self.fence_done[f]
        return nd

    def _fence_done_add_locked(self, fid: str) -> None:
        if fid not in self.fence_done:
            self.fence_done[fid] = True
            self._fence_done_order.append(fid)
            while len(self._fence_done_order) > 4096:
                self.fence_done.pop(self._fence_done_order.pop(0),
                                    None)

    def fence_snapshot(self, prefix: str = "") -> dict:
        """Doctor-facing capture of in-flight (incomplete) fences
        (DESIGN.md §23): fence id -> accumulated arrival weight,
        parked waiter count, and the per-client arrival map, filtered
        by id prefix (fence ids are ns-prefixed, so a session scope is
        a prefix).  A fence that appears here during a stall names
        exactly who has NOT arrived — the hang doctor's fence-side
        verdict.  Cold path; takes the store lock."""
        with self.cv:
            out: Dict[str, dict] = {}
            for fid, have in self.fences.items():
                if prefix and not fid.startswith(prefix):
                    continue
                out[fid] = {
                    "arrived_weight": have,
                    "waiters": len(self.fence_waiters.get(fid, ())),
                    "arrivals": dict(self.fence_cids.get(fid, {})),
                }
            return out

    def _fence_arrive_locked(self, msg: dict,
                             conn: Optional[socket.socket]
                             ) -> Optional[dict]:
        """Register one (possibly re-sent) fence arrival.  Returns an
        immediate reply dict for ``conn`` (error, or fence_done from
        the completed-fence memory), or None when the arrival parked
        or completed — completion broadcasts fence_done to every
        registered waiter, including ``conn``.  Replicated arrivals
        pass conn=None: the standby accumulates weights without
        waiter sockets, and reconstructs the waiter side from the
        clients' own re-sent arrivals after failover."""
        fid = msg["id"]
        if fid in self.fence_done:
            return {"fence_done": fid} if conn is not None else None
        want = int(msg.get("n") or self.nprocs)
        ns = msg.get("ns")
        ab = self.aborted
        if ab is None and ns is not None:
            ab = self.ns_aborted.get(ns)
        if ab is None and self.ns_aborted:
            # untagged late arrival (e.g. a proxied fence drops the
            # ns tag): fence ids are ns-prefixed "ns/<id>" by
            # KVClient, so recover the scope by prefix
            for a_ns, rec in self.ns_aborted.items():
                if fid.startswith(a_ns + "/"):
                    ab = rec
                    break
        if ab is not None:
            # the abort sweep only releases waiters already parked; a
            # rank fencing AFTER its scope was poisoned must fail here
            # — the aborting rank will never arrive, and re-registering
            # the fence would park this client forever
            if conn is not None:
                return {"error": f"aborted by rank {ab[0]}: {ab[2]}"}
            return None
        cids = self.fence_cids.setdefault(fid, {})
        cid = msg.get("cid")
        if cid is None:  # legacy arrival: never dedups
            cid = f"anon.{len(cids)}"
        if cid not in cids:
            cids[cid] = int(msg.get("weight", 1))
            self.fences[fid] = self.fences.get(fid, 0) + cids[cid]
        if conn is not None:
            ws = self.fence_waiters.setdefault(fid, [])
            if conn not in ws:
                ws.append(conn)
        if self.fences.get(fid, 0) >= want:
            for c in self.fence_waiters.get(fid, []):
                try:
                    _send_msg(c, {"fence_done": fid})
                except OSError:
                    pass
            self.fences.pop(fid, None)
            self.fence_waiters.pop(fid, None)
            self.fence_cids.pop(fid, None)
            self._fence_done_add_locked(fid)
            self.cv.notify_all()
        return None

    def _abort_locked(self, msg: dict) -> Tuple[bool, tuple]:
        ns = msg.get("ns")
        rec = (msg["rank"], msg["code"], msg.get("msg", ""))
        if ns is not None:
            first = ns not in self.ns_aborted
            if first:
                self.ns_aborted[ns] = rec
            rec = self.ns_aborted[ns]
        else:
            first = self.aborted is None
            if first:
                self.aborted = rec
            rec = self.aborted
        # release fence waiters of the poisoned scope with an error:
        # the aborting rank never arrives, so a parked peer must get
        # a diagnosable failure, not a silent hang.  Fence ids are
        # ns-prefixed ("ns/<id>") by KVClient, so the scope is a
        # prefix match; a global abort releases every fence.
        fpfx = f"{ns}/" if ns is not None else ""
        for fid in [f for f in self.fences if f.startswith(fpfx)]:
            for c in self.fence_waiters.get(fid, []):
                try:
                    _send_msg(c, {"error": f"aborted by rank "
                                           f"{rec[0]}: {rec[2]}"})
                except OSError:
                    pass
            self.fences.pop(fid, None)
            self.fence_waiters.pop(fid, None)
            self.fence_cids.pop(fid, None)
        self.cv.notify_all()
        return first, rec

    def _serve(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if not _require_hello(conn, self.secret):
            return
        dfs_fds: Dict[int, int] = {}
        try:
            while True:
                msg = _recv_msg(conn)
                if msg is None:
                    return
                op = msg.get("op") or ""
                if self._kill is not None and op != "hello" \
                        and self._kill.op():
                    # armed kv_kill: die BEFORE processing, exactly
                    # like a SIGKILL between recv and apply — the
                    # client saw no reply and must retry elsewhere
                    self.crash()
                    return
                if op == "hello":
                    # secretless server: ack so mixed configs work
                    _send_msg(conn, {"ok": True})
                elif op == "repl_stream":
                    # this connection IS the primary's replication
                    # feed: we are the standby from here on
                    self._apply_repl(conn)
                    return
                elif op.startswith("dfs_"):
                    _send_msg(conn, _dfs_serve(op, msg, dfs_fds))
                elif op == "put":
                    with self.cv:
                        self.data[msg["key"]] = msg["value"]
                        self._replicate({"op": "put",
                                         "key": msg["key"],
                                         "value": msg["value"]})
                        self.cv.notify_all()
                    _send_msg(conn, {"ok": True})
                elif op == "get":
                    timeout = msg.get("timeout", 60.0)
                    ns = msg.get("ns")
                    with self.cv:
                        deadline_hit = not self.cv.wait_for(
                            lambda: msg["key"] in self.data
                            or self.aborted is not None
                            or (ns is not None
                                and ns in self.ns_aborted),
                            timeout=timeout)
                        ab = self.aborted if self.aborted is not None \
                            else (self.ns_aborted.get(ns)
                                  if ns is not None else None)
                        if ab is not None:
                            _send_msg(conn, {"abort": list(ab)})
                        elif deadline_hit:
                            _send_msg(conn, {"timeout": True})
                        else:
                            _send_msg(conn, {"value": self.data[msg["key"]]})
                elif op == "incr":
                    # atomic fetch-and-add counter, distinct namespace
                    # from put/get data (dpm cid + rendezvous sequencing)
                    with self.cv:
                        v = self.counters.get(msg["key"], 0)
                        self.counters[msg["key"]] = v + 1
                        # replicated as the RESULT, not the op: a
                        # re-applied absolute value is idempotent
                        self._replicate({"op": "ctr",
                                         "key": msg["key"],
                                         "value": v + 1})
                    _send_msg(conn, {"value": v})
                elif op == "uncr":
                    # compensating decrement: roll a ticket back only
                    # if no later ticket was issued meanwhile (dpm
                    # rendezvous-timeout recovery)
                    with self.cv:
                        cur = self.counters.get(msg["key"], 0)
                        ok = cur == msg["expect"] + 1
                        if ok:
                            self.counters[msg["key"]] = msg["expect"]
                            self._replicate({"op": "ctr",
                                             "key": msg["key"],
                                             "value": msg["expect"]})
                    _send_msg(conn, {"ok": ok})
                elif op == "purge":
                    # prefix delete over data AND counters (including
                    # the put_once claim tickets, which live in the
                    # counter namespace as "claim:<key>"): store
                    # hygiene for ULFM notes/tickets at finalize and
                    # respawn epoch rollover
                    pfx = msg["prefix"]
                    with self.cv:
                        nd = self._purge_locked(pfx)
                        self._replicate({"op": "purge",
                                         "prefix": pfx})
                        self.cv.notify_all()
                    _send_msg(conn, {"ok": True, "n": nd})
                elif op == "take":
                    # blocking get that atomically deletes the record:
                    # one-shot rendezvous consumption (dpm accept/connect)
                    timeout = msg.get("timeout", 60.0)
                    ns = msg.get("ns")
                    with self.cv:
                        deadline_hit = not self.cv.wait_for(
                            lambda: msg["key"] in self.data
                            or self.aborted is not None
                            or (ns is not None
                                and ns in self.ns_aborted),
                            timeout=timeout)
                        ab = self.aborted if self.aborted is not None \
                            else (self.ns_aborted.get(ns)
                                  if ns is not None else None)
                        if ab is not None:
                            _send_msg(conn, {"abort": list(ab)})
                        elif deadline_hit:
                            _send_msg(conn, {"timeout": True})
                        else:
                            val = self.data.pop(msg["key"])
                            self._replicate({"op": "del",
                                             "key": msg["key"]})
                            _send_msg(conn, {"value": val})
                elif op == "fence":
                    # weighted arrival: a daemon KV proxy fences ONCE
                    # on behalf of its node's ranks (weight = local
                    # rank count); the fence completes when the summed
                    # weights reach n (grpcomm aggregation analog,
                    # ref: orte/mca/grpcomm — daemons collect their
                    # local procs' contributions).  cid-deduped, so a
                    # retried arrival is safe and the standby rebuilds
                    # in-flight fences from the replicated records.
                    with self.cv:
                        self._replicate({
                            "op": "fence", "id": msg["id"],
                            "cid": msg.get("cid"),
                            "n": msg.get("n"),
                            "weight": msg.get("weight", 1),
                            "ns": msg.get("ns")})
                        reply = self._fence_arrive_locked(msg, conn)
                    if reply is not None:
                        try:
                            _send_msg(conn, reply)
                        except OSError:
                            pass
                    # else: reply rides the completion broadcast
                elif op == "abort":
                    with self.cv:
                        self._replicate({
                            "op": "abort", "rank": msg["rank"],
                            "code": msg["code"],
                            "msg": msg.get("msg", ""),
                            "ns": msg.get("ns")})
                        first, _rec = self._abort_locked(msg)
                    if first and msg.get("ns") is None \
                            and self.on_abort is not None:
                        self.on_abort(self.aborted)
                    _send_msg(conn, {"ok": True})
                elif op == "spawn":
                    # allocate a universe-rank block and hand the
                    # launch to mpirun's supervision loop.  segments =
                    # [{cmd, args, n}] — one world spanning every
                    # segment (MPI_Comm_spawn_multiple shape; plain
                    # spawn is one segment)
                    segments = msg.get("segments") or [{
                        "cmd": msg["cmd"], "args": msg.get("args") or [],
                        "n": int(msg["maxprocs"])}]
                    total = sum(int(s["n"]) for s in segments)
                    with self.cv:
                        if not self.spawn_enabled:
                            _send_msg(conn, {
                                "error": "dynamic spawn is not "
                                         "supported by this launcher"})
                            continue
                        base = self.universe
                        self.universe += total
                        req = {
                            "base": base,
                            "maxprocs": total,
                            "segments": segments,
                            "parent_root": int(msg["parent_root"]),
                        }
                        self.spawn_requests.append(req)
                        self._replicate({"op": "spawn_state",
                                         "universe": self.universe,
                                         "req": req})
                        self.cv.notify_all()
                    if self.on_spawn is not None:
                        self.on_spawn()
                    _send_msg(conn, {"base": base})
        except OSError:
            return
        finally:
            self._conns.discard(conn)
            # a client gone without dfs_close must not leak this
            # long-lived process's descriptors (EMFILE would take
            # down the whole control plane)
            for fd in dfs_fds.values():
                try:
                    os.close(fd)
                except OSError:
                    pass

    def crash(self) -> None:
        """Simulate process death for chaos runs: hard-close the
        listener, every accepted connection and the replication
        stream, with NO orderly teardown — exactly what clients of a
        SIGKILLed server observe.  The standby (its own object with
        its own listener) keeps running and becomes the acting
        primary as clients fail over to it."""
        self._stop = True
        try:
            # shutdown BEFORE close here too: the accept thread is
            # parked in accept() on this listener, which pins the
            # kernel socket past close() — a reconnecting client's
            # handshake would still complete and the "dead" primary
            # would keep serving it.
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        if self._repl is not None:
            try:
                self._repl.close()
            except OSError:
                pass
            self._repl = None
        for c in list(self._conns):
            # shutdown BEFORE close: a serving thread is parked in
            # recv on this socket, which on Linux pins the open file
            # past close() — no FIN would reach the client and parked
            # fence waiters would never notice the death.  shutdown
            # tears the connection down regardless.
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def crash_host(self, host_id: int) -> bool:
        """Sever every endpoint of this server homed on failure
        domain ``host_id`` (the host-kill path: a dying host takes
        its resident KV endpoint with it).  Primary on the victim →
        crash() and the anti-affine standby keeps serving; standby on
        the victim → hard-close it and degrade replication, the
        primary keeps serving.  A co-resident standby (placed WITHOUT
        anti-affinity) dies together with its primary — exactly the
        wedge rte_base_kv_standby_host exists to avoid.  Returns True
        when any endpoint died."""
        hit = False
        if self.standby is not None \
                and self.standby.host_id == host_id:
            self.standby.crash()
            if self._repl is not None:
                try:
                    self._repl.close()
                except OSError:
                    pass
                self._repl = None
            self.repl_degraded = True
            hit = True
        if self.host_id == host_id:
            self.crash()
            hit = True
        return hit

    def close(self) -> None:
        self._stop = True
        try:
            self.sock.close()
        except OSError:
            pass
        if self._repl is not None:
            try:
                self._repl.close()
            except OSError:
                pass
            self._repl = None
        if self.standby is not None:
            self.standby.close()


class KVClient:
    """One per rank process.  Single socket, single lock: rank
    processes are single-threaded through the rte, and every op is
    strictly request/reply.  A second thread must NOT share this
    client (a blocking fence would starve it on the lock).

    Transient-fault tolerance: ops ride ``_request``, which
    reconnects and retries with backoff against a restarted or
    partitioned server, rotating through the kv2: endpoint list when
    the current endpoint refuses the reconnect (standby failover).
    A failed SEND is always retryable (the server discards a partial
    frame on its read error); a lost REPLY is retried only for
    idempotent ops — resending an ``incr`` the server already applied
    would corrupt the job.  ``fence`` is retryable because arrivals
    are cid-deduped server-side.

    ``ns`` scopes every key under "ns/" (put_once claim tickets under
    "claim:ns/", so the server's purge hygiene still sweeps them) and
    tags blocking ops so a namespace-scoped abort poisons only this
    client's session — the isolation contract of the DVM serve plane,
    where many resident sessions share one long-lived server.  The
    per-node KVProxy does not forward the ns abort tag; DVM sessions
    dial the shared server directly on loopback, never a proxy."""

    def __init__(self, addr: str, ns: Optional[str] = None) -> None:
        # 'host:port', or the replicated multi-endpoint uri
        # 'kv2:<primary>,<standby>' — endpoints tried in order, with
        # rotation on connect failure (the failover path)
        self.uri = addr
        eps = addr[4:] if addr.startswith("kv2:") else addr
        self._eps: List[Tuple[str, int]] = []
        for ep in eps.split(","):
            host, port = ep.rsplit(":", 1)
            self._eps.append((host, int(port)))
        self._ep_i = 0
        self.addr = self._eps[0]
        self.ns = ns or None
        # pvar attribution band: DVM session namespaces are "s<sid>"
        self._band = int(ns[1:]) if ns and ns.startswith("s") \
            and ns[1:].isdigit() else 0
        # stable client id for fence-arrival dedup (per client object,
        # monotonic so a recycled id never aliases an old arrival)
        self._cid = _next_cid()
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        err: Optional[OSError] = None
        for _ in range(len(self._eps)):
            try:
                self._sock = self._connect()
                break
            except PermissionError:
                raise
            except OSError as e:  # dead endpoint at dial time: rotate
                err = e
                self._ep_i = (self._ep_i + 1) % len(self._eps)
                self.addr = self._eps[self._ep_i]
        if self._sock is None:
            raise err if err is not None else ConnectionError(
                "kv server unreachable")
        from ompi_tpu import ft_inject
        self._inj = ft_inject.kv_injector(
            int(os.environ.get("TPUMPI_RANK", "0")))
        # gray-failure shaping (DESIGN.md §24): seeded added latency
        # on every KV op — the health plane's kv_rtt signal target
        self._nj = ft_inject.net_jitter_injector(
            int(os.environ.get("TPUMPI_RANK", "0")), scope="kv_net")

    def _connect(self) -> socket.socket:
        # with a standby available, fail a dead endpoint fast and
        # rotate instead of waiting out the full single-server grace
        timeout = 60 if len(self._eps) == 1 else 5
        s = socket.create_connection(self.addr, timeout=timeout)
        # connect timeout only: blocking ops (fence with rank skew,
        # modex gets) must not inherit a 60s socket timeout — hang
        # protection is the server-side get timeout + mpirun --timeout
        s.settimeout(None)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        secret = job_secret()
        if secret:
            _send_msg(s, {"op": "hello", "secret": secret})
            resp = _recv_msg(s)
            if not resp or not resp.get("ok"):
                s.close()
                raise PermissionError(
                    "kv server refused the job secret "
                    "(TPUMPI_JOB_SECRET mismatch)")
        return s

    def _drop_sock(self) -> None:
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = None

    def _note_failover(self) -> None:
        """Count + trace one endpoint rotation (the standby-promotion
        moment from this client's point of view).  Diagnostics only —
        never allowed to fail the recovery path."""
        try:
            _kv_pvars()[2].add(1, self._band)
            ep = f"{self.addr[0]}:{self.addr[1]}"
            from ompi_tpu import obs as _obs
            from ompi_tpu import trace
            tr = trace.current_tracer()
            if tr is not None:
                tr.instant("kv_failover", "rte", ep=ep, ns=self.ns)
            _obs.record_event(_obs.EV_KV_FAILOVER, self._band,
                              _obs.intern(ep))
        except Exception:  # noqa: BLE001
            pass

    def _request(self, msg: dict, idempotent: bool = False) -> dict:
        """One request/reply with reconnect + jittered-backoff retry
        (see class docstring for the idempotency contract).
        PermissionError (an OSError subclass!) is never retried — a
        refused job secret will not improve with patience.

        Failover: an endpoint that refuses the reconnect is rotated
        out immediately (no backoff) until every endpoint has been
        tried once — a warm standby is reached within one failed
        connect, keeping kill→first-completed-op MTTR at connect
        latency, not backoff latency.  Backoff applies only once a
        whole rotation came up empty."""
        nep = len(self._eps)
        tries = (1 + max(0, _kv_retry_max_var.value)) * nep
        delay = max(0.005, _kv_retry_delay_var.value)
        last: Optional[Exception] = None
        # with a standby, the first retries are SLEEPLESS — one per
        # endpoint: reconnect-refused + rotate + standby send happen
        # at connect latency, not backoff latency
        fast = nep if nep > 1 else 0
        backoffs = 0
        for attempt in range(tries):
            if attempt:
                _kv_pvars()[0].add(1, self._band)
                if fast > 0:
                    fast -= 1
                else:
                    # shared control-plane pacing (oob.backoff_s);
                    # lazy import — oob itself imports this module
                    from ompi_tpu.runtime import oob
                    time.sleep(oob.backoff_s(backoffs, delay, cap=2.0))
                    backoffs += 1
            if self._nj is not None:
                # net_jitter: delay only, never a drop — KV callers
                # see added RTT, exactly what the health plane scores
                d = self._nj.maybe_delay_s()
                if d:
                    time.sleep(d)
            with self._lock:
                if self._inj is not None and self._inj.sever():
                    # injected partition: close the socket under our
                    # own feet and let the machinery below recover
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                try:
                    if self._sock is None:
                        self._sock = self._connect()
                        _kv_pvars()[1].add(1, self._band)
                    _send_msg(self._sock, msg)
                except PermissionError:
                    raise
                except OSError as e:
                    last = e
                    connect_failed = self._sock is None
                    self._drop_sock()
                    if nep > 1 and connect_failed:
                        # the endpoint itself is down (not just this
                        # socket): fail over to the next one now
                        self._ep_i = (self._ep_i + 1) % nep
                        self.addr = self._eps[self._ep_i]
                        self._note_failover()
                    continue
                try:
                    resp = _recv_msg(self._sock)
                except OSError:
                    resp = None
                if resp is None:
                    self._drop_sock()
                    if idempotent:
                        last = ConnectionError(
                            "kv server closed mid-reply")
                        continue
                    raise ConnectionError("kv server closed")
                return resp
        if isinstance(last, Exception):
            eps = ",".join(f"{h}:{p}" for h, p in self._eps)
            hint = ""
            if nep > 1:
                # every endpoint in the kv2 list refused a full
                # rotation of reconnects: the classic cause is both
                # endpoints sharing one dead host (standby placed
                # without anti-affinity) — say so instead of leaving
                # the user to decode a bare connect error
                hint = ("; all endpoints are down — if they share a "
                        "host, the standby was placed without host "
                        "anti-affinity (see rte_base_kv_standby_host)")
            raise ConnectionError(
                f"kv server unreachable after {tries} attempts "
                f"across endpoints [{eps}]{hint}: {last}") from last
        raise ConnectionError("kv server unreachable")

    def _k(self, key: str) -> str:
        """Apply the namespace prefix.  Claim tickets keep their
        "claim:" marker OUTSIDE the namespace ("claim:ns/rest") so the
        server's purge branch — which matches counters against both
        ``pfx`` and ``"claim:" + pfx`` — sweeps a namespaced prefix's
        tickets exactly like an un-namespaced one's."""
        if self.ns is None:
            return key
        if key.startswith("claim:"):
            return "claim:" + self.ns + "/" + key[len("claim:"):]
        return f"{self.ns}/{key}"

    def _ns_tag(self, msg: dict) -> dict:
        if self.ns is not None:
            msg["ns"] = self.ns
        return msg

    def put(self, key: str, value: Any) -> None:
        self._request({"op": "put", "key": self._k(key),
                       "value": value}, idempotent=True)

    def get(self, key: str, timeout: float = 60.0) -> Any:
        resp = self._request(self._ns_tag(
            {"op": "get", "key": self._k(key),
             "timeout": timeout}), idempotent=True)
        if "abort" in resp:
            raise RuntimeError(f"job aborted: {resp['abort']}")
        if resp.get("timeout"):
            raise TimeoutError(f"kv get({key}) timed out")
        return resp["value"]

    def incr(self, key: str) -> int:
        """Atomic fetch-and-add on a server-side counter (returns the
        pre-increment value)."""
        resp = self._request({"op": "incr", "key": self._k(key)})
        return int(resp["value"])

    def put_once(self, key: str, value: Any) -> bool:
        """First-writer-wins publish: claims ``key`` through an
        incr-ticket (pre-increment 0 == first claimant) and only the
        winner stores the value.  Losers return False and must
        ``get`` the winner's value.  Gives the ULFM agreement/shrink
        protocols a decide-once primitive without a server-side CAS
        op."""
        if self.incr("claim:" + key) == 0:
            self.put(key, value)
            return True
        return False

    def purge(self, prefix: str) -> int:
        """Delete every data key and counter (including put_once claim
        tickets) under ``prefix``; returns the number removed.
        Idempotent by construction — deleting twice deletes nothing."""
        resp = self._request({"op": "purge", "prefix": self._k(prefix)},
                             idempotent=True)
        return int(resp.get("n", 0))

    def uncr(self, key: str, expect: int) -> bool:
        """Roll back a ticket taken with incr() (which returned
        ``expect``) — succeeds only if no later ticket was issued."""
        resp = self._request({"op": "uncr", "key": self._k(key),
                              "expect": expect})
        return bool(resp["ok"])

    def take(self, key: str, timeout: float = 60.0) -> Any:
        """Blocking get that atomically removes the record — one-shot
        rendezvous consumption."""
        resp = self._request(self._ns_tag(
            {"op": "take", "key": self._k(key), "timeout": timeout}))
        if "abort" in resp:
            raise RuntimeError(f"job aborted: {resp['abort']}")
        if resp.get("timeout"):
            raise TimeoutError(f"kv take({key}) timed out")
        return resp["value"]

    def fence(self, fence_id: str, n: Optional[int] = None,
              weight: int = 1) -> None:
        # cid-tagged, so a re-sent arrival (lost reply, or failover
        # onto the promoted standby mid-fence) re-registers this
        # client's waiter WITHOUT re-adding its weight — retryable,
        # hence idempotent=True; the standby rebuilds the in-flight
        # fence from the replicated arrivals plus these re-sends
        msg: Dict[str, Any] = self._ns_tag(
            {"op": "fence", "id": self._k(fence_id),
             "cid": self._cid})
        if n is not None:
            msg["n"] = n
        if weight != 1:
            msg["weight"] = weight
        try:
            resp = self._request(msg, idempotent=True)
        except ConnectionError as e:
            raise RuntimeError(f"fence {fence_id} failed: {e}") from e
        if "fence_done" not in resp:
            raise RuntimeError(f"fence {fence_id} failed: {resp}")

    def spawn(self, cmd: str, args: List[str], maxprocs: int,
              parent_root: int) -> int:
        """Ask the launcher for `maxprocs` new universe ranks running
        `cmd`; returns the allocated rank base."""
        return self.spawn_multiple(
            [{"cmd": cmd, "args": args, "n": maxprocs}], parent_root)

    def spawn_multiple(self, segments: List[dict],
                       parent_root: int) -> int:
        """Spawn one world made of several (cmd, args, n) segments
        (MPI_Comm_spawn_multiple)."""
        resp = self._request({"op": "spawn", "segments": segments,
                              "parent_root": parent_root})
        if "error" in resp:
            raise RuntimeError(f"MPI_Comm_spawn: {resp['error']}")
        return int(resp["base"])

    def abort(self, rank: int, code: int, msg: str = "") -> None:
        # best-effort by design: the job is going down anyway, and an
        # unreachable server must not mask the original error
        try:
            self._request(self._ns_tag(
                {"op": "abort", "rank": rank,
                 "code": code, "msg": msg}), idempotent=True)
        except (ConnectionError, OSError, RuntimeError):
            pass

    # -- dfs (orte/mca/dfs/app analog: remote read-only file access) ----
    def _dfs_req(self, msg: dict) -> dict:
        resp = self._request(msg)
        if "error" in resp:
            raise OSError(f"dfs: {resp['error']}")
        return resp

    def dfs_open(self, uri: str) -> Tuple[int, int]:
        resp = self._dfs_req({"op": "dfs_open", "uri": uri})
        return int(resp["fd"]), int(resp["size"])

    def dfs_read(self, fd: int, offset: int, n: int) -> bytes:
        resp = self._dfs_req({"op": "dfs_read", "fd": fd,
                              "offset": offset, "len": n})
        return resp["data"].encode("latin-1")

    def dfs_size(self, fd: int) -> int:
        return int(self._dfs_req({"op": "dfs_size",
                                  "fd": fd})["size"])

    def dfs_close(self, fd: int) -> None:
        self._dfs_req({"op": "dfs_close", "fd": fd})

    def close(self) -> None:
        with self._lock:
            self._drop_sock()


class KVProxy:
    """Per-node KV aggregation daemon — the grpcomm/routed analog.

    Runs inside tpud.  Local ranks speak the ordinary KV wire protocol
    to this proxy on loopback; the proxy maintains ONE upstream
    connection to the HNP's KVServer, so the central server sees
    O(daemons) connections instead of O(ranks) (ref:
    orte/mca/grpcomm/brucks — daemons aggregate their local procs'
    collective contributions; orte/mca/routed — control traffic rides
    the daemon overlay, not per-proc sockets).

    Aggregation:
      * fence  — collect ``local_expected`` arrivals, then ONE
        weighted upstream arrival (weight = local rank count); the
        server completes when summed weights reach n;
      * get    — write-once ``modex:`` keys are cached after the
        first fetch, so N local readers cost one upstream read;
        blocking upstream gets poll with short timeouts so one
        waiting rank never serializes the node's other traffic;
      * everything else (put/incr/uncr/take/abort/spawn) forwards.
    """

    def __init__(self, upstream_addr: str, local_expected: int) -> None:
        self.local_expected = max(1, local_expected)
        self.secret = job_secret()
        self.up = KVClient(upstream_addr)
        # dedicated fence channel, reused across fences (a pending
        # fence must never block ops; fences of one job are
        # sequential, so one channel suffices per node)
        self._up_fence: Optional[KVClient] = None
        self._fence_lock = threading.Lock()
        self._cache: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # fid -> [arrivals, result ('done'|'error'), waiter sockets]
        self._fences: Dict[str, list] = {}
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(64)
        self.addr = f"127.0.0.1:{self.sock.getsockname()[1]}"
        self._stop = False
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _poll_upstream(self, op: str, key: str, timeout: float):
        """Blocking get/take forwarded as short polls so the shared
        upstream channel is never held across a long wait."""
        deadline = time.monotonic() + timeout
        while True:
            left = deadline - time.monotonic()
            step = min(0.2, max(0.01, left))
            try:
                if op == "get":
                    return {"value": self.up.get(key, timeout=step)}
                return {"value": self.up.take(key, timeout=step)}
            except TimeoutError:
                if time.monotonic() >= deadline:
                    return {"timeout": True}
            except RuntimeError as e:  # job abort rides the reply
                return {"abort": str(e)}

    def _dfs_upstream(self, msg: dict) -> dict:
        with self.up._lock:
            _send_msg(self.up._sock, msg)
            resp = _recv_msg(self.up._sock)
        return resp or {"error": "upstream gone"}

    def _serve(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if not _require_hello(conn, self.secret):
            return
        dfs_fds: Dict[int, int] = {}
        dfs_owner: Dict[int, str] = {}  # forwarded fd -> remote host
        try:
            while True:
                msg = _recv_msg(conn)
                if msg is None:
                    return
                op = msg.get("op") or ""
                if op == "hello":
                    _send_msg(conn, {"ok": True})
                elif op.startswith("dfs_"):
                    # client-visible REMOTE fds are offset by _DFS_REMOTE
                    # so they live in a namespace disjoint from this
                    # node's os fds (a collision would silently route
                    # local reads to the wrong remote file)
                    fd_in = int(msg.get("fd", -1))
                    if op == "dfs_open":
                        host = dfs_parse_uri(msg.get("uri", ""))[0]
                        local = host in (
                            "", "localhost",
                            os.environ.get("TPUMPI_NODE_NAME", ""))
                        if local:
                            _send_msg(conn,
                                      _dfs_serve(op, msg, dfs_fds))
                        else:
                            resp = self._dfs_upstream(msg)
                            if "fd" in resp:
                                up = int(resp["fd"])
                                dfs_owner[_DFS_REMOTE + up] = up
                                resp["fd"] = _DFS_REMOTE + up
                            _send_msg(conn, resp)
                    elif fd_in in dfs_owner:
                        fwd = dict(msg)
                        fwd["fd"] = dfs_owner[fd_in]
                        resp = self._dfs_upstream(fwd)
                        if op == "dfs_close":
                            dfs_owner.pop(fd_in, None)
                        _send_msg(conn, resp)
                    else:
                        _send_msg(conn, _dfs_serve(op, msg, dfs_fds))
                elif op == "put":
                    self.up.put(msg["key"], msg["value"])
                    _send_msg(conn, {"ok": True})
                elif op == "get":
                    key = msg["key"]
                    with self._lock:
                        hit = self._cache.get(key)
                    if hit is not None:
                        _send_msg(conn, {"value": hit})
                        continue
                    resp = self._poll_upstream(
                        "get", key, msg.get("timeout", 60.0))
                    if "value" in resp and key.startswith("modex:"):
                        # modex keys are write-once per rank: safe to
                        # serve every later local reader from cache
                        with self._lock:
                            self._cache[key] = resp["value"]
                    _send_msg(conn, resp)
                elif op == "take":
                    _send_msg(conn, self._poll_upstream(
                        "take", msg["key"], msg.get("timeout", 60.0)))
                elif op == "incr":
                    _send_msg(conn, {"value": self.up.incr(msg["key"])})
                elif op == "uncr":
                    _send_msg(conn, {"ok": self.up.uncr(
                        msg["key"], msg["expect"])})
                elif op == "purge":
                    pfx = msg["prefix"]
                    with self._lock:
                        for k in [k for k in self._cache
                                  if k.startswith(pfx)]:
                            del self._cache[k]
                    _send_msg(conn,
                              {"ok": True,
                               "n": self.up.purge(pfx)})
                elif op == "abort":
                    try:
                        self.up.abort(msg["rank"], msg["code"],
                                      msg.get("msg", ""))
                    except (RuntimeError, OSError):
                        pass
                    _send_msg(conn, {"ok": True})
                elif op == "fence":
                    self._fence(conn, msg)
                elif op == "spawn":
                    with self.up._lock:
                        _send_msg(self.up._sock, msg)
                        resp = _recv_msg(self.up._sock)
                    _send_msg(conn, resp or {"error": "upstream gone"})
        except OSError:
            return
        finally:
            for fd in dfs_fds.values():
                try:
                    os.close(fd)
                except OSError:
                    pass
            for cfd, up in dfs_owner.items():
                try:
                    self._dfs_upstream({"op": "dfs_close", "fd": up})
                except Exception:
                    pass

    def _fence(self, conn: socket.socket, msg: dict) -> None:
        fid = msg["id"]
        release = None
        with self._cv:
            ent = self._fences.setdefault(fid, [0, None, []])
            ent[0] += 1
            ent[2].append(conn)
            if ent[0] == self.local_expected:
                release = ent
        if release is None:
            return  # reply comes when the node's last rank arrives
        # last local arrival: ONE weighted upstream fence on the
        # dedicated fence channel
        try:
            with self._fence_lock:
                if self._up_fence is None:
                    # the full uri, not the current endpoint: the
                    # fence channel must inherit the failover list
                    self._up_fence = KVClient(self.up.uri)
                self._up_fence.fence(fid, n=msg.get("n"),
                                     weight=self.local_expected)
            reply = {"fence_done": fid}
        except (RuntimeError, OSError) as e:
            reply = {"error": f"fence failed: {e}"}
        with self._cv:
            ent = self._fences.pop(fid)
        for c in ent[2]:
            try:
                _send_msg(c, reply)
            except OSError:
                pass

    def close(self) -> None:
        self._stop = True
        try:
            self.sock.close()
        except OSError:
            pass
        try:
            self.up.close()
        except OSError:
            pass
        if self._up_fence is not None:
            try:
                self._up_fence.close()
            except OSError:
                pass
