"""Admin event sinks — the orte/mca/notifier analog.

Re-design of orte/mca/notifier (ref: orte/mca/notifier/syslog,
orte/mca/notifier/smtp — administrator-facing job events routed to
pluggable sinks, selected by MCA parameter).  Sinks here:

  * ``stderr`` (default) — one-line tagged records;
  * ``syslog``           — stdlib syslog (severity-mapped);
  * ``file:<path>``      — append-only event log.

The launcher's errmgr state handlers call ``notify`` on job-level
events (proc failure, daemon loss, abort, timeout); severities follow
the reference's ORTE_NOTIFIER_{EMERG..DEBUG} ladder.
"""

from __future__ import annotations

import sys
import time
from typing import List

from ompi_tpu.mca.params import registry

_sinks_var = registry.register(
    "orte", "notifier", "sinks", "", str,
    help="Comma list of admin event sinks: stderr, syslog, "
         "file:<path>.  Empty (default) = off — mpirun's own stderr "
         "diagnostics always print regardless.")

SEVERITIES = ("emerg", "alert", "crit", "error", "warn", "notice",
              "info", "debug")


def _emit_stderr(severity: str, job: str, msg: str) -> None:
    sys.stderr.write(
        f"[notifier:{severity}] {time.strftime('%H:%M:%S')} "
        f"job={job} {msg}\n")
    sys.stderr.flush()


def _emit_syslog(severity: str, job: str, msg: str) -> None:
    import syslog
    level = {
        "emerg": syslog.LOG_EMERG, "alert": syslog.LOG_ALERT,
        "crit": syslog.LOG_CRIT, "error": syslog.LOG_ERR,
        "warn": syslog.LOG_WARNING, "notice": syslog.LOG_NOTICE,
        "info": syslog.LOG_INFO, "debug": syslog.LOG_DEBUG,
    }.get(severity, syslog.LOG_NOTICE)
    syslog.syslog(level, f"ompi_tpu job={job}: {msg}")


def _emit_file(path: str, severity: str, job: str, msg: str) -> None:
    with open(path, "a") as fh:
        fh.write(f"{time.time():.3f} {severity} job={job} {msg}\n")


_warned_sinks: set = set()


def notify(severity: str, job: str, msg: str) -> None:
    """Route one admin event to every configured sink.  EMIT-time
    failures are swallowed (losing a notification must never take the
    job down — the reference's notifier discipline), but a
    misconfigured sink NAME warns once: a typo silently disabling
    admin events is undetectable otherwise."""
    if severity not in SEVERITIES:
        severity = "notice"
    for sink in [s.strip() for s in _sinks_var.value.split(",") if s]:
        try:
            if sink == "stderr":
                _emit_stderr(severity, job, msg)
            elif sink == "syslog":
                _emit_syslog(severity, job, msg)
            elif sink.startswith("file:"):
                _emit_file(sink[5:], severity, job, msg)
            elif sink not in _warned_sinks:
                _warned_sinks.add(sink)
                sys.stderr.write(
                    f"[notifier] unknown sink {sink!r} in "
                    f"orte_notifier_sinks (expected stderr, syslog, "
                    f"file:<path>)\n")
        except Exception:  # noqa: BLE001 — see docstring
            pass
