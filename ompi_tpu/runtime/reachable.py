"""NIC enumeration + pairwise connectivity scoring — the opal if/
reachable analog.

Re-design of opal/mca/if (interface discovery) and
opal/mca/reachable/weighted (ref:
opal/mca/reachable/weighted/reachable_weighted.c — weighted scoring
of (local NIC, remote NIC) pairs: same network > same address kind >
different kind, scaled by link bandwidth).  Interfaces come from
sysfs + SIOCGIFADDR ioctls (Linux stdlib only); the tcp btl uses
``best_addr``/``score_pair`` to advertise every usable address in the
modex and to pick the highest-scoring reachable pair when dialing.
"""

from __future__ import annotations

import fcntl
import glob
import ipaddress
import os
import socket
import struct
from typing import Dict, List, Optional, Tuple

SIOCGIFADDR = 0x8915
SIOCGIFNETMASK = 0x891B


class Interface:
    __slots__ = ("name", "ip", "netmask", "up", "speed_mbps", "mtu",
                 "loopback")

    def __init__(self, name: str, ip: str, netmask: str, up: bool,
                 speed_mbps: int, mtu: int) -> None:
        self.name = name
        self.ip = ip
        self.netmask = netmask
        self.up = up
        self.speed_mbps = speed_mbps
        self.mtu = mtu
        self.loopback = ip.startswith("127.")

    @property
    def network(self) -> Optional[ipaddress.IPv4Network]:
        try:
            return ipaddress.IPv4Network(f"{self.ip}/{self.netmask}",
                                         strict=False)
        except ValueError:
            return None

    def __repr__(self) -> str:
        return (f"Interface({self.name}, {self.ip}/{self.netmask}, "
                f"up={self.up}, {self.speed_mbps} Mb/s)")


def _if_ioctl(sock: socket.socket, name: str, req: int) -> Optional[str]:
    try:
        packed = struct.pack("256s", name.encode()[:15])
        out = fcntl.ioctl(sock.fileno(), req, packed)
        return socket.inet_ntoa(out[20:24])
    except OSError:
        return None


def interfaces() -> List[Interface]:
    """Enumerate IPv4-configured NICs (the opal_if list analog)."""
    out: List[Interface] = []
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        for path in sorted(glob.glob("/sys/class/net/*")):
            name = os.path.basename(path)
            ip = _if_ioctl(s, name, SIOCGIFADDR)
            if ip is None:
                continue
            mask = _if_ioctl(s, name, SIOCGIFNETMASK) or "255.255.255.255"
            up = True
            try:
                with open(os.path.join(path, "operstate")) as fh:
                    st = fh.read().strip()
                up = st in ("up", "unknown")  # lo reports 'unknown'
            except OSError:
                pass
            speed = -1
            try:
                with open(os.path.join(path, "speed")) as fh:
                    speed = int(fh.read().strip())
            except (OSError, ValueError):
                pass
            mtu = 1500
            try:
                with open(os.path.join(path, "mtu")) as fh:
                    mtu = int(fh.read().strip())
            except (OSError, ValueError):
                pass
            out.append(Interface(name, ip, mask, up, speed, mtu))
    finally:
        s.close()
    if not out:
        out = [Interface("lo", "127.0.0.1", "255.0.0.0", True, -1,
                         65536)]
    return out


def _kind(ip: str) -> str:
    a = ipaddress.IPv4Address(ip)
    if a.is_loopback:
        return "loopback"
    if a.is_private:
        return "private"
    return "public"


def score_pair(local: Interface, remote_ip: str) -> int:
    """Weighted connectivity estimate for (local NIC, remote addr) —
    the reachable_weighted calculate_weight model: same network
    beats same kind beats mismatch, bandwidth breaks ties."""
    if not local.up:
        return 0
    lk, rk = _kind(local.ip), _kind(remote_ip)
    if lk == "loopback" or rk == "loopback":
        # loopback never reaches another host; same-host reachability
        # is handled by pick_remote_addr's explicit fallback so a
        # peer's advertised 127.0.0.1 can never outscore its real NIC
        return 0
    net = local.network
    if net is not None and ipaddress.IPv4Address(remote_ip) in net:
        base = 3000
    elif lk == rk:
        base = 2000
    else:
        base = 1000
    bw = max(0, min(local.speed_mbps, 400_000)) // 1000  # 0..400
    return base + bw


def advertised_addrs() -> List[str]:
    """Every usable local address, best NICs first — what the tcp btl
    publishes in the modex (multi-NIC hosts expose them all; the
    dialing side scores and picks)."""
    ifs = sorted(interfaces(),
                 key=lambda i: (not i.up, i.loopback, -i.speed_mbps))
    # loopback is never advertised: a cross-host dialer that selected
    # it would connect to its OWN host (same-host jobs use the
    # loopback-only if_ip path, not multi-NIC advertising)
    return [i.ip for i in ifs if i.up and not i.loopback]


def best_local_toward(remote_ip: str) -> Tuple[Optional[Interface], int]:
    """Highest-scoring local NIC for a remote address."""
    best, best_s = None, 0
    for i in interfaces():
        s = score_pair(i, remote_ip)
        if s > best_s:
            best, best_s = i, s
    return best, best_s


def pick_remote_addr(remote_ips: List[str]) -> Optional[str]:
    """Best remote address to dial from this host (max over the
    pairwise score matrix — the reachable bipartite-graph pick)."""
    best_ip, best_s = None, -1
    for rip in remote_ips:
        _, s = best_local_toward(rip)
        # a loopback address is always locally reachable (same host)
        if s == 0 and _kind(rip) == "loopback":
            s = 1
        if s > best_s:
            best_ip, best_s = rip, s
    return best_ip
