"""RAS analog: resource allocation — turn user input into a node list.

Re-design of orte/mca/ras (node-list acquisition): sources are the
command line (--hosts a,b:4), a hostfile (--hostfile, the flex parser
ref: orte/util/hostfile/hostfile.c:51-55 collapsed to line parsing),
or the **simulator** (--simulate-nodes NxM — the ras/simulator analog,
ref: orte/mca/ras/simulator/ras_sim_module.c:67-91: fabricate an
N-node allocation with M slots each so multi-node mapping/launch/
wireup logic is testable on one machine; each simulated node gets an
M-device forced-CPU jax platform, i.e. a fake N-node × M-chip mesh).

With no source the allocation is the single local node.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class Node:
    """One allocated node (orte_node_t analog)."""

    name: str
    slots: int
    node_id: int = 0
    simulated: bool = False    # launched as a local process, fake identity
    local: bool = False        # the HNP's own host — exec directly, no agent
    sim_devices: int = 0       # simulator: forced-CPU device count


def parse_hosts(spec: str) -> List[Node]:
    """--hosts a,b:4,c — OMPI's comma list with optional :slots."""
    nodes: List[Node] = []
    for i, item in enumerate(x for x in spec.split(",") if x.strip()):
        item = item.strip()
        if ":" in item:
            name, slots_s = item.rsplit(":", 1)
            slots = int(slots_s)
        else:
            name, slots = item, 1
        if slots < 1:
            raise ValueError(f"--hosts: bad slot count in {item!r}")
        nodes.append(Node(name=name, slots=slots, node_id=i,
                          local=name in ("localhost", "127.0.0.1")))
    if not nodes:
        raise ValueError("--hosts: empty host list")
    return nodes


def parse_hostfile(path: str) -> List[Node]:
    """Hostfile lines: ``name [slots=N]`` (# comments allowed)."""
    nodes: List[Node] = []
    with open(path) as f:
        for raw in f:
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            name = parts[0]
            slots = 1
            for p in parts[1:]:
                m = re.fullmatch(r"slots=(\d+)", p)
                if m:
                    slots = int(m.group(1))
            nodes.append(Node(name=name, slots=slots,
                              node_id=len(nodes),
                              local=name in ("localhost", "127.0.0.1")))
    if not nodes:
        raise ValueError(f"hostfile {path}: no nodes")
    return nodes


def parse_simulate(spec: str) -> List[Node]:
    """--simulate-nodes NxM (N nodes, M slots/chips each) or just N."""
    m = re.fullmatch(r"(\d+)(?:x(\d+))?", spec.strip())
    if not m:
        raise ValueError(f"--simulate-nodes: expected NxM, got {spec!r}")
    n, slots = int(m.group(1)), int(m.group(2) or 1)
    if n < 1 or slots < 1:
        raise ValueError("--simulate-nodes: N and M must be >= 1")
    return [Node(name=f"sim{i}", slots=slots, node_id=i, simulated=True,
                 sim_devices=slots) for i in range(n)]


def allocate(hosts: Optional[str], hostfile: Optional[str],
             simulate: Optional[str], np: int) -> List[Node]:
    """Pick the allocation source (priority: simulate > hosts >
    hostfile > single local node sized to the job)."""
    given = sum(x is not None for x in (hosts, hostfile, simulate))
    if given > 1:
        raise ValueError(
            "--hosts, --hostfile and --simulate-nodes are exclusive")
    if simulate is not None:
        return parse_simulate(simulate)
    if hosts is not None:
        return parse_hosts(hosts)
    if hostfile is not None:
        return parse_hostfile(hostfile)
    return [Node(name="localhost", slots=np, node_id=0, local=True)]
