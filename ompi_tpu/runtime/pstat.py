"""Process statistics — the opal/mca/pstat analog.

Re-design of opal/mca/pstat/linux (ref:
opal/mca/pstat/linux/pstat_linux_module.c — /proc scraping into
opal_pstats_t: state, cpu times, vsize/rss, threads).  Exposed as a
plain snapshot function plus MPI_T-style pvar registration so
``ompi_info``/tooling can sample a rank's footprint.
"""

from __future__ import annotations

import os
from typing import Dict, Optional


def snapshot(pid: Optional[int] = None) -> Dict[str, float]:
    """One process-stat sample (the pstat_query analog).  Returns
    empty dict off-Linux rather than failing — diagnostics must never
    take a rank down."""
    pid = pid or os.getpid()
    out: Dict[str, float] = {}
    try:
        with open(f"/proc/{pid}/stat") as fh:
            fields = fh.read().rsplit(")", 1)[1].split()
        # fields are 0-indexed from field 3 ("state") here
        tck = os.sysconf("SC_CLK_TCK") or 100
        out["state"] = float(ord(fields[0][0]))
        out["utime_s"] = int(fields[11]) / tck
        out["stime_s"] = int(fields[12]) / tck
        out["threads"] = float(fields[17])
        out["vsize_mb"] = int(fields[20]) / (1024 * 1024)
        page = os.sysconf("SC_PAGE_SIZE")
        out["rss_mb"] = int(fields[21]) * page / (1024 * 1024)
    except (OSError, IndexError, ValueError):
        return {}
    try:
        with open(f"/proc/{pid}/statm") as fh:
            statm = fh.read().split()
        page = os.sysconf("SC_PAGE_SIZE")
        out["shared_mb"] = int(statm[2]) * page / (1024 * 1024)
    except (OSError, IndexError, ValueError):
        pass
    return out


# ranks whose pvars are already registered: mpi_init runs once per
# WORLD but the registry is process-global, so looped tests creating
# world after world would otherwise re-register rss_mb_r{rank} and
# either collide or silently orphan the fresh getters
_registered: set = set()


def register_pvars(rank: int) -> None:
    """Publish live-sampled pvars (rss/threads) for this rank — the
    MPI_T face of the pstat framework (read-time getters).
    Idempotent per rank across repeated world creation."""
    from ompi_tpu.mca.params import registry

    if rank in _registered:
        return
    _registered.add(rank)
    registry.register_pvar(
        "opal", "pstat", f"rss_mb_r{rank}", var_class="level",
        help="Resident set size (MiB), sampled at read",
        getter=lambda: snapshot().get("rss_mb", 0.0))
    registry.register_pvar(
        "opal", "pstat", f"threads_r{rank}", var_class="level",
        help="OS thread count, sampled at read",
        getter=lambda: snapshot().get("threads", 0.0))
