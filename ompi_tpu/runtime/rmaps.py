"""RMAPS analog: map ranks onto the allocated nodes.

Re-design of orte/mca/rmaps (round_robin component's byslot/bynode
policies, ref: orte/mca/rmaps/round_robin): the map is the launch
blueprint shipped to each node's daemon.  Two shapes per node:

  * classic — one process per rank (blocks of nlocal=0 below);
  * hybrid  — rank-threads grouped into app shells of ``rpp`` ranks
    (the TPU-host model; requires *contiguous* global ranks per shell,
    which is why bynode mapping is rejected when rpp > 1).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

from .ras import Node


@dataclass
class ProcSpec:
    """One local launch unit on a node: a single rank process
    (nlocal == 0) or an app shell owning ranks
    [rank_base, rank_base + nlocal)."""

    rank_base: int
    nlocal: int  # 0 = classic single-rank process


@dataclass
class NodeMap:
    node: Node
    procs: List[ProcSpec] = field(default_factory=list)

    @property
    def ranks(self) -> List[int]:
        out: List[int] = []
        for p in self.procs:
            out += list(range(p.rank_base,
                              p.rank_base + max(1, p.nlocal)))
        return out


def map_ranks(nodes: List[Node], np: int, rpp: int = 1,
              policy: str = "byslot",
              oversubscribe: bool = False) -> List[NodeMap]:
    """Produce the job map.  ``rpp`` > 1 selects hybrid shells of that
    many rank-threads (capped per node by its slot count and the ranks
    assigned to it)."""
    total_slots = sum(n.slots for n in nodes)
    if np > total_slots and not oversubscribe:
        raise ValueError(
            f"not enough slots: {np} ranks > {total_slots} slots "
            f"(use --oversubscribe)")
    if policy not in ("byslot", "bynode"):
        raise ValueError(f"unknown mapping policy {policy!r}")
    if rpp > 1 and policy == "bynode":
        raise ValueError(
            "--ranks-per-proc > 1 requires byslot mapping (app shells "
            "own contiguous rank blocks)")

    # ranks → nodes
    per_node: List[List[int]] = [[] for _ in nodes]
    if policy == "byslot":
        # within capacity: fill each node to its slots in order.
        # oversubscribed: contiguous slot-proportional shares (largest-
        # remainder), preserving the per-node contiguity the hybrid
        # shells rely on.
        if np <= total_slots:
            shares = []
            left = np
            for n in nodes:
                take = min(n.slots, left)
                shares.append(take)
                left -= take
        else:
            shares = [np * n.slots // total_slots for n in nodes]
            rema = sorted(
                range(len(nodes)),
                key=lambda i: (-(np * nodes[i].slots % total_slots), i))
            for i in rema[:np - sum(shares)]:
                shares[i] += 1
        rank = 0
        for i, take in enumerate(shares):
            per_node[i] = list(range(rank, rank + take))
            rank += take
    else:  # bynode round-robin
        i = 0
        counts = [0] * len(nodes)
        for rank in range(np):
            # next node with free slots, else plain round-robin when
            # oversubscribed
            tries = 0
            while tries < len(nodes) and counts[i] >= nodes[i].slots \
                    and any(c < n.slots for c, n in zip(counts, nodes)):
                i = (i + 1) % len(nodes)
                tries += 1
            per_node[i].append(rank)
            counts[i] += 1
            i = (i + 1) % len(nodes)

    # ranks → launch units
    maps: List[NodeMap] = []
    for node, ranks in zip(nodes, per_node):
        nm = NodeMap(node=node)
        if ranks:
            if rpp > 1:
                # contiguity invariant for HybridWorld
                if ranks != list(range(ranks[0], ranks[0] + len(ranks))):
                    raise ValueError(
                        "hybrid shells need contiguous ranks per node")
                base = ranks[0]
                left = len(ranks)
                while left > 0:
                    n = min(rpp, left)
                    nm.procs.append(ProcSpec(rank_base=base, nlocal=n))
                    base += n
                    left -= n
            else:
                nm.procs = [ProcSpec(rank_base=r, nlocal=0) for r in ranks]
        maps.append(nm)
    return maps
