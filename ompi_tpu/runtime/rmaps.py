"""RMAPS analog: map ranks onto the allocated nodes.

Re-design of orte/mca/rmaps: the map is the launch blueprint shipped
to each node's daemon.  Policies:

  * ``byslot`` / ``bynode`` — round_robin component (ref:
    orte/mca/rmaps/round_robin): fill nodes to slot capacity vs
    round-robin across nodes;
  * ``ppr:N:node`` — procs-per-resource (ref: orte/mca/rmaps/ppr):
    exactly N ranks per node, node order;
  * ``seq`` — sequential mapper (ref: orte/mca/rmaps/seq): strict
    round-robin in allocation order, ignoring slot counts;
  * ``rankfile:PATH`` — explicit placement (ref:
    orte/mca/rmaps/rank_file): lines ``rank R=nodename`` (or
    ``R nodename``); every rank must be assigned exactly once.

Within-node placement (cores/NUMA — the mindist concern) is handled
by binding at rank bring-up (runtime/topology.py, --bind-to).

Two launch-unit shapes per node:

  * classic — one process per rank (blocks of nlocal=0 below);
  * hybrid  — rank-threads grouped into app shells of ``rpp`` ranks
    (the TPU-host model; requires *contiguous* global ranks per shell,
    which is why non-contiguous mappings are rejected when rpp > 1).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from .ras import Node


@dataclass
class ProcSpec:
    """One local launch unit on a node: a single rank process
    (nlocal == 0) or an app shell owning ranks
    [rank_base, rank_base + nlocal)."""

    rank_base: int
    nlocal: int  # 0 = classic single-rank process


@dataclass
class NodeMap:
    node: Node
    procs: List[ProcSpec] = field(default_factory=list)

    @property
    def ranks(self) -> List[int]:
        out: List[int] = []
        for p in self.procs:
            out += list(range(p.rank_base,
                              p.rank_base + max(1, p.nlocal)))
        return out


def map_ranks(nodes: List[Node], np: int, rpp: int = 1,
              policy: str = "byslot",
              oversubscribe: bool = False) -> List[NodeMap]:
    """Produce the job map.  ``rpp`` > 1 selects hybrid shells of that
    many rank-threads (capped per node by its slot count and the ranks
    assigned to it)."""
    total_slots = sum(n.slots for n in nodes)
    base_policy = policy.split(":", 1)[0]
    if base_policy not in ("byslot", "bynode", "ppr", "seq",
                           "rankfile"):
        raise ValueError(f"unknown mapping policy {policy!r}")
    if np > total_slots and not oversubscribe \
            and base_policy not in ("seq", "rankfile", "ppr"):
        raise ValueError(
            f"not enough slots: {np} ranks > {total_slots} slots "
            f"(use --oversubscribe)")
    if rpp > 1 and base_policy not in ("byslot", "ppr"):
        raise ValueError(
            "--ranks-per-proc > 1 requires a contiguous mapping "
            "(byslot or ppr: app shells own contiguous rank blocks)")

    # ranks → nodes
    per_node: List[List[int]] = [[] for _ in nodes]
    if base_policy == "ppr":
        # ppr:N:node — exactly N ranks per node in node order
        parts = policy.split(":")
        if len(parts) != 3 or parts[2] != "node":
            raise ValueError(
                f"ppr policy must be 'ppr:N:node', got {policy!r}")
        try:
            n_per = int(parts[1])
        except ValueError:
            raise ValueError(f"bad ppr count in {policy!r}") from None
        if n_per < 1:
            raise ValueError("ppr count must be >= 1")
        if np > n_per * len(nodes):
            raise ValueError(
                f"ppr:{n_per}:node places at most "
                f"{n_per * len(nodes)} ranks < {np}")
        over = [n.name for n in nodes if n_per > n.slots]
        if over and not oversubscribe:
            raise ValueError(
                f"ppr:{n_per}:node exceeds the slot count on "
                f"node(s) {over} (use --oversubscribe)")
        rank = 0
        for i in range(len(nodes)):
            take = min(n_per, np - rank)
            per_node[i] = list(range(rank, rank + take))
            rank += take
            if rank >= np:
                break
    elif base_policy == "seq":
        # strict round-robin in allocation order, slots ignored
        for rank in range(np):
            per_node[rank % len(nodes)].append(rank)
    elif base_policy == "rankfile":
        _, _, path = policy.partition(":")
        if not path:
            raise ValueError("rankfile policy needs a path "
                             "(rankfile:PATH)")
        by_name = {n.name: i for i, n in enumerate(nodes)}
        placed = {}
        with open(path) as fh:
            for ln, line in enumerate(fh, 1):
                line = line.split("#", 1)[0].strip()
                if not line:
                    continue
                # 'rank R=node' (reference syntax) or 'R node'
                try:
                    if line.startswith("rank") and "=" in line:
                        rpart, npart = line[4:].split("=", 1)
                        r, name = int(rpart.strip()), npart.split()[0]
                    else:
                        toks = line.split()
                        r, name = int(toks[0]), toks[1]
                except (ValueError, IndexError):
                    raise ValueError(
                        f"rankfile line {ln}: malformed entry "
                        f"{line!r}") from None
                if not 0 <= r < np:
                    raise ValueError(
                        f"rankfile line {ln}: rank {r} out of range "
                        f"for -np {np}")
                if name not in by_name:
                    raise ValueError(
                        f"rankfile line {ln}: unknown node {name!r}")
                if r in placed:
                    raise ValueError(
                        f"rankfile line {ln}: rank {r} placed twice")
                placed[r] = by_name[name]
        missing = [r for r in range(np) if r not in placed]
        if missing:
            raise ValueError(
                f"rankfile leaves rank(s) {missing} unplaced")
        counts: Dict[int, int] = {}
        for r in range(np):
            counts[placed[r]] = counts.get(placed[r], 0) + 1
        over = [nodes[i].name for i, c in counts.items()
                if c > nodes[i].slots]
        if over and not oversubscribe:
            raise ValueError(
                f"rankfile oversubscribes node(s) {over} "
                f"(use --oversubscribe)")
        for r in range(np):
            per_node[placed[r]].append(r)
    if base_policy == "byslot":
        # within capacity: fill each node to its slots in order.
        # oversubscribed: contiguous slot-proportional shares (largest-
        # remainder), preserving the per-node contiguity the hybrid
        # shells rely on.
        if np <= total_slots:
            shares = []
            left = np
            for n in nodes:
                take = min(n.slots, left)
                shares.append(take)
                left -= take
        else:
            shares = [np * n.slots // total_slots for n in nodes]
            rema = sorted(
                range(len(nodes)),
                key=lambda i: (-(np * nodes[i].slots % total_slots), i))
            for i in rema[:np - sum(shares)]:
                shares[i] += 1
        rank = 0
        for i, take in enumerate(shares):
            per_node[i] = list(range(rank, rank + take))
            rank += take
    elif base_policy == "bynode":  # round-robin
        i = 0
        counts = [0] * len(nodes)
        for rank in range(np):
            # next node with free slots, else plain round-robin when
            # oversubscribed
            tries = 0
            while tries < len(nodes) and counts[i] >= nodes[i].slots \
                    and any(c < n.slots for c, n in zip(counts, nodes)):
                i = (i + 1) % len(nodes)
                tries += 1
            per_node[i].append(rank)
            counts[i] += 1
            i = (i + 1) % len(nodes)

    # ranks → launch units
    maps: List[NodeMap] = []
    for node, ranks in zip(nodes, per_node):
        nm = NodeMap(node=node)
        if ranks:
            if rpp > 1:
                # contiguity invariant for HybridWorld
                if ranks != list(range(ranks[0], ranks[0] + len(ranks))):
                    raise ValueError(
                        "hybrid shells need contiguous ranks per node")
                base = ranks[0]
                left = len(ranks)
                while left > 0:
                    n = min(rpp, left)
                    nm.procs.append(ProcSpec(rank_base=base, nlocal=n))
                    base += n
                    left -= n
            else:
                nm.procs = [ProcSpec(rank_base=r, nlocal=0) for r in ranks]
        maps.append(nm)
    return maps
