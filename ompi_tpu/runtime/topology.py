"""Host topology detection + process binding — the hwloc/rtc analog.

Re-design of opal/mca/hwloc (embedded hwloc topology objects,
ref: opal/mca/hwloc/hwloc.h) and orte/mca/rtc/hwloc (cpu binding
applied pre-exec, ref: orte/mca/rtc/hwloc/rtc_hwloc.c).  The
reference embeds all of hwloc (~40 kLoC) to model caches, packages
and PCI; for a TPU-host framework the model that matters is

    host -> NUMA node -> cpus
         -> accelerator devices (chips), with ICI neighbor order

so detection reads sysfs directly (Linux) with a portable fallback,
and device locality comes from the JAX device table (``coords`` on
real TPUs encode the ICI torus position — rank->chip->ICI-neighbor
placement IS the performance model on pods).

Binding policy (the rtc analog) is applied in-process via
``os.sched_setaffinity`` at rank bring-up: mpirun exports
``TPUMPI_BIND=core|numa|none`` and each rank binds itself using its
local rank index — same effect as the reference's pre-exec binding,
without needing a privileged helper.
"""

from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional, Tuple


def _read_int(path: str, default: int = -1) -> int:
    try:
        with open(path) as fh:
            return int(fh.read().strip())
    except (OSError, ValueError):
        return default


def _parse_cpulist(text: str) -> List[int]:
    """Parse a sysfs cpulist ('0-3,8,10-11') into cpu ids."""
    out: List[int] = []
    for part in text.strip().split(","):
        if not part:
            continue
        if "-" in part:
            lo, hi = part.split("-", 1)
            out.extend(range(int(lo), int(hi) + 1))
        else:
            out.append(int(part))
    return out


class CpuInfo:
    __slots__ = ("cpu", "core", "package", "numa")

    def __init__(self, cpu: int, core: int, package: int,
                 numa: int) -> None:
        self.cpu = cpu
        self.core = core
        self.package = package
        self.numa = numa

    def __repr__(self) -> str:
        return (f"CpuInfo(cpu={self.cpu}, core={self.core}, "
                f"pkg={self.package}, numa={self.numa})")


class Topology:
    """One host's hardware layout (the hwloc topology object analog)."""

    def __init__(self, cpus: List[CpuInfo],
                 numa_nodes: Dict[int, List[int]]) -> None:
        self.cpus = cpus
        self.numa_nodes = numa_nodes  # numa id -> cpu ids

    # -- queries (hwloc_get_nbobjs_by_type analogs) --------------------
    @property
    def ncpus(self) -> int:
        return len(self.cpus)

    @property
    def ncores(self) -> int:
        return len({(c.package, c.core) for c in self.cpus})

    @property
    def npackages(self) -> int:
        return len({c.package for c in self.cpus})

    @property
    def nnuma(self) -> int:
        return max(1, len(self.numa_nodes))

    def cpus_of_numa(self, numa: int) -> List[int]:
        return self.numa_nodes.get(numa, [c.cpu for c in self.cpus])

    def numa_of_cpu(self, cpu: int) -> int:
        for c in self.cpus:
            if c.cpu == cpu:
                return max(0, c.numa)
        return 0

    def core_groups(self) -> List[List[int]]:
        """cpu ids grouped by physical core (SMT siblings together),
        in core order — the bind-to-core unit."""
        groups: Dict[Tuple[int, int], List[int]] = {}
        for c in self.cpus:
            groups.setdefault((c.package, c.core), []).append(c.cpu)
        return [groups[k] for k in sorted(groups)]

    def summary(self) -> str:
        return (f"{self.npackages} package(s) x {self.ncores} core(s) "
                f"/ {self.ncpus} cpu(s), {self.nnuma} NUMA node(s)")


def detect() -> Topology:
    """Detect this host's topology from sysfs; degrade gracefully to
    a flat cpu_count model (the hwloc discover entry point analog)."""
    cpus: List[CpuInfo] = []
    base = "/sys/devices/system/cpu"
    for d in sorted(glob.glob(os.path.join(base, "cpu[0-9]*"))):
        try:
            cpu = int(os.path.basename(d)[3:])
        except ValueError:
            continue
        topo = os.path.join(d, "topology")
        core = _read_int(os.path.join(topo, "core_id"), cpu)
        pkg = _read_int(os.path.join(topo, "physical_package_id"), 0)
        numa = -1
        for nd in glob.glob(os.path.join(d, "node[0-9]*")):
            numa = int(os.path.basename(nd)[4:])
            break
        cpus.append(CpuInfo(cpu, core, max(0, pkg), numa))
    if not cpus:
        cpus = [CpuInfo(i, i, 0, 0)
                for i in range(os.cpu_count() or 1)]
    numa_nodes: Dict[int, List[int]] = {}
    for nd in glob.glob("/sys/devices/system/node/node[0-9]*"):
        try:
            nid = int(os.path.basename(nd)[4:])
            with open(os.path.join(nd, "cpulist")) as fh:
                numa_nodes[nid] = _parse_cpulist(fh.read())
        except (OSError, ValueError):
            continue
    if not numa_nodes:
        numa_nodes = {0: [c.cpu for c in cpus]}
    return Topology(cpus, numa_nodes)


_topology: Optional[Topology] = None


def topology() -> Topology:
    global _topology
    if _topology is None:
        _topology = detect()
    return _topology


# -- device locality (the hwloc PCI/accelerator tree analog) -----------

def device_order_for_locality(devices) -> List:
    """Order local accelerator devices so consecutive local ranks own
    ICI NEIGHBORS: on real TPUs ``device.coords`` is the chip's torus
    position, and a lexicographic snake over the torus keeps rank i
    and rank i+1 one ICI hop apart (the treematch/mindist idea applied
    to the chip interconnect instead of PCI distance)."""
    def key(d):
        coords = getattr(d, "coords", None)
        if coords is None:
            return (0,) * 3 + (getattr(d, "id", 0),)
        # snake order: reverse odd rows so adjacent indices stay
        # physically adjacent on the torus
        c = list(coords)
        if len(c) >= 2 and c[-2] % 2 == 1:
            c[-1] = -c[-1]
        return tuple(c) + (getattr(d, "id", 0),)
    return sorted(devices, key=key)


# -- binding (the orte/mca/rtc/hwloc analog) ---------------------------

def bind_policy() -> str:
    return os.environ.get("TPUMPI_BIND", "none")


def apply_binding(local_rank: int,
                  policy: Optional[str] = None) -> Optional[List[int]]:
    """Bind the calling rank per policy; returns the applied cpuset
    (None = unbound).  Policies (ref: rtc_hwloc.c set of bindings):

      * ``core``: local rank r -> physical core r % ncores (all its
        SMT siblings);
      * ``numa``: local rank r -> every cpu of NUMA node
        r % nnuma (rank spreads round-robin over NUMA domains);
      * ``none``: leave the OS placement.
    """
    policy = policy or bind_policy()
    if policy in ("", "none"):
        return None
    if not hasattr(os, "sched_setaffinity"):
        return None
    topo = topology()
    if policy == "core":
        groups = topo.core_groups()
        cpuset = groups[local_rank % len(groups)]
    elif policy == "numa":
        numa_ids = sorted(topo.numa_nodes)
        nid = numa_ids[local_rank % len(numa_ids)]
        cpuset = topo.cpus_of_numa(nid)
    else:
        raise ValueError(
            f"unknown bind policy {policy!r} (core|numa|none)")
    try:
        os.sched_setaffinity(0, cpuset)
    except OSError:
        return None
    return cpuset
