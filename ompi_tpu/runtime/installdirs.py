"""installdirs: where the installation's pieces live.

Re-design of opal/mca/installdirs (ref: installdirs.h:74-87 — a
component stack layering configure-time defaults under env-var and
config overrides, consumed by show_help/paths/tools).  A Python
package's layout collapses the component stack to: package-derived
defaults, overridden by ``TPUMPI_<FIELD>`` environment variables
(the installdirs/env component's contract).

    from ompi_tpu.runtime import installdirs
    installdirs.get("prefix")    # repo/venv root of the install
    installdirs.expand("${datadir}/help")  # ${field} interpolation
"""

from __future__ import annotations

import os
import sys
from typing import Dict


def _defaults() -> Dict[str, str]:
    import ompi_tpu

    pkgdir = os.path.dirname(os.path.abspath(ompi_tpu.__file__))
    prefix = os.path.dirname(pkgdir)
    return {
        "prefix": prefix,
        "bindir": os.path.dirname(os.path.abspath(sys.executable)),
        "libdir": pkgdir,
        "includedir": os.path.join(prefix, "native"),
        "datadir": os.path.join(pkgdir, "util"),
        "sysconfdir": os.path.join(prefix, "etc"),
        "localstatedir": os.environ.get("TMPDIR", "/tmp"),
        "pkglibdir": os.path.join(prefix, "native"),
        "docdir": os.path.join(prefix, "docs"),
    }


def _raw_dirs() -> Dict[str, str]:
    out = {}
    for field, default in _defaults().items():
        out[field] = os.environ.get(f"TPUMPI_{field.upper()}", default)
    return out


def all_dirs() -> Dict[str, str]:
    """Every field, env overrides applied (TPUMPI_PREFIX etc) and
    ${field} references expanded — an override may reference other
    fields ('${prefix}/share'), so consumers always get a usable
    path."""
    dirs = _raw_dirs()
    for _ in range(4):
        changed = False
        for field, value in dirs.items():
            for ref, rv in dirs.items():
                token = "${" + ref + "}"
                if token in value and ref != field:
                    value = value.replace(token, rv)
            if value != dirs[field]:
                dirs[field] = value
                changed = True
        if not changed:
            break
    return dirs


def get(field: str) -> str:
    dirs = all_dirs()
    if field not in dirs:
        raise KeyError(
            f"unknown installdirs field {field!r} "
            f"(have: {', '.join(sorted(dirs))})")
    return dirs[field]


def expand(template: str) -> str:
    """${field} interpolation (the opal_install_dirs_expand
    contract)."""
    out = template
    dirs = all_dirs()  # already fully expanded
    for field, value in dirs.items():
        out = out.replace("${" + field + "}", value)
    return out
