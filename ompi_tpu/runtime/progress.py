"""Progress engine: the framework's hot polling loop.

Re-design of opal_progress (ref: opal/runtime/opal_progress.c:183-243)
plus the wait_sync completion primitive used by MPI_Wait
(ref: opal/threads/wait_sync.h:27,40,79-82).

Every rank owns one ``Progress``.  Transports and nonblocking
collective schedules register callbacks; blocking waits spin on
``progress()``.  High-priority callbacks fire every call; low-priority
callbacks every 8th call (the reference's opal_progress_lp_call_ratio
idea).  An optional idle yield keeps oversubscribed thread-ranks and
oversubscribed local processes fair, mirroring opal_progress_yield.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from ompi_tpu.mca.params import registry

_yield_var = registry.register(
    "opal", "progress", "yield_when_idle", True, bool,
    help="Call sched_yield (time.sleep(0)) when a progress sweep "
         "finds no events")
_lp_ratio_var = registry.register(
    "opal", "progress", "lp_call_ratio", 8, int,
    help="Low-priority callbacks run every Nth progress call")

import os as _os

# conservative import-time default: local ranks on THIS host vs local
# cores (multi-host jobs export TPUMPI_LOCAL_SIZE per node).  mpi_init
# refines per-state once the real local-rank count is known — env vars
# can't see thread-rank worlds (run_ranks, hostrun app shells).
_OVERSUBSCRIBED = (
    int(_os.environ.get("TPUMPI_LOCAL_SIZE",
                        _os.environ.get("TPUMPI_SIZE", "1")))
    > (_os.cpu_count() or 1))


class Progress:
    def __init__(self) -> None:
        self._callbacks: List[Callable[[], int]] = []
        self._lp_callbacks: List[Callable[[], int]] = []
        # immutable snapshots of the two lists, rebuilt on (un)register.
        # The hot sweep iterates these: no per-sweep list() copy (one
        # less allocation per sweep — Progress.progress is under the
        # hotpath audit), and mutation during a sweep stays safe
        # because the tuple being iterated can't change underneath us.
        self._cbs: tuple = ()
        self._lp_cbs: tuple = ()
        self._counter = 0
        self._lock = threading.Lock()
        # armed by the ft watcher (runtime/ft.py): the next progress
        # sweep raises it out of whatever blocking wait the rank is
        # parked in — the only way to interrupt a collective whose
        # peers died.  Recovery disarms before rebuilding.
        self.interrupt: Optional[BaseException] = None
        # finalize teardown sets this: a JobRecovery armed by the
        # watcher after the app's last collective must not escape
        # MPI_Finalize as an unrelated error — there is nothing left
        # to recover (ADVICE r5 #5).  Once set, armed interrupts are
        # discarded.
        self.suppress_interrupts = False
        # checkpoint writes bump this: the interrupt stays ARMED but
        # is not raised until the counter drops back to zero, so a
        # recovery signal can never tear a half-written checkpoint.
        self.defer_interrupts = 0
        self.oversubscribed = _OVERSUBSCRIBED
        # Doorbell peers ring when they enqueue work for this rank, so
        # a rank parked in WaitSync wakes immediately instead of
        # polling (the wait_sync condvar signal in the reference).
        self.doorbell = threading.Event()
        # poll_mode: at least one transport is poll-only (shm rings,
        # tcp sockets across processes) — nobody can ring the
        # doorbell, so blocked waits must keep polling with short
        # backoff instead of parking.
        self.poll_mode = False
        # Idle selector: transports register kernel-wakeable fds (shm
        # doorbell FIFOs, tcp sockets) so an idle rank BLOCKS in
        # select() and the kernel schedules it the instant a peer
        # enqueues work — the cross-process analog of the reference's
        # libevent-blocking opal_progress when no btl needs polling.
        # Critical on oversubscribed hosts: sched_yield spinning burns
        # whole CFS quanta (~ms) before the rank holding our message
        # runs; an fd wakeup context-switches in ~10 us.
        self._idle_sel = None
        self._idle_drains: dict = {}
        self._wake_wfd = -1  # self-pipe write end (thread wakeups)
        # park hooks: transports publish "this rank is parked" so
        # senders skip the doorbell syscall (and its wake-preemption)
        # while we're awake and polling anyway (futex-style protocol)
        self._park_set: list = []
        self._park_clear: list = []
        # finalize hooks: subsystems with pending deferred work (fused
        # device collectives, the device dispatcher queue) flush here.
        # mpi_finalize runs them BEFORE the finalize fence so a flush
        # that needs a cross-rank rendezvous still has live peers.
        self._finalize_hooks: List[Callable[[], None]] = []
        # span tracer (ompi_tpu/trace): set by mpi_init when
        # trace_enable; every sweep then feeds the progress-tick
        # latency histogram.  None = one is-None check per sweep.
        self.tracer = None
        # telemetry scraper (ompi_tpu/obs): set by obs.attach when
        # obs_scrape_interval_ms > 0 and a tracer is on; its tick
        # snapshots the latency histograms into a buffer the DVM
        # metrics RPC reads without stopping this rank.  Ticked only
        # on the tracer's SAMPLED sweeps with the already-read
        # timestamp, so scrape-on adds no clock reads per sweep.
        self.obs = None
        # fleet controller (ompi_tpu/serve): set by the DVM pool on
        # resident session ranks; ticks on the same sampled sweeps as
        # the scraper (one extra is-None check), so control decisions
        # react at traffic speed while jobs run — the hb loop covers
        # the idle pool, where no rank-thread sweeps.
        self.ctrl = None

    def deferred_interrupts(self):
        """Context manager: hold any armed ft interrupt until exit.
        Nestable; the pending exception fires on the first progress
        sweep after the outermost exit."""
        import contextlib

        @contextlib.contextmanager
        def _hold():
            self.defer_interrupts += 1
            try:
                yield
            finally:
                self.defer_interrupts -= 1
        return _hold()

    def register_park_hooks(self, set_cb, clear_cb) -> None:
        self._park_set.append(set_cb)
        self._park_clear.append(clear_cb)

    def unregister_park_hooks(self, set_cb, clear_cb) -> None:
        """Transports must remove their hooks at finalize: a stale
        hook dereferences freed transport state on any later idle
        park."""
        if set_cb in self._park_set:
            self._park_set.remove(set_cb)
        if clear_cb in self._park_clear:
            self._park_clear.remove(clear_cb)

    def register_idle_fd(self, fd: int, drain: Callable[[], None] | None = None) -> None:
        import selectors
        if self._idle_sel is None:
            self._idle_sel = selectors.DefaultSelector()
        try:
            self._idle_sel.register(fd, selectors.EVENT_READ)
        except KeyError:
            # stale entry for a reused fd number (a transport socket
            # closed without unregistering — injected sever): replace
            # it, and drop the dead owner's drain hook
            try:
                self._idle_sel.unregister(fd)
                self._idle_sel.register(fd, selectors.EVENT_READ)
            except (KeyError, ValueError, OSError):
                return
            self._idle_drains.pop(fd, None)
        except (ValueError, OSError):
            return
        if drain is not None:
            self._idle_drains[fd] = drain

    def unregister_idle_fd(self, fd: int) -> None:
        if self._idle_sel is not None:
            try:
                self._idle_sel.unregister(fd)
            except (KeyError, ValueError, OSError):
                pass
        self._idle_drains.pop(fd, None)

    def enable_thread_wakeup(self) -> None:
        """Self-pipe so same-process threads (inproc btl) can wake a
        rank parked in idle_wait."""
        if self._wake_wfd >= 0:
            return
        import os
        r, w = os.pipe()
        os.set_blocking(r, False)
        os.set_blocking(w, False)
        self._wake_wfd = w
        self.register_idle_fd(r, drain=lambda: self._drain_pipe(r))

    def _drain_pipe(self, fd: int) -> None:
        import os
        try:
            while os.read(fd, 4096):
                pass
        except (BlockingIOError, OSError):
            pass

    def idle_wait(self, timeout: float) -> None:
        """Block until a registered fd becomes readable (or timeout).
        Drains doorbell bytes; the caller re-sweeps progress()."""
        sel = self._idle_sel
        if sel is None or not sel.get_map():
            time.sleep(min(timeout, 0.0002))
            return
        if self._park_set:
            # publish parked BEFORE the final sweep: a sender that
            # pushes after our sweep will see the flag and ring the
            # doorbell; one that pushed before is caught by the sweep
            for cb in self._park_set:
                cb()
            if self.progress():
                for cb in self._park_clear:
                    cb()
                return
        try:
            for key, _ in sel.select(timeout):
                drain = self._idle_drains.get(key.fd)
                if drain is not None:
                    drain()
        finally:
            for cb in self._park_clear:
                cb()

    @property
    def has_idle_fds(self) -> bool:
        return self._idle_sel is not None and bool(self._idle_sel.get_map())

    def wakeup(self) -> None:
        self.doorbell.set()
        if self._wake_wfd >= 0:
            import os
            try:
                os.write(self._wake_wfd, b"\x01")
            except (BlockingIOError, OSError):
                pass

    def register_finalize_hook(self, cb: Callable[[], None]) -> None:
        """Idempotent: re-registering the same callable is a no-op."""
        with self._lock:
            if cb not in self._finalize_hooks:
                self._finalize_hooks.append(cb)

    def run_finalize_hooks(self) -> None:
        """Run and clear all finalize hooks.  Every hook runs even if
        an earlier one raises; the first error is re-raised after."""
        with self._lock:
            hooks, self._finalize_hooks = self._finalize_hooks, []
        first: Optional[BaseException] = None
        for cb in hooks:
            try:
                cb()
            except BaseException as e:  # noqa: BLE001
                if first is None:
                    first = e
        if first is not None:
            raise first

    def register(self, cb: Callable[[], int], low_priority: bool = False) -> None:
        with self._lock:
            if low_priority:
                self._lp_callbacks.append(cb)
            else:
                self._callbacks.append(cb)
            self._snapshot()

    def unregister(self, cb: Callable[[], int]) -> None:
        with self._lock:
            if cb in self._callbacks:
                self._callbacks.remove(cb)
            if cb in self._lp_callbacks:
                self._lp_callbacks.remove(cb)
            self._snapshot()

    def _snapshot(self) -> None:
        # caller holds self._lock
        self._cbs = tuple(self._callbacks)
        self._lp_cbs = tuple(self._lp_callbacks)

    def progress(self) -> int:
        """One sweep; returns number of events completed.

        Never yields or sleeps: a sweep must cost microseconds so
        blocking loops can spin a few times then park (idle_tick /
        WaitSync).  An implicit sched_yield here costs a whole CFS
        quantum (~200 us measured) per call on oversubscribed hosts.
        """
        if self.interrupt is not None:
            if self.suppress_interrupts:
                self.interrupt = None
            elif not self.defer_interrupts:
                exc = self.interrupt
                self.interrupt = None
                raise exc
        tr = self.tracer
        if tr is not None:
            # SAMPLED tick timing (1 in 16): a blocked rank spins this
            # loop thousands of times a second, and two clock reads
            # per sweep measurably slow every other rank on a shared
            # core.  The histogram stays representative; the sweeps it
            # skips are statistically identical to the ones it keeps.
            _t0 = time.perf_counter_ns() if (self._counter & 15) == 0 \
                else 0
        self._counter += 1
        events = 0
        for cb in self._cbs:
            events += cb()
        if self._lp_cbs and self._counter % max(1, _lp_ratio_var.value) == 0:
            for cb in self._lp_cbs:
                events += cb()
        if tr is not None and _t0:
            # the scrape tick rides 1 in 16 of the SAMPLED sweeps
            # (1 in 256 overall: _t0 is taken when the pre-increment
            # counter & 15 == 0, so & 255 == 1 here picks every 16th
            # of those), reusing the timestamp already read above.
            # Even a bound method call per sampled sweep is measurable
            # on a hot p2p spin loop; at 1-in-256 the whole scrape
            # path costs well under the 5% budget while still
            # checking the interval every few hundred microseconds.
            # Placed before the tick-end read so a refresh's copy
            # cost lands in the progress_tick histogram the overhead
            # probe judges.
            if (self._counter & 255) == 1:
                obs = self.obs
                if obs is not None:
                    obs.tick(_t0)
                ctrl = self.ctrl
                if ctrl is not None:
                    ctrl.tick(_t0)
            tr.tick_ns(time.perf_counter_ns() - _t0)
        return events

    def idle_tick(self, timeout: float = 0.002) -> None:
        """Call after a zero-event sweep in a blocking spin loop:
        parks on the idle selector when transports registered wakeup
        fds, else yields the core (opal_progress_yield analog)."""
        if self.has_idle_fds:
            self.idle_wait(timeout)
        elif _yield_var.value:
            time.sleep(0)


class WaitSync:
    """Completion object a blocking wait parks on.

    The reference spins on opal_progress() single-threaded and blocks
    on a pthread condvar under MPI_THREAD_MULTIPLE
    (ref: opal/threads/wait_sync.c:84).  Here completions may arrive
    from a peer rank-thread (inproc btl) or from our own progress
    sweeps, so we spin on progress with a short adaptive backoff and
    an Event for cross-thread wakeups.
    """

    __slots__ = ("_count",)

    def __init__(self, count: int = 1) -> None:
        # A bare counter, no Event: completions always run in the
        # owning rank's thread (actor model), so the waiter observes
        # the decrement directly; cross-thread producers wake us via
        # the progress doorbell / idle fds, never this object.  Keeps
        # request allocation to one int (requests are per-message).
        self._count = count

    def signal(self, n: int = 1) -> None:
        self._count -= n

    @property
    def done(self) -> bool:
        return self._count <= 0

    def wait(self, progress: Progress, timeout: float | None = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        park = 2 if progress.oversubscribed else 50
        while self._count > 0:
            if progress.progress() == 0:
                spins += 1
                if progress.has_idle_fds:
                    # kernel-wakeable transports: park in select()
                    # after a short spin; peers ring the fd doorbell
                    # the instant they enqueue (essential on
                    # oversubscribed hosts where yield-spinning
                    # burns whole scheduler quanta)
                    if spins > park:
                        progress.idle_wait(0.002)
                        spins = 0
                elif progress.poll_mode:
                    # poll-only transports.  Oversubscribed hosts
                    # (ranks > cores) need aggressive yielding or every
                    # blocked rank burns a scheduler timeslice before
                    # the rank holding our message runs (the reference
                    # auto-sets yield_when_idle for oversubscription).
                    if progress.oversubscribed:
                        if spins > 4:
                            time.sleep(0)  # sched_yield to peers
                    elif spins > 5000:
                        time.sleep(0.0002)
                        spins = 0
                elif progress.oversubscribed and spins > 4:
                    # thread-ranks sharing too few cores: park early on
                    # the doorbell instead of spinning down a shared
                    # core (the convoy shows up as multi-ms latency
                    # spikes on small messages)
                    progress.doorbell.clear()
                    if progress.progress() == 0 and self._count > 0:
                        progress.doorbell.wait(0.005)
                    spins = 0
                elif spins > 200:
                    # Park on the doorbell; peers ring it when they
                    # enqueue frags for us (cross-thread wakeup).
                    progress.doorbell.clear()
                    if progress.progress() == 0 and self._count > 0:
                        progress.doorbell.wait(0.01)
                    spins = 0
            else:
                spins = 0
            if deadline is not None and time.monotonic() > deadline:
                return self._count <= 0
        return True
