"""Progress engine: the framework's hot polling loop.

Re-design of opal_progress (ref: opal/runtime/opal_progress.c:183-243)
plus the wait_sync completion primitive used by MPI_Wait
(ref: opal/threads/wait_sync.h:27,40,79-82).

Every rank owns one ``Progress``.  Transports and nonblocking
collective schedules register callbacks; blocking waits spin on
``progress()``.  High-priority callbacks fire every call; low-priority
callbacks every 8th call (the reference's opal_progress_lp_call_ratio
idea).  An optional idle yield keeps oversubscribed thread-ranks and
oversubscribed local processes fair, mirroring opal_progress_yield.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List

from ompi_tpu.mca.params import registry

_yield_var = registry.register(
    "opal", "progress", "yield_when_idle", True, bool,
    help="Call sched_yield (time.sleep(0)) when a progress sweep "
         "finds no events")
_lp_ratio_var = registry.register(
    "opal", "progress", "lp_call_ratio", 8, int,
    help="Low-priority callbacks run every Nth progress call")

import os as _os

# conservative import-time default: local ranks on THIS host vs local
# cores (multi-host jobs export TPUMPI_LOCAL_SIZE per node).  mpi_init
# refines per-state once the real local-rank count is known — env vars
# can't see thread-rank worlds (run_ranks, hostrun app shells).
_OVERSUBSCRIBED = (
    int(_os.environ.get("TPUMPI_LOCAL_SIZE",
                        _os.environ.get("TPUMPI_SIZE", "1")))
    > (_os.cpu_count() or 1))


class Progress:
    def __init__(self) -> None:
        self._callbacks: List[Callable[[], int]] = []
        self._lp_callbacks: List[Callable[[], int]] = []
        self._counter = 0
        self._lock = threading.Lock()
        self.oversubscribed = _OVERSUBSCRIBED
        # Doorbell peers ring when they enqueue work for this rank, so
        # a rank parked in WaitSync wakes immediately instead of
        # polling (the wait_sync condvar signal in the reference).
        self.doorbell = threading.Event()
        # poll_mode: at least one transport is poll-only (shm rings,
        # tcp sockets across processes) — nobody can ring the
        # doorbell, so blocked waits must keep polling with short
        # backoff instead of parking.
        self.poll_mode = False

    def wakeup(self) -> None:
        self.doorbell.set()

    def register(self, cb: Callable[[], int], low_priority: bool = False) -> None:
        with self._lock:
            if low_priority:
                self._lp_callbacks.append(cb)
            else:
                self._callbacks.append(cb)

    def unregister(self, cb: Callable[[], int]) -> None:
        with self._lock:
            if cb in self._callbacks:
                self._callbacks.remove(cb)
            if cb in self._lp_callbacks:
                self._lp_callbacks.remove(cb)

    def progress(self) -> int:
        """One sweep; returns number of events completed."""
        self._counter += 1
        events = 0
        for cb in list(self._callbacks):
            events += cb()
        if self._lp_callbacks and self._counter % max(1, _lp_ratio_var.value) == 0:
            for cb in list(self._lp_callbacks):
                events += cb()
        if events == 0 and _yield_var.value:
            time.sleep(0)
        return events


class WaitSync:
    """Completion object a blocking wait parks on.

    The reference spins on opal_progress() single-threaded and blocks
    on a pthread condvar under MPI_THREAD_MULTIPLE
    (ref: opal/threads/wait_sync.c:84).  Here completions may arrive
    from a peer rank-thread (inproc btl) or from our own progress
    sweeps, so we spin on progress with a short adaptive backoff and
    an Event for cross-thread wakeups.
    """

    __slots__ = ("_event", "_count")

    def __init__(self, count: int = 1) -> None:
        self._event = threading.Event()
        self._count = count

    def signal(self, n: int = 1) -> None:
        self._count -= n
        if self._count <= 0:
            self._event.set()

    @property
    def done(self) -> bool:
        return self._count <= 0

    def wait(self, progress: Progress, timeout: float | None = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        while not self._event.is_set():
            if progress.progress() == 0:
                spins += 1
                if progress.poll_mode:
                    # poll-only transports.  Oversubscribed hosts
                    # (ranks > cores) need aggressive yielding or every
                    # blocked rank burns a scheduler timeslice before
                    # the rank holding our message runs (the reference
                    # auto-sets yield_when_idle for oversubscription).
                    if progress.oversubscribed:
                        if spins > 4:
                            time.sleep(0)  # sched_yield to peers
                    elif spins > 5000:
                        time.sleep(0.0002)
                        spins = 0
                elif progress.oversubscribed and spins > 4:
                    # thread-ranks sharing too few cores: park early on
                    # the doorbell instead of spinning down a shared
                    # core (the convoy shows up as multi-ms latency
                    # spikes on small messages)
                    progress.doorbell.clear()
                    if progress.progress() == 0 and not self._event.is_set():
                        progress.doorbell.wait(0.005)
                    spins = 0
                elif spins > 200:
                    # Park on the doorbell; peers ring it when they
                    # enqueue frags for us (cross-thread wakeup).
                    progress.doorbell.clear()
                    if progress.progress() == 0 and not self._event.is_set():
                        progress.doorbell.wait(0.01)
                    spins = 0
            else:
                spins = 0
            if deadline is not None and time.monotonic() > deadline:
                return self._event.is_set()
        return True
