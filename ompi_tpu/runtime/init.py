"""MPI bring-up / teardown for one rank.

Mirrors the ompi_mpi_init sequence (ref: ompi/runtime/ompi_mpi_init.c:
rte init → frameworks open → pml select → modex fence → add_procs →
comm_world/self → coll select → final fence) and ompi_mpi_finalize.c's
reverse teardown.
"""

from __future__ import annotations

from typing import Optional

from ompi_tpu.btl import base as btl_base
from ompi_tpu.btl import inproc as _btl_inproc  # noqa: F401 (registers)
from ompi_tpu.btl import self_btl as _btl_self  # noqa: F401
from ompi_tpu.btl import shm as _btl_shm  # noqa: F401
from ompi_tpu.btl import tcp as _btl_tcp  # noqa: F401
from ompi_tpu.comm.communicator import (SESSION_CID_STRIDE, Communicator,
                                        Group)
from ompi_tpu.pml import ob1 as _pml_ob1
from ompi_tpu.pml import monitoring as _pml_monitoring
from .state import ProcState, clear_current, set_current


def mpi_init(state: ProcState, device=None) -> ProcState:
    import os

    set_current(state)
    state.device = device
    # span tracer attach (ompi_tpu/trace) BEFORE pml/coll selection so
    # every layer constructed below can cache state.tracer (None when
    # trace_enable is off — the whole hot-path cost)
    from ompi_tpu import trace as _trace
    _trace.attach(state)
    # online autotune attach rides DIRECTLY on the trace attach (it
    # force-attaches a tracer when trace_enable is off) so the pml/
    # coll layers below still cache a non-None state.tracer
    from ompi_tpu.coll import autotune as _autotune
    _autotune.attach(state)
    # debugger attach support (MPIR analog, ref: ompi/debuggers):
    # SIGUSR1 dumps every thread's stack to stderr so
    # ompi_tpu.tools.attach --stacks can show where a hung job is
    # stuck; binding (rtc/hwloc analog) applies TPUMPI_BIND
    try:
        import faulthandler
        import signal as _signal
        faulthandler.register(_signal.SIGUSR1, all_threads=True,
                              chain=True)
        # crash backtraces (SIGSEGV/SIGFPE/SIGABRT -> all-thread
        # dumps): the opal/mca/backtrace analog for native-code
        # faults in jax/XLA/our C++ ring
        faulthandler.enable(all_threads=True)
    except (ImportError, AttributeError, ValueError, OSError):
        pass  # non-main thread or unsupported platform
    from ompi_tpu.runtime import pstat as _pstat
    _pstat.register_pvars(state.rank)
    # telemetry plane: percentile gauges + flight recorder (idempotent
    # across looped worlds), and the scrape tick when enabled
    from ompi_tpu import obs as _obs
    _obs.attach(state)
    from ompi_tpu.runtime import topology as _topo
    _world = getattr(state.rte, "world", None)
    if _world is not None:
        # thread-rank: sched_setaffinity(0) binds the calling THREAD.
        # The binding index is the rank's position within its NODE
        # (TPUMPI_NODE_RANK_BASE), not within its shell — two shells
        # on one node must not overlap their core assignments
        node_base = int(os.environ.get(
            "TPUMPI_NODE_RANK_BASE",
            str(getattr(_world, "rank_base", 0))))
        _local_rank = state.rank - node_base
    else:
        # process-rank: the launcher exports the rank's index WITHIN
        # its node (never the global rank — that would misbind every
        # node after the first)
        _local_rank = int(os.environ.get("TPUMPI_LOCAL_RANK", "0"))
    try:
        _topo.apply_binding(_local_rank)
    except (ValueError, OSError):
        pass
    # refine the oversubscription hint with the true local-rank count:
    # thread-rank worlds (inproc/hybrid) know it exactly; process-ranks
    # read the launcher's TPUMPI_LOCAL_SIZE (ref: the reference
    # auto-enables yield_when_idle when ranks exceed cores)
    world = getattr(state.rte, "world", None)
    nlocal = getattr(world, "nlocal", None) or (
        world.size if world is not None
        else int(os.environ.get("TPUMPI_LOCAL_SIZE", "1")))
    state.progress.oversubscribed = nlocal > (os.cpu_count() or 1)
    # ULFM failure-mitigation state BEFORE pml selection so the pml
    # can cache state.ulfm (None when mpi_ft_ulfm is off — the same
    # one-is-None-check contract as the tracer)
    from ompi_tpu.ft import ulfm as _ulfm
    _ulfm.attach(state)
    # 1. select the single pml engine (ref: ompi_mpi_init.c:640),
    # optionally interposed by pml/monitoring
    comp, pml_cls = _pml_ob1.pml_framework.select_one(state)
    from ompi_tpu.pml import vprotocol as _pml_vprotocol
    state.pml = _pml_vprotocol.maybe_wrap(
        _pml_monitoring.maybe_wrap(pml_cls(state), state), state)
    # live recovery: a restarted rank joins at a bumped epoch
    # (runtime/ft.py); post-recovery cross-process traffic rides tcp
    # only — the shm rings of a pre-failure epoch cannot be made
    # stale-byte-safe, so shm stays out of an epoch>0 world
    state.ft_epoch = int(os.environ.get("TPUMPI_FT_EPOCH", "0"))
    # self-healing respawn (ft/respawn): a replacement PROCESS carries
    # TPUMPI_RESPAWN=1 and the epoch its failure opened — it must run
    # the rejoin protocol before doing real work, and it must never
    # re-arm the fault that killed its predecessor.  Thread-world
    # replacements get these attrs set by the driver before mpi_init
    # (threads share the environment, so the env flag is a
    # process-rank signal only).
    if (not state.respawn_joining and os.environ.get("TPUMPI_RESPAWN")
            and getattr(state.rte, "kv", None) is not None):
        state.respawn_joining = True
        state.respawn_epoch = max(0, state.ft_epoch - 1)
    # 2. btl modules + endpoint wiring (modex happens inside init).
    # At a recovery epoch the shm COMPONENT is skipped outright — a
    # constructed-then-dropped module would have created rings,
    # registered callbacks and forced poll_mode for a transport the
    # epoch never uses
    modules = []
    for c in btl_base.btl_framework.components():
        if state.ft_epoch and getattr(c, "name", "") == "shm":
            continue
        modules += c.init_modules(state)
    state.btls = modules
    # publish our state for inproc peers + our device assignment for
    # the job (VERDICT r1 #2: device ids ride the modex so launchers /
    # future cross-host device planes can see the chip map), then
    # fence (modex sync #1, ref: ompi_mpi_init.c:654-661)
    world = getattr(state.rte, "world", None)
    if world is not None:
        world.states[state.rank] = state
    if device is not None:
        state.rte.modex_put("device_id", int(device.id))
    # node + cores ride the modex so collective algorithm selection
    # can be COMM-CONSISTENT about oversubscription (every member of
    # a comm must pick the same algorithm; local env hints diverge —
    # e.g. a dpm-spawned singleton vs its 8-rank parent)
    state.rte.modex_put("node_id", getattr(state.rte, "node_id", 0))
    state.rte.modex_put("cores", os.cpu_count() or 1)
    if state.ft_epoch and os.environ.get("FT_DEBUG"):
        import sys as _sys
        print(f"[ft-init r{state.rank}] entering fence 1 "
              f"(epoch {state.ft_epoch})", file=_sys.stderr, flush=True)
    state.rte.fence()
    if state.ft_epoch and os.environ.get("FT_DEBUG"):
        import sys as _sys
        print(f"[ft-init r{state.rank}] fence 1 passed",
              file=_sys.stderr, flush=True)
    endpoints = btl_base.wire_endpoints(state, modules)
    state.pml.add_procs(endpoints)
    # 3. predefined communicators: world cid 0, self cid 1.  The world
    # group is this JOB's rank block — a spawned job's world starts at
    # its universe base (dpm, ref: ompi/dpm)
    wbase = getattr(state.rte, "world_base", 0)
    wsize = getattr(state.rte, "world_size", state.size)
    # DVM-resident sessions carry a session cid band: the predefined
    # comms live at the band base, so even cid 0/1 are session-unique
    # across the pool (next_cid floors derived comms into the same
    # band; SESSION_CID_STRIDE keeps the session dimension disjoint
    # from respawn-epoch banding).  Ordinary jobs have band 0 — world
    # cid 0, self cid 1.
    band = state.cid_band * SESSION_CID_STRIDE
    state.comm_world = Communicator(state, band,
                                    Group(range(wbase, wbase + wsize)),
                                    name="MPI_COMM_WORLD")
    from ompi_tpu import attrs as _attrs
    _attrs.init_world_attrs(state.comm_world)
    state.comm_self = Communicator(state, band + 1, Group([state.rank]),
                                   name="MPI_COMM_SELF")
    # wire the predefined communicators' error handler EXPLICITLY
    # (mpi_errhandler_world_default; derived comms keep inheriting
    # from their parent) — the dispatch fallback for handler-less
    # objects resolves through comm_world, so this is the one place
    # the job default is installed
    from ompi_tpu import errhandler as _eh
    state.comm_world.errhandler = _eh.world_default()
    state.comm_self.errhandler = state.comm_world.errhandler
    # 4. collective module stacks are installed by Communicator
    # construction itself (coll_base_comm_select analog)
    # 5. final fence before returning (sync #2, ref: :833-838)
    state.rte.fence()
    state.initialized = True
    if os.environ.get("TPUMPI_FT_RECOVER"):
        # the launcher runs the recover errmgr policy: watch for
        # recovery epochs so a daemon loss interrupts blocking waits
        # instead of hanging them (runtime/ft.py)
        from ompi_tpu.runtime import ft as _ft
        _ft.start_watcher(state)
    if state.ulfm is not None:
        # ft_inject rank_kill: this rank is the victim — arm the
        # one-shot death timer (fires as a RankKilled interrupt out
        # of the next progress sweep)
        from ompi_tpu import ft_inject as _fi
        if ("rank_kill" in _fi.rank_faults(state.rank, state.size)
                and not state.respawn_joining):
            # a respawned replacement never re-arms its predecessor's
            # death — that would be an infinite kill/respawn loop
            _ulfm.arm_rank_kill(state, _fi.after_s())
        if os.environ.get("TPUMPI_ULFM"):
            # launcher runs the ulfm errmgr policy: consume job-wide
            # ulfm:note:<n> failure/revoke records from the KV store
            _ulfm.start_watcher(state)
    return state


def extend_universe(state: ProcState, new_size: int) -> None:
    """Make universe ranks [state.size, new_size) addressable: grow
    the endpoint table and let each btl prepare for the new peers
    (the dynamic-peer half of the reference's connect/accept
    MCA_PML_CALL(add_procs) path, ref: ompi/dpm/dpm.c)."""
    if new_size <= state.size:
        return
    old = state.size
    state.size = new_size
    for m in state.btls:
        ext = getattr(m, "extend", None)
        if ext is not None:
            ext(new_size)
    eps = list(state.pml.endpoints)
    for peer in range(old, new_size):
        reach = sorted((m for m in state.btls if m.reaches(peer)),
                       key=lambda m: -m.exclusivity)
        eps.append(btl_base.Endpoint(peer, reach) if reach else None)
    state.pml.add_procs(eps)


def mpi_finalize(state: ProcState) -> None:
    if state.finalized:
        return
    # past this point a JobRecovery interrupt has nothing to recover
    # and must not escape finalize as an unrelated error (ADVICE r5
    # #5); the watcher may still arm one mid-teardown, so suppression
    # is a standing flag, not a one-shot disarm
    state.progress.suppress_interrupts = True
    state.progress.interrupt = None
    # flush deferred work (fused device collectives, dispatcher queue)
    # BEFORE the fence: a flush may need one last cross-rank
    # rendezvous, so peers must still be alive and symmetric here
    state.progress.run_finalize_hooks()
    # mpisync clock-offset measurement BEFORE the fence (it is itself
    # collective — Barrier/Send/Recv/Bcast need a live pml): embeds
    # the offset table into every rank's trace dump so traceview /
    # critpath align timelines without a hand-plumbed --sync file
    from ompi_tpu import trace as _trace
    _trace.sync_state(state)
    # pml/monitoring traffic-matrix dump BEFORE the fence: every
    # rank's .prof file must exist by the time the fence releases
    # rank 0 to aggregate them (profile2mat semantics)
    _pml_monitoring.finalize_dump(state)
    # barrier, then teardown in reverse (ref: ompi_mpi_finalize.c:101)
    state.rte.fence()
    _pml_monitoring.finalize_aggregate(state)
    if state.ulfm is not None:
        # store hygiene: drop this job's ULFM notes and put-once
        # tickets so looped worlds (pytest re-entry, warm pools) never
        # replay a finished run's failure records.  After the fence —
        # every rank is in finalize, nobody consumes notes anymore —
        # and before rte.finalize closes the KV client.  Idempotent,
        # so every rank calling it is fine.
        from ompi_tpu.ft import ulfm as _fin_ulfm
        _fin_ulfm.purge_store(state)
    for m in state.btls:
        m.finalize()
    # autotune deregistration before the tracer dump: the process
    # tuner must stop reading this world's histograms
    from ompi_tpu.coll import autotune as _autotune
    _autotune.detach(state)
    state.rte.finalize()
    # stop the telemetry scrape tick for this world (the recorder and
    # registered gauges are process-scoped and survive into the next
    # looped world)
    from ompi_tpu import obs as _obs_fin
    _obs_fin.detach(state)
    # trace dump LAST: teardown spans (flush rendezvous, btl close)
    # are part of the timeline (_trace imported above for sync_state)
    _trace.dump_state(state)
    state.finalized = True
    clear_current(state)
