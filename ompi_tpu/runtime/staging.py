"""runtime/staging: the shared host<->device staging discipline.

Hoisted from osc/device.py (the zero-copy DMA path) so every
subsystem that stages host memory into device buffers — one-sided
windows, the coll plan executor's pack bypass, and the pml, should it
grow a staged eager path — shares ONE alignment rule, ONE runtime
aliasing probe and ONE mirror pool, instead of growing private copies
that drift.

Three pieces:

* ``STAGE_ALIGN`` / ``aligned_empty``: the CPU runtime aliases a
  64-byte-aligned host buffer on ``device_put`` instead of copying it;
  numpy only guarantees 16-byte alignment, so staging buffers are
  carved at the right offset out of an oversized allocation.
* ``runtime_zero_copy()``: probes ONCE per process whether
  ``device_put`` of an aligned host buffer ALIASES it (the CPU runtime
  does; an accelerator with discrete HBM copies).  Write-through
  mirrors, deferred-decouple puts and the coll pack bypass are only
  sound when it does; otherwise callers degrade to compose-and-upload.
* ``MirrorPool``: a bounded free-list of displaced staging buffers, so
  steady-state re-mirroring (osc decoupling copies, repeated ragged
  packs) never pays fresh-page faults.
"""

from __future__ import annotations

import threading
import warnings
from typing import List, Optional

import numpy as np

# donation is a no-op on the CPU backend (and on a zero-copy runtime
# the donated global may alias host mirrors); the warning would fire
# once per compiled kernel in every tier-1 run
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")

#: staging alignment for DMA-path uploads: the CPU runtime aliases a
#: 64-byte-aligned host buffer on device_put instead of copying it
STAGE_ALIGN = 64


def aligned_empty(nbytes: int) -> np.ndarray:
    """Uninitialized uint8 staging buffer whose data pointer is
    STAGE_ALIGN-aligned (numpy only guarantees 16)."""
    raw = np.empty(nbytes + STAGE_ALIGN, dtype=np.uint8)
    off = (-raw.ctypes.data) % STAGE_ALIGN
    return raw[off: off + nbytes]


_zero_copy: Optional[bool] = None
_probe_lock = threading.Lock()


def runtime_zero_copy() -> bool:
    """Whether device_put of an aligned host buffer ALIASES it (the
    CPU runtime does; an accelerator with discrete HBM copies).
    Probed once per process by mutating the host buffer after the put
    and reading the device view back."""
    global _zero_copy
    if _zero_copy is None:
        with _probe_lock:
            if _zero_copy is None:
                import jax
                probe = aligned_empty(STAGE_ALIGN)
                probe[:] = 0
                arr = jax.device_put(probe)
                arr.block_until_ready()
                probe[0] = 1
                _zero_copy = bool(np.asarray(arr)[0] == 1)
    return _zero_copy


class MirrorPool:
    """Bounded free-list of displaced aligned staging buffers.

    ``take`` prefers a parked buffer of sufficient capacity (sliced to
    the requested span — slicing from offset 0 preserves alignment)
    and falls back to a fresh ``aligned_empty``; ``park`` keeps at
    most ``max_buffers`` around so a pathological caller cannot hoard
    host memory.  Contents of a taken buffer are UNDEFINED — callers
    overwrite before use, exactly as with ``aligned_empty``."""

    __slots__ = ("_free", "_max", "_lock")

    def __init__(self, max_buffers: int = 8) -> None:
        self._free: List[np.ndarray] = []
        self._max = max(1, int(max_buffers))
        self._lock = threading.Lock()

    def take(self, nbytes: int) -> np.ndarray:
        with self._lock:
            for i in range(len(self._free) - 1, -1, -1):
                buf = self._free[i]
                if buf.nbytes >= nbytes:
                    del self._free[i]
                    return buf[:nbytes]
        return aligned_empty(nbytes)

    def park(self, buf: Optional[np.ndarray]) -> None:
        if buf is None:
            return
        with self._lock:
            if len(self._free) < self._max:
                self._free.append(buf)
