"""Runtime-environment abstraction: the contract the MPI layer needs
from any runtime.

This mirrors the reference's rte interface spec exactly
(ref: ompi/mca/rte/rte.h:35-145): process naming, modex put/get
(business-card exchange), barrier/fence, abort, and init/finalize.
Implementations:

  * InprocRTE — thread-ranks inside one host process (the TPU-host
    model; also the fast test harness).  Modex is a shared dict,
    fence a threading.Barrier.
  * EnvRTE — process-ranks launched by ompi_tpu.tools.mpirun; modex
    and fence go through the launcher's KV store over TCP (the
    PMIx-like put/commit/fence, ref: opal/mca/pmix usage in
    ompi_mpi_init.c:654-661).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional


class RTE:
    rank: int
    size: int

    def modex_put(self, key: str, value: Any) -> None:
        raise NotImplementedError

    def modex_get(self, peer: int, key: str) -> Any:
        raise NotImplementedError

    def fence(self) -> None:
        raise NotImplementedError

    def abort(self, code: int, msg: str = "") -> None:
        raise NotImplementedError

    def finalize(self) -> None:
        pass


class InprocWorld:
    """Shared state for an N-thread-rank world on one host."""

    def __init__(self, size: int) -> None:
        self.size = size
        self.modex: Dict[tuple, Any] = {}
        self.modex_cv = threading.Condition()
        self.barrier = threading.Barrier(size)
        self.states: List[Any] = [None] * size  # ProcState per rank
        self.aborted: Optional[tuple] = None
        # shared rendezvous objects for device collectives (coll/tpu,
        # coll/hbm), keyed by communicator cid
        self.shared: Dict[Any, Any] = {}
        self.shared_lock = threading.Lock()

    def make_rte(self, rank: int) -> "InprocRTE":
        return InprocRTE(self, rank)


class InprocRTE(RTE):
    def __init__(self, world: InprocWorld, rank: int) -> None:
        self.world = world
        self.rank = rank
        self.size = world.size

    def modex_put(self, key: str, value: Any) -> None:
        with self.world.modex_cv:
            self.world.modex[(self.rank, key)] = value
            self.world.modex_cv.notify_all()

    def modex_get(self, peer: int, key: str) -> Any:
        with self.world.modex_cv:
            while (peer, key) not in self.world.modex:
                if self.world.aborted:
                    raise RuntimeError(f"job aborted: {self.world.aborted}")
                if not self.world.modex_cv.wait(timeout=30):
                    raise TimeoutError(
                        f"modex_get({peer},{key}) timed out")
            return self.world.modex[(peer, key)]

    def fence(self) -> None:
        self.world.barrier.wait(timeout=60)

    def abort(self, code: int, msg: str = "") -> None:
        self.world.aborted = (self.rank, code, msg)
        with self.world.modex_cv:
            self.world.modex_cv.notify_all()
        raise SystemExit(code)


class EnvRTE(RTE):
    """Process-rank runtime: identity from the environment set by the
    launcher (ompi_tpu.tools.mpirun), modex/fence through its KV
    server (ref: orte/mca/ess env component + pmix client)."""

    def __init__(self) -> None:
        import os

        from .kvstore import KVClient  # noqa: PLC0415

        self.rank = int(os.environ["TPUMPI_RANK"])
        self.size = int(os.environ["TPUMPI_SIZE"])
        self.jobid = os.environ.get("TPUMPI_JOBID", "job0")
        self.node_id = int(os.environ.get("TPUMPI_NODE", "0"))
        self.session_dir = os.environ.get("TPUMPI_SESSION_DIR", "/tmp")
        self.kv = KVClient(os.environ["TPUMPI_KV_ADDR"])
        self._fence_count = 0

    def modex_put(self, key: str, value: Any) -> None:
        self.kv.put(f"modex:{self.rank}:{key}", value)

    def modex_get(self, peer: int, key: str) -> Any:
        return self.kv.get(f"modex:{peer}:{key}")

    def fence(self) -> None:
        self._fence_count += 1
        self.kv.fence(f"f{self._fence_count}")

    def abort(self, code: int, msg: str = "") -> None:
        import os
        import sys

        self.kv.abort(self.rank, code, msg)
        sys.stderr.write(f"[rank {self.rank}] MPI_Abort({code}): {msg}\n")
        sys.stderr.flush()
        os._exit(code)

    def finalize(self) -> None:
        self.kv.close()


def make_rte() -> RTE:
    """Bootstrap this process's runtime (ess component selection
    analog, ref: orte/mca/ess): launched by our mpirun → EnvRTE;
    standalone → singleton world of size 1."""
    import os

    if "TPUMPI_KV_ADDR" in os.environ:
        return EnvRTE()
    world = InprocWorld(1)
    return world.make_rte(0)
