"""Runtime-environment abstraction: the contract the MPI layer needs
from any runtime.

This mirrors the reference's rte interface spec exactly
(ref: ompi/mca/rte/rte.h:35-145): process naming, modex put/get
(business-card exchange), barrier/fence, abort, and init/finalize.
Implementations:

  * InprocRTE — thread-ranks inside one host process (the TPU-host
    model; also the fast test harness).  Modex is a shared dict,
    fence a threading.Barrier.
  * EnvRTE — process-ranks launched by ompi_tpu.tools.launch; modex
    and fence go through the launcher's KV store over TCP (the
    PMIx-like put/commit/fence, ref: opal/mca/pmix usage in
    ompi_mpi_init.c:654-661).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional


class RTE:
    rank: int
    size: int

    def modex_put(self, key: str, value: Any) -> None:
        raise NotImplementedError

    def modex_get(self, peer: int, key: str) -> Any:
        raise NotImplementedError

    def fence(self) -> None:
        raise NotImplementedError

    def abort(self, code: int, msg: str = "") -> None:
        raise NotImplementedError

    def finalize(self) -> None:
        pass


class InprocWorld:
    """Shared state for an N-thread-rank world on one host."""

    def __init__(self, size: int) -> None:
        self.size = size
        self.modex: Dict[tuple, Any] = {}
        self.modex_cv = threading.Condition()
        self.barrier = threading.Barrier(size)
        self.states: List[Any] = [None] * size  # ProcState per rank
        self.aborted: Optional[tuple] = None
        # shared rendezvous objects for device collectives (coll/tpu,
        # coll/hbm), keyed by communicator cid
        self.shared: Dict[Any, Any] = {}
        self.shared_lock = threading.Lock()

    def make_rte(self, rank: int) -> "InprocRTE":
        return InprocRTE(self, rank)


class InprocRTE(RTE):
    def __init__(self, world: InprocWorld, rank: int) -> None:
        self.world = world
        self.rank = rank
        self.size = world.size

    def modex_put(self, key: str, value: Any) -> None:
        with self.world.modex_cv:
            self.world.modex[(self.rank, key)] = value
            self.world.modex_cv.notify_all()

    def modex_get(self, peer: int, key: str) -> Any:
        with self.world.modex_cv:
            while (peer, key) not in self.world.modex:
                if self.world.aborted:
                    raise RuntimeError(f"job aborted: {self.world.aborted}")
                if not self.world.modex_cv.wait(timeout=30):
                    raise TimeoutError(
                        f"modex_get({peer},{key}) timed out")
            return self.world.modex[(peer, key)]

    def fence(self) -> None:
        self.world.barrier.wait(timeout=60)

    def abort(self, code: int, msg: str = "") -> None:
        self.world.aborted = (self.rank, code, msg)
        with self.world.modex_cv:
            self.world.modex_cv.notify_all()
        raise SystemExit(code)
