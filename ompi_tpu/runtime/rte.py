"""Runtime-environment abstraction: the contract the MPI layer needs
from any runtime.

This mirrors the reference's rte interface spec exactly
(ref: ompi/mca/rte/rte.h:35-145): process naming, modex put/get
(business-card exchange), barrier/fence, abort, and init/finalize.
Implementations:

  * InprocRTE — thread-ranks inside one host process (the TPU-host
    model; also the fast test harness).  Modex is a shared dict,
    fence a threading.Barrier.
  * EnvRTE — process-ranks launched by ompi_tpu.tools.mpirun; modex
    and fence go through the launcher's KV store over TCP (the
    PMIx-like put/commit/fence, ref: opal/mca/pmix usage in
    ompi_mpi_init.c:654-661).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Set

from ompi_tpu.mca.params import registry

_modex_timeout_var = registry.register(
    "rte", "base", "modex_timeout", 30.0, float,
    help="Seconds a modex_get waits for a peer's business card "
         "before failing (raise under debuggers / huge jobs)")
_fence_timeout_var = registry.register(
    "rte", "base", "fence_timeout", 60.0, float,
    help="Seconds a fence waits for all ranks before failing")


class RTE:
    rank: int
    size: int

    def modex_put(self, key: str, value: Any) -> None:
        raise NotImplementedError

    def modex_get(self, peer: int, key: str) -> Any:
        raise NotImplementedError

    def fence(self) -> None:
        raise NotImplementedError

    def abort(self, code: int, msg: str = "") -> None:
        raise NotImplementedError

    def finalize(self) -> None:
        pass


class InprocWorld:
    """Shared state for an N-thread-rank world on one host."""

    def __init__(self, size: int) -> None:
        self.size = size
        self.modex: Dict[tuple, Any] = {}
        self.modex_cv = threading.Condition()
        self.barrier = threading.Barrier(size)
        self.states: List[Any] = [None] * size  # ProcState per rank
        self.aborted: Optional[tuple] = None
        # shared rendezvous objects for device collectives (coll/tpu,
        # coll/hbm), keyed by communicator cid
        self.shared: Dict[Any, Any] = {}
        self.shared_lock = threading.Lock()
        # ULFM (ompi_tpu/ft/ulfm): global ranks declared permanently
        # dead.  Fences count survivors only, so a kill shrinks the
        # quorum instead of hanging every later fence
        self.ulfm_failed: Set[int] = set()
        self._uf_cv = threading.Condition()
        self._uf_count = 0
        self._uf_gen = 0

    def ulfm_fence(self, rank: int, timeout: float) -> None:
        """Generation-counting barrier over the SURVIVORS: `need` is
        recomputed on every wake, so a rank dying while others are
        parked here shrinks the quorum and releases them (a
        threading.Barrier's party count is frozen at construction —
        exactly what a failure-aware fence cannot use).  The short
        wait slices double as an abort poll: a peer that errors out
        releases everyone without needing to know about this cv."""
        with self._uf_cv:
            gen = self._uf_gen
            self._uf_count += 1
            deadline = time.monotonic() + timeout
            while gen == self._uf_gen:
                if self.aborted is not None and self.aborted[0] != rank:
                    raise RuntimeError(
                        f"peer rank {self.aborted[0]} aborted: "
                        f"{self.aborted[2]}")
                if self._uf_count >= self.size - len(self.ulfm_failed):
                    self._uf_count = 0
                    self._uf_gen += 1
                    self._uf_cv.notify_all()
                    return
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"fence timed out (rank {rank})")
                self._uf_cv.wait(timeout=min(left, 0.05))

    def is_local(self, rank: int) -> bool:
        """Is `rank` a thread in this process (inproc-btl reachable,
        device-rendezvous capable)?"""
        return 0 <= rank < self.size

    def make_rte(self, rank: int) -> "InprocRTE":
        return InprocRTE(self, rank)


class HybridWorld(InprocWorld):
    """Shared state for the hybrid launch model: one process per host
    owning a contiguous block of rank-threads, with more such
    processes elsewhere in the job (see docs/DESIGN.md).  `states` is
    indexed by GLOBAL rank — entries for remote ranks stay None, which
    is exactly what makes comm.mesh() refuse comms that span hosts
    (they fall back to the host-staged p2p path until the DCN device
    plane exists)."""

    def __init__(self, world_size: int, rank_base: int, nlocal: int) -> None:
        super().__init__(nlocal)
        self.size = world_size
        self.rank_base = rank_base
        self.nlocal = nlocal
        self.states = [None] * world_size
        # local barrier deliberately sized nlocal (threading.Barrier in
        # super().__init__) — global fences go through the KV server

    def is_local(self, rank: int) -> bool:
        return self.rank_base <= rank < self.rank_base + self.nlocal


class InprocRTE(RTE):
    def __init__(self, world: InprocWorld, rank: int) -> None:
        self.world = world
        self.rank = rank
        self.size = world.size

    def modex_put(self, key: str, value: Any) -> None:
        with self.world.modex_cv:
            self.world.modex[(self.rank, key)] = value
            self.world.modex_cv.notify_all()

    def modex_get(self, peer: int, key: str) -> Any:
        with self.world.modex_cv:
            while (peer, key) not in self.world.modex:
                if self.world.aborted:
                    raise RuntimeError(f"job aborted: {self.world.aborted}")
                if not self.world.modex_cv.wait(
                        timeout=_modex_timeout_var.value):
                    raise TimeoutError(
                        f"modex_get({peer},{key}) timed out")
            return self.world.modex[(peer, key)]

    def fence(self) -> None:
        self.world.ulfm_fence(self.rank, _fence_timeout_var.value)

    def abort(self, code: int, msg: str = "") -> None:
        self.world.aborted = (self.rank, code, msg)
        with self.world.modex_cv:
            self.world.modex_cv.notify_all()
        raise SystemExit(code)


class EnvRTE(RTE):
    """Process-rank runtime: identity from the environment set by the
    launcher (ompi_tpu.tools.mpirun), modex/fence through its KV
    server (ref: orte/mca/ess env component + pmix client)."""

    def __init__(self) -> None:
        import os

        from .kvstore import KVClient  # noqa: PLC0415

        self.rank = int(os.environ["TPUMPI_RANK"])
        # world = this job's ranks; universe = every rank launched so
        # far (dpm: spawned jobs extend the universe, ref: ompi/dpm).
        # `size` is the universe extent — it sizes endpoint tables so
        # dynamic peers are addressable; comm_world uses world_base/
        # world_size.
        self.world_size = int(os.environ.get(
            "TPUMPI_WORLD_SIZE", os.environ["TPUMPI_SIZE"]))
        self.world_base = int(os.environ.get("TPUMPI_WORLD_BASE", "0"))
        self.size = int(os.environ.get(
            "TPUMPI_UNIVERSE", os.environ["TPUMPI_SIZE"]))
        self.parent_root = os.environ.get("TPUMPI_PARENT_ROOT")
        self.appnum = int(os.environ.get("TPUMPI_APPNUM", "0"))
        self.jobid = os.environ.get("TPUMPI_JOBID", "job0")
        self.node_id = int(os.environ.get("TPUMPI_NODE", "0"))
        self.session_dir = os.environ.get("TPUMPI_SESSION_DIR", "/tmp")
        self.kv = KVClient(os.environ["TPUMPI_KV_ADDR"])
        self._fence_count = 0
        # live recovery (runtime/ft.py): a restarted rank joins the
        # job at a bumped epoch — its fences and modex keys live in
        # the epoch namespace so the KV proxies' write-once modex
        # caches can never serve pre-failure values, and its init
        # fences meet the survivors' recover() fences, not the
        # long-gone originals
        self.modex_epoch = int(os.environ.get("TPUMPI_FT_EPOCH", "0"))
        if self.modex_epoch:
            self.jobid_base = self.jobid
            self.jobid = f"{self.jobid}:e{self.modex_epoch}"

    def modex_put(self, key: str, value: Any) -> None:
        e = getattr(self, "modex_epoch", 0)
        sfx = f"@e{e}" if e else ""
        self.kv.put(f"modex:{self.rank}:{key}{sfx}", value)

    def modex_get(self, peer: int, key: str) -> Any:
        e = getattr(self, "modex_epoch", 0)
        sfx = f"@e{e}" if e else ""
        return self.kv.get(f"modex:{peer}:{key}{sfx}",
                           timeout=_modex_timeout_var.value)

    def fence(self) -> None:
        # namespaced by job and sized to the job's world: spawned
        # jobs fence among themselves, never with the parent job.
        # ULFM-declared dead ranks (ulfm_failed is maintained by
        # UlfmState._ingest) never arrive — shrink the quorum so
        # survivor fences complete (the KV server honors per-message
        # weights)
        self._fence_count += 1
        dead = sum(1 for r in getattr(self, "ulfm_failed", ())
                   if self.world_base <= r <
                   self.world_base + self.world_size)
        self.kv.fence(f"{self.jobid}:f{self._fence_count}",
                      n=self.world_size - dead)

    def abort(self, code: int, msg: str = "") -> None:
        import os
        import sys

        self.kv.abort(self.rank, code, msg)
        sys.stderr.write(f"[rank {self.rank}] MPI_Abort({code}): {msg}\n")
        sys.stderr.flush()
        os._exit(code)

    def finalize(self) -> None:
        self.kv.close()


class HybridRTE(EnvRTE):
    """Rank-thread runtime for the hybrid launch model: global modex /
    fence / abort through the launcher's KV server (EnvRTE behavior),
    plus a HybridWorld shared with co-resident rank-threads so the
    inproc btl and the device-collective rendezvous work across them.
    This is how coll/tpu becomes reachable from a real mpirun job: the
    per-host app shell (ompi_tpu.tools.hostrun) builds one of these
    per rank-thread (ref: the per-node orted owning its local procs,
    orte/orted/orted_main.c — except local 'procs' are threads
    driving local chips)."""

    def __init__(self, world: HybridWorld, rank: int, kv_addr: str,
                 node_id: int = 0, jobid: str = "job0",
                 session_dir: str = "/tmp",
                 kv_ns: Optional[str] = None) -> None:
        from .kvstore import KVClient  # noqa: PLC0415

        # no super().__init__(): identity comes from the app shell's
        # arguments, not per-process env vars (threads share env).
        # kv_ns scopes every KV key (modex, fences, ULFM notes) under
        # a session namespace — the DVM serve plane runs many resident
        # sessions against ONE shared KV server
        self.world = world
        self.rank = rank
        self.size = world.size
        self.world_base = 0
        self.world_size = world.size
        self.jobid = jobid
        self.node_id = node_id
        self.session_dir = session_dir
        self.kv = KVClient(kv_addr, ns=kv_ns)
        self.default_device: Any = None
        self._fence_count = 0

    def abort(self, code: int, msg: str = "") -> None:
        # flag local rank-threads first so parked rendezvous/progress
        # loops see the abort before the process dies
        self.world.aborted = (self.rank, code, msg)
        for st in self.world.states:
            if st is not None and getattr(st, "progress", None) is not None:
                st.progress.wakeup()
        EnvRTE.abort(self, code, msg)


_tls_rte = threading.local()


def set_thread_rte(rte: Optional[RTE]) -> None:
    """Install the RTE the next make_rte() on THIS thread returns —
    the hook the hostrun app shell uses to hand each rank-thread its
    pre-built HybridRTE before running the user program."""
    _tls_rte.rte = rte


def make_rte() -> RTE:
    """Bootstrap this process's runtime (ess component selection
    analog, ref: orte/mca/ess): app-shell rank-thread → injected
    HybridRTE; launched by our mpirun → EnvRTE; standalone →
    singleton world of size 1."""
    import os

    injected = getattr(_tls_rte, "rte", None)
    if injected is not None:
        return injected
    if "TPUMPI_KV_ADDR" in os.environ:
        return EnvRTE()
    world = InprocWorld(1)
    return world.make_rte(0)
