"""dfs: read-only access to files on OTHER hosts of the job.

Re-design of orte/mca/dfs (ref: dfs.h:50-107 and dfs/app/dfs_app.c —
an app opens ``file://host/path``, and open/seek/read are forwarded
to the daemon on the host that owns the file; read-only by design).
The tpu-native collapse: requests ride the existing KV control plane
— a rank's node-local KV proxy serves files on its OWN node
directly, and forwards other hosts upstream, where the HNP serves
its host's files.  The primary dfs use case — compute ranks reading
input staged on the launch host without a shared filesystem — is
exactly that one forwarded hop.

    from ompi_tpu.runtime import dfs
    f = dfs.open("file://hnp//data/input.bin", comm.state.rte)
    header = f.read(128)
    f.seek(0)
    ...
    f.close()

Local paths (no host, or this host's name) bypass the control plane
entirely and use posix."""

from __future__ import annotations

import os
from typing import Optional

from ompi_tpu.runtime.kvstore import dfs_parse_uri

SEEK_SET, SEEK_CUR, SEEK_END = 0, 1, 2


class DfsFile:
    """One open (possibly remote) read-only file."""

    def __init__(self, uri: str, rte=None) -> None:
        host, path = dfs_parse_uri(uri)
        me = os.environ.get("TPUMPI_NODE_NAME", "")
        self._pos = 0
        self._closed = False
        if host in ("", "localhost") or host == me:
            self._kv = None
            self._fd = os.open(path, os.O_RDONLY)
            self._size = os.fstat(self._fd).st_size
        else:
            kv = getattr(rte, "kv", None)
            if kv is None:
                raise OSError(
                    f"dfs: no control plane to reach host {host!r} "
                    "(not launched under mpirun?)")
            self._kv = kv
            self._fd, self._size = kv.dfs_open(uri)

    # -- surface (dfs.h contract: open/size/seek/read/close) ------------
    def size(self) -> int:
        return self._size

    def seek(self, offset: int, whence: int = SEEK_SET) -> int:
        new = {SEEK_SET: offset,
               SEEK_CUR: self._pos + offset,
               SEEK_END: self._size + offset}[whence]
        if new < 0 or new > self._size:
            # the reference errors on seeking past EOF (contrary to
            # lseek, consistent with read-only files: dfs.h:86-89)
            raise OSError(f"dfs seek to {new} outside [0, {self._size}]")
        self._pos = new
        return new

    def tell(self) -> int:
        return self._pos

    def pread(self, offset: int, n: int) -> bytes:
        if self._kv is None:
            return os.pread(self._fd, n, offset)
        return self._kv.dfs_read(self._fd, offset, n)

    def read(self, n: Optional[int] = None) -> bytes:
        if n is None:
            n = self._size - self._pos
        data = self.pread(self._pos, n)
        self._pos += len(data)
        return data

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._kv is None:
            os.close(self._fd)
        else:
            self._kv.dfs_close(self._fd)

    def __enter__(self) -> "DfsFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def open(uri: str, rte=None) -> DfsFile:  # noqa: A001 (dfs.open API)
    return DfsFile(uri, rte)
