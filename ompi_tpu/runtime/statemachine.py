"""Event-driven job/proc state machine — the orte/mca/state analog.

Re-design of the reference's state machinery: ``ORTE_ACTIVATE_JOB_STATE``
posts an event that runs the handler registered for (role, state)
(ref: orte/mca/state/state.h:92-109; per-role state tables in
state_base_fns.c:428-843; hnp/orted/app components under
orte/mca/state/).  Differences from the reference:

  * the event loop is an explicit queue drained by ``run()`` on the
    launcher's main thread instead of libevent callbacks — activations
    may come from any thread (OOB dispatch, process reapers, timers,
    KV-server callbacks) and are serialized here;
  * errmgr policy IS a set of state handlers: failure events
    (PROC_FAILED / DAEMON_FAILED / ABORTED / TIMEOUT) are ordinary
    states whose handlers decide the transition to DRAINING (the
    errmgr/default_hnp "first abnormal exit kills the job" policy,
    ref: orte/mca/errmgr/default_hnp/errmgr_default_hnp.c);
  * a ``--verbose state`` trace prints every transition.

Launch lifecycle (the VERDICT r2 table):

    INIT -> ALLOCATE -> MAP -> LAUNCH_DAEMONS -> DAEMONS_REPORTED
         -> LAUNCH_APPS -> RUNNING -> DRAINING -> TERMINATED

with error states entering from anywhere:

    PROC_FAILED, DAEMON_FAILED, ABORTED, TIMEOUT, LAUNCH_FAILED

The single-host direct path skips the daemon states
(INIT -> ALLOCATE -> MAP -> LAUNCH_APPS -> ...).
"""

from __future__ import annotations

import queue
import sys
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

# lifecycle states
INIT = "INIT"
ALLOCATE = "ALLOCATE"
MAP = "MAP"
LAUNCH_DAEMONS = "LAUNCH_DAEMONS"
DAEMONS_REPORTED = "DAEMONS_REPORTED"
LAUNCH_APPS = "LAUNCH_APPS"
RUNNING = "RUNNING"
DRAINING = "DRAINING"
TERMINATED = "TERMINATED"

# error states (handlers implement the errmgr policy)
PROC_FAILED = "PROC_FAILED"
DAEMON_FAILED = "DAEMON_FAILED"
ABORTED = "ABORTED"
TIMEOUT = "TIMEOUT"
LAUNCH_FAILED = "LAUNCH_FAILED"

# non-state events routed through the same queue so handlers stay
# serialized with transitions (spawn requests, proc exits, node
# completions, daemon registrations)
EVENT_PREFIX = "EV_"


class StateMachine:
    """One job's state machine; owned by the launcher (HNP role) or a
    daemon (orted role)."""

    def __init__(self, role: str = "hnp", verbose: bool = False,
                 name: str = "mpirun") -> None:
        self.role = role
        self.verbose = verbose
        self.name = name
        self.state = INIT
        self.exit_code = 0
        self.data: Dict[str, Any] = {}  # handler blackboard
        self._handlers: Dict[str, Callable] = {}
        self._events: "queue.Queue[Tuple[str, dict]]" = queue.Queue()
        self._seen_terminal = False
        self._timer: Optional[threading.Timer] = None

    # -- registration --------------------------------------------------
    def register(self, state: str,
                 handler: Callable[["StateMachine", dict], None]) -> None:
        """Install the handler for ``state`` (replacing any previous
        one — the reference's state-table override semantics)."""
        self._handlers[state] = handler

    def register_table(self, table: Dict[str, Callable]) -> None:
        for state, handler in table.items():
            self.register(state, handler)

    # -- activation (any thread) ---------------------------------------
    def activate(self, state: str, **info: Any) -> None:
        """Post ``state`` to the event queue (the
        ORTE_ACTIVATE_JOB_STATE analog).  Never blocks; never runs the
        handler inline."""
        self._events.put((state, info))

    def start_timeout(self, seconds: float) -> None:
        """Arm the job timeout (activates TIMEOUT)."""
        if seconds and seconds > 0:
            self._timer = threading.Timer(
                seconds, lambda: self.activate(TIMEOUT, seconds=seconds))
            self._timer.daemon = True
            self._timer.start()

    # -- event loop ----------------------------------------------------
    def _trace(self, prev: str, state: str, info: dict) -> None:
        if self.verbose:
            extra = " ".join(f"{k}={v!r}" for k, v in info.items()
                             if k not in ("proc",))
            sys.stderr.write(
                f"[{self.name}:{self.role}:state] {prev} -> {state}"
                + (f" ({extra})" if extra else "") + "\n")
            sys.stderr.flush()

    def dispatch(self, state: str, info: dict) -> None:
        handler = self._handlers.get(state)
        prev = self.state
        if not state.startswith(EVENT_PREFIX):
            self.state = state
            self._trace(prev, state, info)
        if handler is not None:
            handler(self, info)

    def run(self) -> int:
        """Drain events until TERMINATED; returns the job exit code."""
        while self.state != TERMINATED:
            try:
                state, info = self._events.get(timeout=60.0)
            except queue.Empty:
                continue  # quiescent running job: keep waiting
            self.dispatch(state, info)
        if self._timer is not None:
            self._timer.cancel()
        return self.exit_code
