"""OOB/RML analog: tag-dispatched control messaging between the
launcher (HNP) and per-node daemons.

Re-design of orte/mca/oob/tcp + orte/mca/rml (tag-based async
send_nb/recv_nb, ref: orte/mca/rml/rml.h:204,263): one TCP socket per
daemon⇄HNP pair, frames of 4-byte big-endian length + JSON, a reader
thread per channel dispatching on the message's "op" field.  The
control plane never carries data-plane traffic (that is the btl's
job), so JSON framing is fine; byte payloads (IOF lines) travel
latin-1-escaped.

Unlike the reference there is no routing overlay in the message path:
daemons connect directly to the HNP (the routed/direct component
model), while the *launch* may still fan out as a tree (plm tree
spawn, see tools/plm.py).
"""

from __future__ import annotations

import socket
import threading
from typing import Any, Callable, Optional

from ompi_tpu.mca.params import registry
from .kvstore import _recv_msg, _send_msg

# control-plane hardening knobs (shared by tools/tpud and tools/plm;
# registered here because both sides import oob)
retry_max_var = registry.register(
    "oob", "base", "retry_max", 5, int,
    help="Daemon-side reconnect attempts after its HNP channel drops "
         "before it gives up and kills its local procs")
retry_delay_var = registry.register(
    "oob", "base", "retry_delay", 0.25, float,
    help="Base daemon reconnect backoff (exponential, jittered, "
         "capped 5 s)")
heartbeat_interval_var = registry.register(
    "oob", "base", "heartbeat_interval", 2.0, float,
    help="Seconds between daemon->HNP liveness beats (0 disables "
         "sending)")
heartbeat_budget_var = registry.register(
    "oob", "base", "heartbeat_budget", 0, int,
    help="HNP declares a daemon lost after this many missed beat "
         "intervals — liveness by silence, not only by TCP death "
         "(0 disables monitoring)")
reconnect_grace_var = registry.register(
    "oob", "base", "reconnect_grace", 0.0, float,
    help="HNP holds EV_DAEMON_LOST this long after a channel drop, "
         "waiting for the daemon to reconnect (0 = fire immediately, "
         "the legacy behavior)")
host_grace_var = registry.register(
    "oob", "host", "grace_s", 0.0, float,
    help="Extra seconds of heartbeat silence tolerated before a WHOLE "
         "host is declared a lost failure domain (added on top of the "
         "per-daemon silence budget; 0 = no extra slack).  Consumed "
         "by the HNP beat monitor and the DVM host-liveness plane — "
         "one knob paces both host-granularity detectors")


def backoff_s(attempt: int, base: float, cap: float = 5.0) -> float:
    """One control-plane reconnect backoff step: exponential in
    ``attempt``, capped, with full 0.5x–1.5x jitter so a fleet of
    reconnecting clients never stampedes a freshly promoted standby
    or a supervisor-respawned server in lockstep.  The single
    definition every reconnect loop in the control plane sleeps on —
    daemon→HNP (tools/tpud) and KV client failover (runtime/kvstore,
    DESIGN.md §20) — so tuning recovery pacing changes ONE policy,
    not one copy per loop."""
    import random
    d = min(cap, max(0.001, base) * (2 ** min(6, max(0, attempt))))
    return d * (0.5 + random.random())


def silence_budget_s() -> float:
    """Heartbeat-silence horizon: how long a daemon may stay quiet
    before the HNP declares it lost (0.0 = monitoring disabled).
    The ULFM errmgr policy promotes this signal into per-rank failure
    records — the same budget, one definition."""
    if heartbeat_budget_var.value <= 0 or \
            heartbeat_interval_var.value <= 0:
        return 0.0
    return heartbeat_budget_var.value * heartbeat_interval_var.value


class Channel:
    """One framed bidirectional control connection.  ``send`` is
    thread-safe; inbound messages are dispatched from a dedicated
    reader thread to ``handler(msg)``; EOF/error fires
    ``on_close(exc_or_none)`` exactly once."""

    def __init__(self, sock: socket.socket,
                 handler: Callable[[dict], None],
                 on_close: Optional[Callable[[Optional[Exception]], None]]
                 = None) -> None:
        self.sock = sock
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.handler = handler
        self.on_close = on_close
        self._wlock = threading.Lock()
        self._closed = False
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _read_loop(self) -> None:
        exc: Optional[Exception] = None
        try:
            while True:
                msg = _recv_msg(self.sock)
                if msg is None:
                    break
                self.handler(msg)
        except OSError as e:
            exc = e
        finally:
            closed_now = False
            with self._wlock:
                if not self._closed:
                    self._closed = True
                    closed_now = True
            if closed_now and self.on_close is not None:
                self.on_close(exc)

    def send(self, msg: dict) -> None:
        with self._wlock:
            if self._closed:
                raise ConnectionError("oob channel closed")
            _send_msg(self.sock, msg)

    def close(self) -> None:
        with self._wlock:
            self._closed = True
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


def connect(addr: str, handler: Callable[[dict], None],
            on_close: Optional[Callable[[Optional[Exception]], None]] = None,
            timeout: float = 60.0) -> Channel:
    host, port = addr.rsplit(":", 1)
    s = socket.create_connection((host, int(port)), timeout=timeout)
    s.settimeout(None)
    return Channel(s, handler, on_close)


def local_ip_toward(addr: str) -> str:
    """The IP this host would use to reach ``addr`` (the opal if/
    reachable analog collapsed to the UDP-connect trick: no packet is
    sent, the kernel just picks the route's source address)."""
    host, port = addr.rsplit(":", 1)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((host, int(port)))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()
