"""Silent-data-corruption plane for device collectives (DESIGN.md §25).

Every fault plane before this one models failure as something *loud*:
a dead rank trips ULFM, a dead host trips the liveness grace, a slow
host trips the §24 gray-failure scorer.  The accelerator failure mode
that actually kills large training runs is the opposite — a chip that
computes wrong answers while passing every heartbeat.  This module
closes that rung: an online, sampled, algebraic integrity check that
rides the existing collective dispatch instead of doubling it.

Detection model (per sampled op, knob ``integrity_sample``)::

    gate      each rank folds a cheap checksum ("digest") of its own
              contribution at deposit time — exact modular sum for
              int dtypes, float64 sum with a relative tolerance band
              for floats, exact extremum for MAX/MIN — and wraps its
              deposit in a ``_Checked`` carrier;
    verify    the executing rank (the rendezvous last-arriver, which
              already holds every rank's deposit AND the reduced
              output) cross-checks the fold of the per-rank claims
              against the digest of the reduced data.  The check is
              algebraic: digest(reduce(x_0..x_n)) == fold(digest(x_r))
              holds exactly for int SUM (mod 2^width), MAX and MIN,
              and within a reassociation band for float SUM;
    bisect    on mismatch, a bisection round re-digests every rank's
              deposited operand against the claim it made at the
              gate.  A divergent rank corrupted its operand *after*
              digesting it — that chip is convicted.  No divergence
              means the reduction itself went wrong: the executing
              chip is convicted;
    survive   the poisoned op is retried from the pristine sources
              (byte-identical result, never a failed job), the
              conviction flows to the §24 health plane as the ``sdc``
              signal (immediate quarantine, drain/park/migrate), and
              state older than the detection window restores from the
              §14 checkpoint ladder.

Sampling is comm-consistent without any extra communication: the
rendezvous runs ONE rank's closure, so either every rank wraps an op
or none may.  Each rank keeps an identical per-comm op countdown
(collective call sequences are identical across ranks by MPI
ordering), so the decision is deterministic and lockstep.  The
countdown is adaptive like trace sampling: it starts at 1-in-1 and
doubles toward the ``integrity_sample`` cap every
``integrity_sample_auto`` banked checks, so a fresh (or freshly
suspect) world is checked densely and a proven-clean one cheaply.

``sample`` and ``fold`` are hotpath_audit-enforced (tools/
hotpath_audit.py): the always-on per-op cost is one dict lookup and
integer countdown; the per-sampled-check cost is one NumPy reduction
per operand.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ompi_tpu.mca.params import registry

_enable_var = registry.register(
    "integrity", "", "enable", 0, int,
    help="Arm the SDC-detection plane for device collectives: sampled "
         "algebraic checksum cross-checks on the rendezvous path, "
         "bisection attribution, retry-from-source and health-plane "
         "conviction on mismatch")
_sample_var = registry.register(
    "integrity", "", "sample", 64, int,
    help="Steady-state check sampling period cap (1-in-N sampled "
         "collectives carry an integrity check; 1 = every op).  The "
         "live period starts at 1 and doubles toward this cap as "
         "clean checks bank up — the trace-sampler adaptation model")
_sample_auto_var = registry.register(
    "integrity", "", "sample_auto", 256, int,
    help="Banked clean checks per period doubling (adaptive sampler "
         "ramp rate); 0 pins the period at integrity_sample")
_rel_tol_var = registry.register(
    "integrity", "", "rel_tol", 1e-4, float,
    help="Relative tolerance band for float SUM digests (reassociated "
         "device reductions round differently from the float64 host "
         "fold; int/MAX/MIN digests are exact and ignore this)")

_pv_checks = registry.register_pvar(
    "integrity", "", "checks",
    help="Device-collective ops that carried a sampled integrity "
         "check (gate + verify both counted here once)")
_pv_mismatches = registry.register_pvar(
    "integrity", "", "mismatches",
    help="Integrity checks whose reduced-data digest disagreed with "
         "the fold of per-rank claims (each triggers bisection)")
_pv_convictions = registry.register_pvar(
    "integrity", "", "convictions",
    help="Chips convicted of silent data corruption by the bisection "
         "round (attributed to a specific rank/host)")
_pv_retries = registry.register_pvar(
    "integrity", "", "retry_ops",
    help="Poisoned collectives re-executed from pristine per-rank "
         "sources after a conviction (byte-identical recovery — "
         "never a failed job)")

#: module arm flag — a plain attribute so the coll hot path pays one
#: module-dict lookup (``_ig.on``) per op when the plane is off.
on = False

#: live sampler parameters, cached from the knobs at refresh() time so
#: the audited sample() never touches registry properties.
_cap = 64
_auto = 256
_rel_tol = 1e-4

#: fold codes — the digest algebra each spec selects.
F_INTSUM, F_FSUM, F_MAX, F_MIN = 1, 2, 3, 4

#: process-global conviction registry (the doctor's evidence) and the
#: hook list the DVM uses to feed the §24 health plane.
_conv_lock = threading.Lock()
convicted: List[Dict[str, Any]] = []
_hooks: List[Callable[[Dict[str, Any]], None]] = []

_UVIEW = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}


def refresh() -> None:
    """Re-read the knobs into the cached module globals.  Called from
    obs.attach (i.e. every mpi_init) and directly by tests/probes
    after twiddling integrity_* knobs mid-process."""
    global on, _cap, _auto, _rel_tol
    _cap = max(1, int(_sample_var.value or 1))
    _auto = max(0, int(_sample_auto_var.value or 0))
    _rel_tol = float(_rel_tol_var.value or 0.0)
    on = bool(_enable_var.value)


def set_armed(flag: bool) -> None:
    """Probe/benchmark toggle: arm or disarm without touching knobs
    (the trace_overhead integrity arm flips this per chunk)."""
    global on
    on = bool(flag)


def install_convict_hook(fn: Callable[[Dict[str, Any]], None]) -> None:
    """Register a conviction listener (the DVM wires the health
    plane's note_sdc through this).  Idempotent per function."""
    with _conv_lock:
        if fn not in _hooks:
            _hooks.append(fn)


def remove_convict_hook(fn: Callable[[Dict[str, Any]], None]) -> None:
    with _conv_lock:
        if fn in _hooks:
            _hooks.remove(fn)


def convicted_snapshot() -> List[Dict[str, Any]]:
    """Copy of the conviction registry (doctor capture / metrics)."""
    with _conv_lock:
        return [dict(r) for r in convicted]


def reset() -> None:
    """Test/probe helper: clear convictions and per-run sampler state
    is per-comm (dies with the world), so only the registry needs it."""
    with _conv_lock:
        del convicted[:]


# -- spec construction (what can be checked, and how) ------------------------

def spec(kind: str, opname: str, x: Any, root: int = 0):
    """Build the check spec for one collective, or None when the op
    is not algebraically checkable (exotic reduce op, non-numeric
    dtype).  The result depends only on (kind, opname, dtype), never
    on rank-local state, so every rank derives the same spec and the
    comm-consistency invariant holds.

    Spec tuple: ``(kind, foldcode, itemsize[, root])``.
    """
    if not on:
        return None
    return spec_static(kind, opname, x, root)


def spec_static(kind: str, opname: str, x: Any, root: int = 0):
    """spec() without the arm-flag gate — for cached Plan objects that
    outlive arm/disarm; their executor re-gates on ``on`` per call."""
    try:
        dt = np.dtype(getattr(x, "dtype", None) or np.asarray(x).dtype)
    except TypeError:
        return None
    k = dt.kind
    # bool excluded: device reductions treat PRED SUM as OR, which the
    # modular-sum digest would flag as corruption.
    if k in "iu":
        base = F_INTSUM
    elif k == "f":
        base = F_FSUM
    else:
        return None
    if kind in ("allreduce", "redscat"):
        if opname == "MPI_SUM":
            return (kind, base, dt.itemsize)
        if opname == "MPI_MAX":
            return (kind, F_MAX, dt.itemsize)
        if opname == "MPI_MIN":
            return (kind, F_MIN, dt.itemsize)
        return None
    if kind in ("gather", "alltoall"):
        # conservation checks: the op moves data without combining it,
        # so total content (modular/float sum) is invariant.
        return (kind, base, dt.itemsize)
    if kind == "bcast":
        return (kind, base, dt.itemsize, int(root))
    return None


# -- digests (the per-operand checksums) -------------------------------------

def fold(a, code):
    """Scalar fold of a prepared 1-D array: the hot reduction of the
    sampled check path (hotpath_audit-enforced — one NumPy reduction,
    no allocation beyond the scalar)."""
    if code == 1:
        return int(np.add.reduce(a, dtype=np.uint64))
    if code == 2:
        return float(np.add.reduce(a, dtype=np.float64))
    if code == 3:
        return a.max().item()
    return a.min().item()


def digest(x: Any, code: int):
    """Checksum one operand.  Int dtypes fold as a uint64 modular sum
    (exact mod 2^width at compare time); floats fold in float64."""
    a = np.asarray(x)
    if a.size == 0:
        return 0 if code != 2 else 0.0
    if code == F_INTSUM:
        u = _UVIEW.get(a.dtype.itemsize, np.uint64)
        try:
            a = a.view(u)
        except (ValueError, TypeError):
            a = np.ascontiguousarray(a).view(u)
        return fold(a.ravel(), 1)
    return fold(a.ravel(), code)


def _fold_claims(code: int, ds: List[Any]):
    """Combine per-rank claims with the same algebra the reduction
    used (python-int exact for modular sums)."""
    if code in (F_INTSUM, F_FSUM):
        t = 0
        for d in ds:
            t += d
        return t
    if code == F_MAX:
        return max(ds)
    return min(ds)


def _eq(code: int, a, b, itemsize: int, tol: float) -> bool:
    if code == F_INTSUM:
        m = (1 << (8 * itemsize)) - 1
        return (int(a) & m) == (int(b) & m)
    try:
        fa, fb = float(a), float(b)
    except (TypeError, ValueError):
        return a == b
    if fa != fa or fb != fb or fa in (float("inf"), float("-inf")) \
            or fb in (float("inf"), float("-inf")):
        # non-finite digests are unjudgeable (NaN-poisoned data is a
        # model problem, not chip corruption) — fail open.
        return True
    if code == F_FSUM and tol > 0.0:
        return abs(fa - fb) <= tol * max(abs(fa), abs(fb), 1.0)
    return fa == fb


# -- sampling (per-op hot path) ----------------------------------------------

def _new_state(comm):
    # countdown, live period, banked-clean-checks. Lives in the comm's
    # instance dict so looped worlds start fresh and sibling comms
    # sample independently (their op sequences differ).
    st = [0, 1, 0]
    comm.__dict__["_ig_state"] = st
    return st


def sample(comm):
    """Deterministic 1-in-N sampling decision for the next collective
    on ``comm`` (hotpath_audit-enforced: dict lookup + integer
    countdown).  Every rank advances an identical counter over an
    identical op sequence, so the decision is comm-consistent without
    communication — the invariant the last-arriver execution model
    requires."""
    st = comm.__dict__.get("_ig_state")
    if st is None:
        st = _new_state(comm)
    c = st[0]
    if c > 0:
        st[0] = c - 1
        return 0
    p = st[1]
    b = st[2] + 1
    st[2] = b
    if _auto > 0 and b >= _auto and p < _cap:
        p = p + p
        if p > _cap:
            p = _cap
        st[1] = p
        st[2] = 0
    st[0] = p - 1
    return 1


# -- the gate (wrap a sampled op) --------------------------------------------

class _Checked:
    """Per-rank deposit carrier for a sampled op: ``v`` is what enters
    the datapath (the device_sdc injector retargets this binding to a
    corrupted copy — the source stays pristine), ``src`` a pristine
    HOST copy for retry (donating plan programs may invalidate the
    original device buffers, so retry never reads them), ``d`` the
    digest claimed at the gate."""

    __slots__ = ("v", "src", "d", "rank")

    def __init__(self, v, src, d, rank):
        self.v = v
        self.src = src
        self.d = d
        self.rank = rank


def _digest_for(ck, value):
    if ck[0] == "fused":
        arrays = value[1]
        out = []
        for ent in ck[1]:
            out.append(digest(arrays[ent[2]], ent[1]))
        return tuple(out)
    return digest(value, ck[1])


def gate(comm, value, fn, ck):
    """Wrap (value, fn) for one sampled collective.  Returns the pair
    unchanged when this op is not sampled.  Called from the coll meet
    path only when a spec exists (ck is not None) and the plane is
    armed."""
    if not sample(comm):
        return value, fn
    _pv_checks.add(1)
    if ck[0] == "fused":
        src = (value[0], [np.array(a, copy=True) for a in value[1]])
    else:
        src = np.array(value, copy=True)
    c = _Checked(value, src, _digest_for(ck, src), comm.rank)

    def checked_fn(shards, _fn=fn, _ck=ck, _comm=comm):
        return _run_checked(_comm, _fn, _ck, shards)

    return c, checked_fn


# -- verify / bisect / convict / retry (executing-rank side) -----------------

def _run_checked(comm, fn, ck, shards):
    outs = fn([s.v for s in shards])
    try:
        ok = _verify(ck, shards, outs)
    except Exception:
        # A checker defect must never take down the datapath: the
        # plane's contract is "never a failed job" — fail open.
        return outs
    if ok:
        return outs
    _pv_mismatches.add(1)
    from ompi_tpu import obs as _obs
    _obs.record_event(_obs.EV_SDC_MISMATCH, getattr(comm, "cid", 0),
                      int(getattr(comm, "_dev_seq", 0)),
                      _obs.intern(ck[0]), rank=comm.rank)
    bad = _bisect(ck, shards)
    if bad < 0:
        # no rank's operand diverged from its gate claim: the
        # reduction itself was computed wrong — the executing chip
        # (this one) is the culprit.
        bad = comm.rank
    _convict(comm, bad, ck[0])
    outs = fn([s.src for s in shards])
    _pv_retries.add(1)
    _obs.record_event(_obs.EV_SDC_RETRY, getattr(comm, "cid", 0),
                      int(getattr(comm, "_dev_seq", 0)), bad,
                      rank=comm.rank)
    return outs


def _verify(ck, shards, outs) -> bool:
    kind = ck[0]
    if kind == "fused":
        out0 = outs[0]
        for ent in ck[1]:
            if not _verify_entry(ent, shards, out0):
                return False
        return True
    code, isz = ck[1], ck[2]
    claims = [s.d for s in shards]
    if kind == "allreduce":
        outd = digest(outs[0], code)
        return _eq(code, _fold_claims(code, claims), outd, isz, _rel_tol)
    if kind == "redscat":
        outd = _fold_claims(code, [digest(o, code) for o in outs])
        return _eq(code, _fold_claims(code, claims), outd, isz, _rel_tol)
    if kind == "gather":
        outd = digest(outs[0], code)
        return _eq(code, _fold_claims(code, claims), outd, isz, _rel_tol)
    if kind == "alltoall":
        outd = _fold_claims(code, [digest(o, code) for o in outs])
        return _eq(code, _fold_claims(code, claims), outd, isz, _rel_tol)
    if kind == "bcast":
        outd = digest(outs[0], code)
        # bcast moves bytes verbatim: digests of identical data are
        # identical, so the compare is exact even for floats.
        return _eq(code, claims[ck[3]], outd, isz, 0.0)
    return True


def _verify_entry(ent, shards, out0) -> bool:
    """One fused-batch entry: ``("g", code, ci, slots, isz)`` folds
    the per-rank claim at index ``ci`` against the output slots;
    ``("b", code, ci, root, isz)`` is an exact root-claim match (hbm
    bcast)."""
    ekind, code, ci = ent[0], ent[1], ent[2]
    if ekind == "g":
        claims = [s.d[ci] for s in shards]
        parts = [digest(out0[i], code) for i in ent[3]]
        return _eq(code, _fold_claims(code, claims),
                   _fold_claims(code, parts), ent[4], _rel_tol)
    if ekind == "b":
        root = ent[3]
        return _eq(code, shards[root].d[ci],
                   digest(out0[ci], code), ent[4], 0.0)
    return True


def _bisect(ck, shards) -> int:
    """Attribution round: re-digest every rank's deposited operand
    (the value that actually entered the datapath) against the claim
    it made at the gate.  A diverging rank corrupted its operand in
    the detection window — convict it.  Returns -1 when every operand
    still matches its claim (compute-side corruption)."""
    kind = ck[0]
    for r, s in enumerate(shards):
        d2 = _digest_for(ck, s.v)
        if kind == "fused":
            if d2 != s.d:
                return r
        elif not _eq(ck[1], d2, s.d, ck[2], 0.0):
            return r
    return -1


def _convict(comm, rank: int, kind: str) -> None:
    grank = rank
    host = 0
    try:
        grank = comm.group[rank]
        st = comm._peer_state(grank)
        host = int(getattr(getattr(st, "rte", None), "node_id", 0) or 0)
    except Exception:
        pass
    _pv_convictions.add(1)
    rec = {"rank": int(grank), "host": host,
           "cid": int(getattr(comm, "cid", 0)), "kind": kind}
    from ompi_tpu import obs as _obs
    _obs.record_event(_obs.EV_SDC_CONVICT, int(grank), host,
                      _obs.intern(kind), rank=comm.rank)
    with _conv_lock:
        convicted.append(rec)
        hooks = list(_hooks)
    for h in hooks:
        try:
            h(rec)
        except Exception:
            pass


# -- fault-injection support -------------------------------------------------

def flip_value(value):
    """Corrupt one operand the way a bad chip would: flip a high
    mantissa/magnitude bit of the middle element.  Understands the
    ``_Checked`` carrier (retargets ``.v``, leaving ``.src`` and the
    gate claim pristine — exactly the divergence _bisect attributes)
    and fused-batch deposits.  On an unwrapped value (op not sampled)
    the corruption is silent — the honest semantics of sampled
    detection."""
    if isinstance(value, _Checked):
        value.v = _flip_inner(value.v)
        return value
    return _flip_inner(value)


def _flip_inner(value):
    if isinstance(value, tuple) and len(value) == 2 \
            and isinstance(value[1], list) and value[1]:
        arrays = list(value[1])
        arrays[0] = _flip_array(arrays[0])
        return (value[0], arrays)
    return _flip_array(value)


def _flip_array(x):
    a = np.asarray(x)
    if a.size == 0:
        return x
    flat = np.ascontiguousarray(a).copy()
    bv = flat.view(np.uint8).reshape(-1)
    isz = max(1, a.dtype.itemsize)
    # last byte of the middle element: sign/exponent/high-magnitude
    # bits live there on little-endian, so SUM/MAX/MIN digests all see
    # the flip.
    mid = (a.size // 2) * isz + isz - 1
    bv[mid] ^= 0x40
    return flat.reshape(a.shape)
