"""Fleet telemetry plane: live pvar scrape, per-session attribution,
and a structured flight recorder (docs/DESIGN.md §16).

Everything the repo had before this module was post-mortem and
process-global: pvars are read inside the process, trace rings dump
at finalize, and the DVM service plane folds every resident session's
counters into one pool-wide number.  This module adds the three
pieces a *fleet* operator needs, riding the surfaces that already
exist (the MPI_T registry, the trace histograms, the DVM control
socket) rather than inventing parallel ones:

* **Scraper** — a rank-local snapshot of the trace latency histograms
  into a preallocated integer buffer, refreshed on the progress tick
  at a bounded cadence (``obs_scrape_interval_ms``).  The DVM
  ``metrics`` RPC reads these buffers from its accept thread without
  stopping any rank: the rank writes on its own tick, the server
  reads a generation-stamped copy.  ``Scraper.tick`` follows the
  Tracer's columns-not-objects discipline and is enforced by
  ``tools/hotpath_audit.py`` (same banned-construct list).

* **ScopedPvar** — per-session attribution for serve-plane hot
  counters.  The global value stays a plain O(1) integer bump on the
  underlying registry PVar (MPI_T readers see exactly what they saw
  before); a parallel per-band integer list accumulates the same adds
  keyed by the session id the serve plane already threads through
  ``ProcState.cid_band``.  Per-session reads come ONLY from the
  scrape path — the hot path never sums bands.

* **FlightRecorder** — a bounded ring of typed operational events
  (ULFM detect/revoke/shrink, respawn epochs, ckpt commit/abort/CRC
  fallback, admission rejects, fault injections, DVM
  attach/detach/halt) held as parallel integer columns with
  perf-counter timestamps against a wall anchor adopted from the
  Tracer when one exists — so flight events land on the same
  perfetto timeline as trace spans.  Persisted via the io layer on
  failure and on ``halt``; queryable live through
  ``ompi_tpu-attach --events``; merged by ``traceview``.

Registration is idempotent across looped worlds (the pstat model):
``register_pvars()`` is guarded by a module flag, the recorder is a
lazy process singleton, and ``attach(state)`` may run once per world
without duplicating anything.
"""

from __future__ import annotations

import json
import os
import threading
import time
from array import array
from typing import Any, Dict, List, Optional

from ompi_tpu import trace as _trace
from ompi_tpu.mca.params import registry
from ompi_tpu.runtime import state as _statemod

# -- knobs ------------------------------------------------------------------

_interval_var = registry.register(
    "obs", "", "scrape_interval_ms", 100, int,
    help="Minimum interval between rank-local histogram snapshots on "
         "the progress tick (0 disables the scrape tick; the metrics "
         "RPC then reads tracer histograms directly)")
_ring_var = registry.register(
    "obs", "", "events_ring", 256, int,
    help="Flight-recorder capacity (events); the oldest event is "
         "overwritten and the dropped counter grows")
_prom_var = registry.register(
    "obs", "", "prometheus", True, bool,
    help="Include Prometheus text exposition in metrics RPC replies")
_wd_ms_var = registry.register(
    "obs", "", "watchdog_ms", 0, int,
    help="Progress-stall watchdog tick interval for the DVM serving "
         "plane, milliseconds (0 = off, the default).  A running job "
         "whose wall time exceeds the pool's EWMA estimate by "
         "obs_watchdog_factor fires a wd_stall flight event and a "
         "doctor capture (stacks + rendezvous/fence/ULFM state) "
         "within ~2 ticks")
_wd_factor_var = registry.register(
    "obs", "", "watchdog_factor", 4, int,
    help="Stall threshold as a multiple of the pool's EWMA wall "
         "estimate (§17): a job running longer than factor x estimate "
         "is declared stalled.  With the FleetController on, the "
         "published per-tick tolerance (widened under backlog) takes "
         "precedence, this knob seeding its floor")


def watchdog_ms() -> int:
    return max(0, int(_wd_ms_var.value))


def watchdog_factor_pct() -> int:
    """The stall threshold in percent of the EWMA wall estimate
    (knob x100; the FleetController publishes an adaptive override)."""
    return max(100, int(_wd_factor_var.value) * 100)


def prometheus_enabled() -> bool:
    return bool(_prom_var.value)


# -- per-session attribution ------------------------------------------------

# Session ids band into a fixed power-of-two table: adds stay two
# integer bumps with a mask (no dict lookup on the hot serve path).
# Band 0 is the unattributed bucket (non-session work); the global
# read always equals the sum over ALL bands including band 0.
MAX_BANDS = 1024
_BAND_MASK = MAX_BANDS - 1

_scoped: Dict[str, "ScopedPvar"] = {}
_scoped_lock = threading.Lock()


class ScopedPvar:
    """A registry PVar plus a per-session-band shadow accumulator.

    ``add(n, band)`` is two integer adds: the global ``PVar._value``
    (so every existing MPI_T reader, pvar handle and index is
    untouched) and ``bands[band & mask]``.  Global reads stay O(1);
    per-band reads are served by the scrape path only.
    """

    __slots__ = ("pvar", "bands")

    def __init__(self, pvar) -> None:
        self.pvar = pvar
        self.bands = [0] * MAX_BANDS

    @property
    def full_name(self) -> str:
        return self.pvar.full_name

    def add(self, n: int = 1, band: int = 0) -> None:
        self.pvar._value += n
        self.bands[band & _BAND_MASK] += n

    def read(self) -> int:
        return self.pvar.read()

    def read_band(self, band: int) -> int:
        return self.bands[band & _BAND_MASK]

    def nonzero_bands(self) -> Dict[int, int]:
        out = {}
        for b, v in enumerate(self.bands):
            if v:
                out[b] = v
        return out


def scoped_pvar(framework: str, component: str, name: str,
                help: str = "", var_class: str = "counter") -> ScopedPvar:
    """Idempotent factory: wraps (or registers) the PVar of that full
    name.  Safe to call at import time and across looped worlds — the
    registry returns the existing PVar on collision and the scoped
    wrapper is cached by full name, so indices never move and bands
    never reset behind a caller's back."""
    pv = registry.register_pvar(framework, component, name,
                                help=help, var_class=var_class)
    with _scoped_lock:
        sp = _scoped.get(pv.full_name)
        if sp is None:
            sp = ScopedPvar(pv)
            _scoped[pv.full_name] = sp
        return sp


def scoped_items() -> List[ScopedPvar]:
    with _scoped_lock:
        return list(_scoped.values())


def scoped_snapshot() -> Dict[str, Dict[str, Any]]:
    """{name: {"global": v, "bands": {band: v}}} — the attribution
    view the metrics RPC exports.  global == sum(bands) always holds
    because every add goes through ScopedPvar.add."""
    out: Dict[str, Dict[str, Any]] = {}
    for sp in scoped_items():
        out[sp.full_name] = {"global": sp.read(),
                             "bands": {str(b): v for b, v in
                                       sp.nonzero_bands().items()}}
    return out


def current_band() -> int:
    """The calling thread's session band (0 when no MPI state)."""
    st = _statemod.maybe_current()
    return st.cid_band if st is not None else 0


class ScopedHist:
    """Per-session log2 latency histogram for serve-plane SLI gauges
    (queue-wait p99 and friends): one global histogram plus a lazy
    per-band shadow keyed by the same cid-band the ScopedPvars use.
    ``add_us`` is a bit_length bucket index and two integer bumps;
    band rows allocate under a lock the FIRST time a session appears
    — adds ride the serve control path (attach/run bookkeeping),
    never a traced rank hot path, so the lazy allocation is fine.
    Buckets are the trace module's fixed log2 bounds, so
    ``hist_percentiles`` reads these directly."""

    __slots__ = ("name", "total", "bands", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self.total = [0] * _trace.N_BUCKETS
        self.bands: Dict[int, List[int]] = {}
        self._lock = threading.Lock()

    def add_us(self, us: int, band: int = 0) -> None:
        b = int(us).bit_length()
        if b >= _trace.N_BUCKETS:
            b = _trace.N_BUCKETS - 1
        self.total[b] += 1
        band &= _BAND_MASK
        h = self.bands.get(band)
        if h is None:
            with self._lock:
                h = self.bands.setdefault(band,
                                          [0] * _trace.N_BUCKETS)
        h[b] += 1

    def band_hist(self, band: int) -> Optional[List[int]]:
        return self.bands.get(band & _BAND_MASK)

    def band_percentile(self, band: int, tag: str = "p99") -> int:
        h = self.band_hist(band)
        if h is None:
            return 0
        return int(hist_percentiles(h)[tag])


_scoped_hists: Dict[str, ScopedHist] = {}


def scoped_hist(name: str) -> ScopedHist:
    """Idempotent factory (the scoped_pvar model): one ScopedHist per
    full name, cached for the life of the process so bands never
    reset behind a reader's back."""
    with _scoped_lock:
        sh = _scoped_hists.get(name)
        if sh is None:
            sh = ScopedHist(name)
            _scoped_hists[name] = sh
        return sh


def scoped_hist_snapshot() -> Dict[str, Dict[str, Any]]:
    """{name: {"total": [...], "bands": {band: [...]}}} — the SLI
    attribution view the metrics RPC exports next to ``scoped``."""
    with _scoped_lock:
        hists = list(_scoped_hists.values())
    out: Dict[str, Dict[str, Any]] = {}
    for sh in hists:
        out[sh.name] = {"total": list(sh.total),
                        "bands": {str(b): list(h) for b, h in
                                  sh.bands.items() if sum(h)}}
    return out


# -- flight recorder --------------------------------------------------------

EV_ULFM_DETECT = 0
EV_ULFM_REVOKE = 1
EV_ULFM_AGREE = 2
EV_ULFM_SHRINK = 3
EV_RESPAWN = 4
EV_CKPT_COMMIT = 5
EV_CKPT_ABORT = 6
EV_CKPT_CRC_FALLBACK = 7
EV_ADMIT_REJECT = 8
EV_QUEUE_FULL = 9
EV_FT_INJECT = 10
EV_DVM_ATTACH = 11
EV_DVM_DETACH = 12
EV_DVM_HALT = 13
EV_DVM_RUN = 14
EV_DVM_PREEMPT = 15
EV_DVM_SHED = 16
EV_DVM_RESIZE = 17
EV_DVM_QUOTA = 18
EV_CTRL_ADJUST = 19
EV_KV_FAILOVER = 20
EV_DVM_REHYDRATE = 21
EV_DVM_REPLAY = 22
EV_HOST_LOST = 23
EV_HOST_RESPAWN = 24
# request-scoped tracing + hang doctor (DESIGN.md §23): the ``tid``
# argument is the 63-bit request trace id minted at DvmClient
# attach/run — traceview --job stitches these into one waterfall
EV_REQ_ATTACH = 25
EV_REQ_RUN = 26
EV_REQ_PARK = 27
EV_REQ_RESUME = 28
EV_WD_STALL = 29
EV_REQ_DRAIN = 30
# gray-failure health plane (DESIGN.md §24): hysteresis transitions
# on the host state machine plus the quarantine drain-and-migrate
EV_HOST_DEGRADED = 31
EV_HOST_QUARANTINE = 32
EV_HOST_RECOVERED = 33
EV_MIGRATE = 34
# silent-data-corruption plane (DESIGN.md §25): sampled check
# mismatch, bisection conviction of a rank/chip, retry-from-source
EV_SDC_MISMATCH = 35
EV_SDC_CONVICT = 36
EV_SDC_RETRY = 37

EVENT_NAMES = (
    "ulfm_detect", "ulfm_revoke", "ulfm_agree", "ulfm_shrink",
    "respawn_rejoin", "ckpt_commit", "ckpt_abort", "ckpt_crc_fallback",
    "dvm_reject", "dvm_queue_full", "ft_inject", "dvm_attach",
    "dvm_detach", "dvm_halt", "dvm_run", "dvm_preempt", "dvm_shed",
    "dvm_resize", "dvm_quota", "ctrl_adjust", "kv_failover",
    "dvm_rehydrate", "dvm_replay", "host_lost", "host_respawn",
    "req_attach", "req_run", "req_park", "req_resume", "wd_stall",
    "req_drain", "host_degraded", "host_quarantine", "host_recovered",
    "dvm_migrate", "sdc_mismatch", "sdc_convict", "sdc_retry",
)

# Per-type argument field names (positional a0..a3); a trailing "$"
# marks an interned-string id decoded at snapshot time — the same
# convention the Tracer uses for span args.
EVENT_FIELDS = (
    ("failed", "epoch"),                     # ulfm_detect
    ("cid",),                                # ulfm_revoke
    ("cid", "seq", "flag"),                  # ulfm_agree
    ("cid", "new_cid", "survivors", "us"),   # ulfm_shrink
    ("epoch", "replaced", "us"),             # respawn_rejoin
    ("epoch", "us"),                         # ckpt_commit
    ("epoch",),                              # ckpt_abort
    ("epoch",),                              # ckpt_crc_fallback
    ("sid", "reason$"),                      # dvm_reject
    ("depth",),                              # dvm_queue_full
    ("cls$", "scope$"),                      # ft_inject
    ("sid", "np", "us"),                     # dvm_attach
    ("sid",),                                # dvm_detach
    ("sessions", "jobs"),                    # dvm_halt
    ("sid", "code", "wall_ms"),              # dvm_run
    ("sid", "by_sid", "prio", "us"),         # dvm_preempt
    ("sid", "deadline_ms", "est_ms"),        # dvm_shed
    ("old", "new", "epoch"),                 # dvm_resize
    ("sid", "kind$", "val"),                 # dvm_quota
    ("margin_pct", "qdepth", "p99_us"),      # ctrl_adjust
    ("band", "ep$"),                         # kv_failover
    ("sessions", "jobs_done", "inc$"),       # dvm_rehydrate
    ("sid", "code"),                         # dvm_replay
    ("host", "ranks", "sessions"),           # host_lost
    ("host", "sessions", "ms"),              # host_respawn
    ("sid", "tid", "queued_us"),             # req_attach
    ("sid", "tid", "span", "wall_ms"),       # req_run
    ("sid", "tid"),                          # req_park
    ("sid", "tid", "us"),                    # req_resume
    ("sid", "tid", "run_ms", "est_ms"),      # wd_stall
    ("band", "epoch", "us"),                 # req_drain
    ("host", "score", "state"),              # host_degraded
    ("host", "score", "sessions"),           # host_quarantine
    ("host", "score"),                       # host_recovered
    ("sid", "host", "us"),                   # dvm_migrate
    ("cid", "seq", "kind$"),                 # sdc_mismatch
    ("rank", "host", "kind$"),               # sdc_convict
    ("cid", "seq", "rank"),                  # sdc_retry
)

# interned strings for event args (reason/cls/scope): the ring holds
# only integers; decode happens at snapshot, off the recording path
_strings: List[str] = []
_string_ids: Dict[str, int] = {}
_str_lock = threading.Lock()


def intern(s: str) -> int:
    sid = _string_ids.get(s)
    if sid is not None:
        return sid
    with _str_lock:
        sid = _string_ids.get(s)
        if sid is None:
            sid = len(_strings)
            _strings.append(s)
            _string_ids[s] = sid
        return sid


def intern_lookup(sid: int) -> str:
    return _strings[sid] if 0 <= sid < len(_strings) else str(sid)


class FlightRecorder:
    """Bounded ring of typed operational events as parallel integer
    columns (timestamp ns, type code, rank, four int args).  Recording
    is cold-path (failures, attaches, commits) but still cheap and
    thread-safe — pool threads, rank threads and the OOB thread all
    record into the one process ring."""

    __slots__ = ("cap", "head", "lock", "anchor_wall", "anchor_ns",
                 "_ts", "_type", "_rank", "_a0", "_a1", "_a2", "_a3")

    def __init__(self, cap: int, anchor: Optional[tuple] = None) -> None:
        self.cap = max(8, int(cap))
        self.head = 0  # total events ever recorded
        self.lock = threading.Lock()
        if anchor is not None:
            self.anchor_wall, self.anchor_ns = anchor
        else:
            # same two-clock anchor the Tracer captures: wall epoch +
            # monotonic perf counter sampled back to back
            self.anchor_wall = time.time()
            self.anchor_ns = time.perf_counter_ns()
        self._ts = array("q", [0] * self.cap)
        self._type = array("i", [0] * self.cap)
        self._rank = array("i", [0] * self.cap)
        self._a0 = array("q", [0] * self.cap)
        self._a1 = array("q", [0] * self.cap)
        self._a2 = array("q", [0] * self.cap)
        self._a3 = array("q", [0] * self.cap)

    @property
    def recorded(self) -> int:
        return self.head

    @property
    def dropped(self) -> int:
        return max(0, self.head - self.cap)

    def record(self, ev: int, a0: int = 0, a1: int = 0, a2: int = 0,
               a3: int = 0, rank: int = -1) -> None:
        with self.lock:
            i = self.head % self.cap
            self._ts[i] = time.perf_counter_ns()
            self._type[i] = ev
            self._rank[i] = rank
            self._a0[i] = a0
            self._a1[i] = a1
            self._a2[i] = a2
            self._a3[i] = a3
            self.head += 1

    def _wall(self, ts_ns: int) -> float:
        return self.anchor_wall + (ts_ns - self.anchor_ns) * 1e-9

    def snapshot(self, last: Optional[int] = None) -> List[dict]:
        """Events oldest-first as dicts in the trace-dump event shape
        (name/cat/ph/ts/args) so traceview merges them unchanged.
        ``last`` keeps only the newest N."""
        with self.lock:
            live = min(self.head, self.cap)
            start = self.head - live
            if last is not None and last >= 0:
                start = max(start, self.head - last)
            rows = []
            for n in range(start, self.head):
                i = n % self.cap
                rows.append((self._ts[i], self._type[i], self._rank[i],
                             self._a0[i], self._a1[i], self._a2[i],
                             self._a3[i]))
        out = []
        for ts, typ, rank, a0, a1, a2, a3 in rows:
            fields = EVENT_FIELDS[typ] if 0 <= typ < len(EVENT_FIELDS) \
                else ()
            args: Dict[str, Any] = {}
            vals = (a0, a1, a2, a3)
            for k, v in zip(fields, vals):
                if k.endswith("$"):
                    args[k[:-1]] = intern_lookup(v)
                else:
                    args[k] = v
            out.append({"name": EVENT_NAMES[typ]
                        if 0 <= typ < len(EVENT_NAMES) else str(typ),
                        "cat": "flight", "ph": "i",
                        "ts": self._wall(ts), "rank": rank,
                        "args": args})
        return out

    def trace_dump(self, last: Optional[int] = None) -> dict:
        """A traceview-loadable document (has rank + events; rank -1
        passes through clock correction uncorrected, like daemon
        dumps)."""
        return {"rank": -1, "flight": True,
                "recorded": self.recorded, "dropped": self.dropped,
                "capacity": self.cap,
                "anchor": {"wall_s": self.anchor_wall,
                           "perf_ns": self.anchor_ns},
                "events": self.snapshot(last)}

    def persist(self, path: str, comm=None) -> Optional[str]:
        """Write the ring as JSON.  With a communicator, write through
        the io layer (collective open, rank 0 lays down the bytes) —
        the failure path in an MPI world.  Without one (pool halt, no
        comm in scope) fall back to an atomic plain write.  Returns
        the path on success, None on best-effort failure."""
        try:
            data = json.dumps(self.trace_dump(), indent=1).encode()
            if comm is not None:
                import numpy as np

                from ompi_tpu import io as mpiio
                f = mpiio.open(comm, path,
                               mpiio.MODE_CREATE | mpiio.MODE_RDWR)
                try:
                    if comm.rank == 0:
                        f.write_at(0, np.frombuffer(bytearray(data),
                                                    dtype=np.uint8))
                finally:
                    f.close()
            else:
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "wb") as fh:
                    fh.write(data)
                os.replace(tmp, path)
            return path
        except (OSError, ValueError):
            return None


_recorder: Optional[FlightRecorder] = None
_rec_lock = threading.Lock()


def recorder() -> FlightRecorder:
    """The process flight recorder (lazy singleton; ring sized by
    ``obs_events_ring`` at first use; anchor adopted from the current
    or global tracer when one exists so flight timestamps share the
    trace timeline)."""
    global _recorder
    r = _recorder
    if r is None:
        with _rec_lock:
            r = _recorder
            if r is None:
                anchor = None
                tr = _trace.current_tracer()
                if tr is not None:
                    anchor = (tr.anchor_wall, tr.anchor_ns)
                r = FlightRecorder(_ring_var.value, anchor)
                _recorder = r
    return r


def record_event(ev: int, a0: int = 0, a1: int = 0, a2: int = 0,
                 a3: int = 0, rank: int = -1) -> None:
    """The one-call tap every subsystem uses (ulfm, respawn, ckpt,
    ft_inject, dvm).  Never raises."""
    try:
        recorder().record(ev, a0, a1, a2, a3, rank)
    except Exception:
        pass


# -- rank-local scrape on the progress tick ---------------------------------

# buffer layout (array('q')):
#   [0] generation (odd while a refresh is in flight — seqlock)
#   [1] perf_counter_ns of the last refresh
#   [2 : 2+n_hists*N_BUCKETS]  trace histogram counts, hist-major
_BUF_HDR = 2


class Scraper:
    """Rank-local snapshot of the trace latency histograms into a
    preallocated integer buffer, refreshed on the progress tick no
    more often than ``obs_scrape_interval_ms``.  The DVM metrics RPC
    reads ``buf`` from another thread; the odd/even generation stamp
    lets it detect a torn read and retry.  ``tick`` is hot-path
    audited: no allocation, no displays, integers only — and no clock
    read of its own: the progress engine passes the timestamp it
    already sampled for tracer tick timing (1-in-16 sweeps), so the
    scrape adds zero clock reads to the hot spin.  The first refresh
    snapshots every histogram; later refreshes copy ONE histogram
    round-robin (21 ints), so the amortized cost stays flat no matter
    how hot the interval is — per-histogram consistency is all the
    percentile math downstream needs, and a histogram is never staler
    than nhists intervals."""

    __slots__ = ("tracer", "interval_ns", "next_ns", "buf",
                 "nhists", "nbuckets", "ticks", "cursor")

    def __init__(self, tracer, interval_ms: int) -> None:
        self.tracer = tracer
        self.interval_ns = max(1, int(interval_ms)) * 1_000_000
        self.next_ns = 0
        self.nhists = len(_trace.HIST_NAMES)
        self.nbuckets = _trace.N_BUCKETS
        self.buf = array("q", [0] * (_BUF_HDR +
                                     self.nhists * self.nbuckets))
        self.ticks = 0
        self.cursor = 0

    def tick(self, now: int) -> int:
        if now < self.next_ns:
            return 0
        self.next_ns = now + self.interval_ns
        buf = self.buf
        hists = self.tracer.hists
        nb = self.nbuckets
        nh = self.nhists
        buf[0] += 1
        if self.ticks == 0:
            j = 2
            k = 0
            while k < nh:
                h = hists[k]
                m = 0
                while m < nb:
                    buf[j] = h[m]
                    j += 1
                    m += 1
                k += 1
        else:
            k = self.cursor
            h = hists[k]
            j = _BUF_HDR + k * nb
            m = 0
            while m < nb:
                buf[j] = h[m]
                j += 1
                m += 1
            k += 1
            if k >= nh:
                k = 0
            self.cursor = k
        buf[1] = now
        buf[0] += 1
        self.ticks += 1
        return 1

    def read_hists(self) -> Optional[List[List[int]]]:
        """Server-thread side: a consistent [hist][bucket] copy, or
        None when no refresh has landed yet (caller falls back to the
        tracer's own lists)."""
        for _ in range(8):
            g0 = self.buf[0]
            if g0 == 0 or g0 & 1:
                if g0 == 0:
                    return None
                continue
            flat = list(self.buf)
            if flat[0] != g0:
                continue
            nb = self.nbuckets
            out = []
            for k in range(self.nhists):
                off = _BUF_HDR + k * nb
                out.append(flat[off:off + nb])
            return out
        return None


# -- percentile gauges ------------------------------------------------------

PCT_TAGS = ("p50", "p90", "p99")
_PCT_QS = (0.50, 0.90, 0.99)


def hist_percentiles(hist) -> Dict[str, float]:
    """p50/p90/p99 in microseconds from a log2 latency histogram
    (bucket b holds durations in [2^(b-1), 2^b) us; the reported
    value is the bucket's upper bound — the resolution the histogram
    actually has)."""
    total = 0
    for c in hist:
        total += c
    out: Dict[str, float] = {}
    if total == 0:
        for tag in PCT_TAGS:
            out[tag] = 0.0
        return out
    for tag, q in zip(PCT_TAGS, _PCT_QS):
        target = q * total
        cum = 0
        for b, c in enumerate(hist):
            cum += c
            if cum >= target:
                out[tag] = _trace.bucket_upper_us(b)
                break
    return out


def _pct_getter(which: int, qi: int):
    def get() -> int:
        tr = _trace.current_tracer()
        if tr is None:
            return 0
        tag = PCT_TAGS[qi]
        return int(hist_percentiles(tr.hists[which])[tag])
    return get


# -- registration (idempotent across looped worlds) -------------------------

_registered = False
_reg_lock = threading.Lock()


def register_pvars() -> None:
    """Register the obs gauges exactly once per process (the pstat
    idempotency model): looped worlds re-enter mpi_init, and MPI_T
    requires that pvar indices never move once handed out — a second
    registration pass must be a no-op, not a duplicate set."""
    global _registered
    with _reg_lock:
        if _registered:
            return
        _registered = True
        for wi, hname in enumerate(_trace.HIST_NAMES):
            for qi, tag in enumerate(PCT_TAGS):
                registry.register_pvar(
                    "obs", tag, hname, var_class="level",
                    getter=_pct_getter(wi, qi),
                    help=f"{tag} of the {hname} latency histogram "
                         f"(us, log2-bucket upper bound)")
        registry.register_pvar(
            "obs", "events", "recorded", var_class="counter",
            getter=lambda: recorder().recorded,
            help="Flight-recorder events recorded (kept + dropped)")
        registry.register_pvar(
            "obs", "events", "dropped", var_class="counter",
            getter=lambda: recorder().dropped,
            help="Flight-recorder events overwritten (ring wrapped)")
        registry.register_pvar(
            "obs", "", "scrapes", var_class="counter",
            getter=_scrapes_getter,
            help="Histogram snapshots taken by this rank's scraper")
        # critical-path profiler gauges (DESIGN.md §18): live view of
        # the phase-span totals tools/critpath.py analyzes offline
        registry.register_pvar(
            "obs", "critpath", "phase_us", var_class="level",
            getter=_critpath_phase_us,
            help="Cumulative us recorded per dispatch phase "
                 "(rendezvous/pack/dispatch/execute/unpack/compile) "
                 "by the phase profiler (trace_phase_enable)")
        registry.register_pvar(
            "obs", "critpath", "gating_phase", var_class="level",
            getter=_gating_phase,
            help="Phase with the largest cumulative recorded time on "
                 "this rank — the local dispatch-tax leader")
        registry.register_pvar(
            "obs", "straggler", "skew_us", var_class="level",
            getter=_straggler_skew_us,
            help="p90 of the rendezvous-wait histogram (us): how long "
                 "this rank typically waits for its slowest peer")


def _critpath_phase_us() -> Dict[str, int]:
    st = _statemod.maybe_current()
    tr = st.tracer if st is not None else None
    return tr.phase_totals() if tr is not None else {}


def _gating_phase() -> str:
    best = ""
    best_v = -1
    for label, us in _critpath_phase_us().items():
        if us > best_v:
            best, best_v = label, us
    return best


def _straggler_skew_us() -> int:
    st = _statemod.maybe_current()
    tr = st.tracer if st is not None else None
    if tr is None:
        return 0
    return int(hist_percentiles(tr.hists[_trace.HIST_RDV_WAIT])["p90"])


def _scrapes_getter() -> int:
    st = _statemod.maybe_current()
    if st is None:
        return 0
    sc = st.extra.get("obs_scraper")
    return sc.ticks if sc is not None else 0


def attach(state) -> None:
    """mpi_init hook (rides next to trace.attach / pstat): register
    the gauges, make sure the recorder exists (adopting this world's
    tracer anchor when it is first built here), and hang a Scraper off
    the progress engine when scraping is enabled and a tracer is on.
    With trace off or interval 0 the progress engine pays exactly one
    is-None check — the same contract as the tracer slot."""
    register_pvars()
    recorder()
    # arm (or refresh) the SDC-detection plane from its knobs — the
    # coll meet path reads the integrity module's cached flag only
    from ompi_tpu.obs import integrity as _integrity
    _integrity.refresh()
    iv = _interval_var.value
    if iv and iv > 0 and state.tracer is not None:
        sc = Scraper(state.tracer, iv)
        state.extra["obs_scraper"] = sc
        state.progress.obs = sc


def detach(state) -> None:
    """mpi_finalize hook: stop the scrape tick for this world.  The
    recorder and registered gauges survive (process-scoped; the next
    looped world reuses them)."""
    state.progress.obs = None
    state.extra.pop("obs_scraper", None)


# -- local metrics + Prometheus exposition ----------------------------------

def local_metrics(events: int = 16, tracer=None,
                  prefix: Optional[str] = None) -> Dict[str, Any]:
    """Process-local metrics document: the full pvar registry, the
    latency histograms + derived percentiles, scoped-counter
    attribution, and the flight-recorder tail.  Used by the tpud
    ``metrics`` OOB op and as the building block of the DVM RPC.
    ``prefix`` narrows the pvar snapshot to one subsystem (a fleet
    scraper polling ``dvm_``/``ctrl_`` state does not ship the whole
    registry per node per tick)."""
    from ompi_tpu import mpit
    if tracer is None:
        tracer = _trace.current_tracer()
    hists: Dict[str, List[int]] = {}
    pcts: Dict[str, Dict[str, float]] = {}
    if tracer is not None:
        for name, h in zip(_trace.HIST_NAMES, tracer.hists):
            hists[name] = list(h)
            pcts[name] = hist_percentiles(h)
    return {
        "ts": time.time(),
        "pvars": mpit.pvar_snapshot(prefix),
        "hists": hists,
        "percentiles": pcts,
        "scoped": scoped_snapshot(),
        "scoped_hists": scoped_hist_snapshot(),
        "events": recorder().snapshot(events),
    }


def _prom_escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"')


def prometheus_text(metrics: Dict[str, Any],
                    prefix: str = "ompi_tpu") -> str:
    """Prometheus text exposition format (version 0.0.4) rendered from
    a metrics document: scalar pvars as counters/gauges, scoped
    counters as ONE grouped family each — the global sum plus a
    ``session`` label per cid band (0.0.4 requires all samples of a
    family in one group, so scoped names are skipped in the plain
    pvar sweep and rendered here) — per-session SLI histograms as
    labeled percentile gauges, and the latency percentile gauges as a
    labeled ``latency_us`` family."""
    classes: Dict[str, str] = {}
    for p in registry.pvars_in_registration_order():
        classes[p.full_name] = p.var_class
    scoped = metrics.get("scoped", {})
    lines: List[str] = []
    for name, val in metrics.get("pvars", {}).items():
        if name in scoped:
            continue  # rendered grouped with its session series below
        if isinstance(val, bool) or not isinstance(val, (int, float)):
            continue
        typ = "counter" if classes.get(name) == "counter" else "gauge"
        lines.append(f"# TYPE {prefix}_{name} {typ}")
        lines.append(f"{prefix}_{name} {val}")
    for sname, sv in scoped.items():
        typ = "counter" if classes.get(sname, "counter") == "counter" \
            else "gauge"
        lines.append(f"# TYPE {prefix}_{sname} {typ}")
        g = sv.get("global")
        if isinstance(g, (int, float)) and not isinstance(g, bool):
            lines.append(f"{prefix}_{sname} {g}")
        for band, v in sorted(sv.get("bands", {}).items(),
                              key=lambda kv: int(kv[0])):
            lines.append(f'{prefix}_{sname}'
                         f'{{session="{_prom_escape(str(band))}"}} {v}')
    for hname, hv in sorted(metrics.get("scoped_hists", {}).items()):
        lines.append(f"# TYPE {prefix}_{hname} gauge")
        tot = hist_percentiles(hv.get("total") or [])
        for tag in PCT_TAGS:
            lines.append(f'{prefix}_{hname}{{q="{tag}"}} '
                         f'{tot.get(tag, 0.0)}')
        for band, h in sorted(hv.get("bands", {}).items(),
                              key=lambda kv: int(kv[0])):
            p = hist_percentiles(h)
            for tag in PCT_TAGS:
                lines.append(f'{prefix}_{hname}'
                             f'{{session="{_prom_escape(str(band))}",'
                             f'q="{tag}"}} {p.get(tag, 0.0)}')
    # per-host gray-failure health rows (DESIGN.md §24): numeric
    # state (0 healthy / 1 degraded / 2 quarantined) + score as one
    # host-labeled family each, so alerting can key on max() directly
    hh = metrics.get("host_health")
    if hh:
        lines.append(f"# TYPE {prefix}_host_health_state gauge")
        for row in hh:
            st = row.get("state", "healthy")
            code = st if isinstance(st, int) else \
                {"healthy": 0, "degraded": 1, "quarantined": 2}.get(st, 0)
            lines.append(f'{prefix}_host_health_state'
                         f'{{host="{row.get("host", 0)}"}} {code}')
        lines.append(f"# TYPE {prefix}_host_health_score gauge")
        for row in hh:
            lines.append(f'{prefix}_host_health_score'
                         f'{{host="{row.get("host", 0)}"}} '
                         f'{row.get("score", 0)}')
    pct = metrics.get("percentiles", {})
    if pct:
        lines.append(f"# TYPE {prefix}_latency_us gauge")
        for hname in sorted(pct):
            for tag in PCT_TAGS:
                v = pct[hname].get(tag, 0.0)
                lines.append(f'{prefix}_latency_us'
                             f'{{hist="{_prom_escape(hname)}",'
                             f'q="{tag}"}} {v}')
    return "\n".join(lines) + "\n"
