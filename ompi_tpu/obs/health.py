"""Gray-failure health plane (docs/DESIGN.md §24).

Every fault plane before this one (ULFM shrink, respawn, host
domains, KV failover) models failure as *death detected by silence*.
Real fleets mostly fail the other way — a host stays alive but runs
10x slow (thermal throttle, flaky NIC, contended disk), drags every
collective it participates in down to its speed, and never trips a
liveness grace (Huang et al., HotOS'17; Dean & Barroso, CACM'13).

This module scores every host failure domain from signals the stack
already emits and runs them through a hysteresis state machine::

    healthy (0)  ->  degraded (1)  ->  quarantined (2)
        ^________________|__________________|   (recovery, one step
                                                 per clear streak)

Signals (all integer EWMAs over preallocated per-host arrays):

  * heartbeat inter-arrival EWMA + jitter, sampled where the pool's
    ``host_beat`` op already stamps liveness — the primary signal.
    An OVERDUE beat counts immediately (``now - last`` replaces the
    EWMA once it exceeds 3x), so detection never waits for a slow
    beat to actually arrive;
  * cross-rank ``rdv_wait`` skew from the critpath phase tables
    (fed via note_rdv_skew — corroboration, attributed to the host
    the beat estimator already suspects);
  * per-session queue-wait SLIs and KV round-trip EWMA
    (note_queue_wait / note_kv_rtt);
  * io stall counts (note_io_stall).

The per-tick sweep — ``HealthPlane.tick`` — is hotpath_audit-enforced
like DVMServer._host_tick it rides beside: pure integer arithmetic
over preallocated lists, no allocation, no formatting.  Everything
that allocates (events, pvars, mitigation) runs in the cold half
(``collect``), driven off the pool heartbeat loop.

Mitigation ladder (applied by tools/dvm + serve/controller):

  * degraded: stop placing NEW sessions on the host, reroute the
    hierarchical-collective leader hop off it (coll/pipeline), widen
    its deadlines/watchdog grace adaptively instead of shedding;
  * quarantined: drain-and-migrate — park resident sessions (the
    PR 12 preemption machinery), restore from checkpoint tiers onto
    healthy domains at the next bring-up, optionally cycle the
    offending domain (health_respawn) — never a failed job;
  * recovery walks back one state per sustained-clean streak.

The adaptive host-liveness grace also lives here: the shared
``HostBeatEstimator`` derives each host's dead-declaration grace
from its own beat EWMA + jitter, floored at the static
``3*dvm_heartbeat_s + oob_host_grace_s`` horizon — a jittery-but-
alive host is not declared dead while a crisp host keeps the tight
floor.  The DVM pool sweep and the HNP beat monitor (tools/plm)
consume the same estimator.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ompi_tpu.mca.params import registry

_enable_var = registry.register(
    "health", "", "enable", 1, int,
    help="Arm the gray-failure health plane on multi-host pools "
         "(score hosts, degrade/quarantine, mitigate); 0 leaves only "
         "the death-by-silence liveness plane")
_tick_ms_var = registry.register(
    "health", "", "tick_ms", 250, int,
    help="Health-plane scoring period (the audited tick rides the "
         "pool heartbeat loop, so the effective period is "
         "max(health_tick_ms, dvm_heartbeat_s))")
_degrade_var = registry.register(
    "health", "", "degrade_score", 40, int,
    help="Composite score (0-100) at or above which a host's trip "
         "streak runs toward `degraded`")
_quarantine_var = registry.register(
    "health", "", "quarantine_score", 75, int,
    help="Composite score (0-100) at or above which a degraded "
         "host's trip streak runs toward `quarantined`")
_trip_var = registry.register(
    "health", "", "trip_ticks", 3, int,
    help="Consecutive over-threshold ticks before the state machine "
         "escalates one step (hysteresis against transient blips)")
_clear_var = registry.register(
    "health", "", "clear_ticks", 8, int,
    help="Consecutive under-threshold ticks before the state machine "
         "recovers one step")
_widen_var = registry.register(
    "health", "", "widen_pct", 300, int,
    help="Deadline widening for sessions touching a degraded host: "
         "the client deadline is treated as this percent of itself "
         "at shed admission (degraded hosts run slow on purpose — "
         "widen, don't shed)")
_grace_k_var = registry.register(
    "health", "", "grace_jitter_k", 4, int,
    help="Adaptive host-liveness grace: jitter multiplier in "
         "grace = max(floor, 6*beat_EWMA + k*jitter)")
_skew_budget_var = registry.register(
    "health", "", "skew_budget_us", 50000, int,
    help="Cross-rank rdv_wait skew EWMA that scores 100 health "
         "points (corroboration signal weighting)")
_respawn_var = registry.register(
    "health", "", "respawn", 0, int,
    help="After a quarantined host is fully drained, cycle the "
         "domain (kill_host + respawn_host) so a fresh agent rejoins "
         "clean; 0 leaves the offender quarantined for the operator")

_pv_host_health = registry.register_pvar(
    "fleet", "", "host_health", var_class="level",
    help="Hosts currently NOT healthy (degraded + quarantined) — the "
         "gray-failure plane's live gauge")
_pv_quarantines = registry.register_pvar(
    "fleet", "", "quarantines",
    help="Host quarantine transitions declared by the health plane "
         "(lifetime; a healthy fleet keeps this at 0)")
_pv_migrations = registry.register_pvar(
    "fleet", "", "migrations",
    help="Sessions drained off a quarantined host (parked + replayed "
         "onto healthy domains — never a failed job)")

#: state machine encoding (ints on the hot path, names for humans)
HEALTHY, DEGRADED, QUARANTINED = 0, 1, 2
STATE_NAMES = ("healthy", "degraded", "quarantined")

#: leader-hop penalty consulted by coll/pipeline._hier_plan: split
#: keys of ranks resident on a degraded/quarantined host are biased
#: past every healthy rank's, so the intra-slice leader (intra.rank 0
#: = smallest key) lands on a healthy host whenever the slice has one
_degraded_mask = 0


def set_degraded_mask(mask: int) -> None:
    global _degraded_mask
    _degraded_mask = int(mask)


def node_degraded(node_id: int) -> bool:
    """True when the health plane holds this host domain at degraded
    or worse — the hier leader-reroute gate (process-global: resident
    DVM rank-threads share the pool process)."""
    return bool(_degraded_mask >> max(0, int(node_id)) & 1)


class HostBeatEstimator:
    """Per-host beat inter-arrival EWMA + jitter, all int ns — the
    shared estimator behind the ADAPTIVE host-liveness grace
    (satellite of DESIGN.md §24).  ``note(h, now_ns)`` on every beat;
    ``grace_ns(h)`` answers with::

        max(floor_ns, mult * ewma + health_grace_jitter_k * jitter)

    With an agent pacing itself at grace/6 (tools/tpud), a crisp host
    sits exactly at the floor; a jittery-but-alive host widens its own
    grace instead of being declared dead.  Consumed by both the DVM
    pool sweep (_host_tick reads the preallocated grace list) and the
    HNP beat monitor (tools/plm._beat_monitor)."""

    def __init__(self, hosts: int, floor_ns: int,
                 mult: int = 6) -> None:
        n = max(1, int(hosts))
        self.hosts = n
        self.floor_ns = max(1, int(floor_ns))
        # grace = mult * EWMA + k * jitter: mult mirrors the
        # consumer's own beat pacing (the DVM agent beats at grace/6
        # -> 6; the HNP daemon beats at interval with a budget-beat
        # horizon -> budget), so a CRISP host sits exactly at the
        # static floor and only genuine jitter widens anything
        self.mult = max(1, int(mult))
        self.last_ns = [0] * n    # last beat stamp (0 = never)
        self.ewma_ns = [0] * n    # inter-arrival EWMA
        self.jitter_ns = [0] * n  # EWMA of |delta - ewma|
        # preallocated adaptive grace, floor-seeded: _host_tick (and
        # the plm monitor) index this list on their sweep paths
        self.grace = [self.floor_ns] * n

    def note(self, h: int, now_ns: int) -> None:
        """One beat arrived from host ``h`` (cold path: the host_beat
        op / HNP dispatch)."""
        if not 0 <= h < self.hosts:
            return
        last = self.last_ns[h]
        self.last_ns[h] = now_ns
        if last <= 0:
            return
        delta = now_ns - last
        if delta <= 0:
            return
        ew = self.ewma_ns[h]
        if ew <= 0:
            ew = delta
        else:
            ew += (delta - ew) >> 1  # alpha 1/2: track mode shifts fast
        self.ewma_ns[h] = ew
        dev = delta - ew
        if dev < 0:
            dev = -dev
        jit = self.jitter_ns[h]
        jit += (dev - jit) >> 1
        self.jitter_ns[h] = jit
        k = max(0, _grace_k_var.value)
        g = self.mult * ew + k * jit
        if g < self.floor_ns:
            g = self.floor_ns
        self.grace[h] = g

    def grace_ns(self, h: int) -> int:
        if not 0 <= h < self.hosts:
            return self.floor_ns
        return self.grace[h]


class HealthPlane:
    """Score -> hysteresis -> mitigation flags for every host domain.

    ``tick(now_ns)`` is the audited hot half (rides the pool's
    _host_tick sweep): integer scoring over preallocated arrays,
    state transitions latched into ``pending``.  ``collect()`` is the
    cold half: drains pending transitions for the server's mitigation
    ladder and maintains the fleet_* pvars."""

    def __init__(self, hosts: int, expect_beat_ns: int,
                 floor_grace_ns: int) -> None:
        n = max(1, int(hosts))
        self.hosts = n
        self.enabled = 1 if _enable_var.value else 0
        self.expect_ns = max(1, int(expect_beat_ns))
        self.est = HostBeatEstimator(n, floor_grace_ns)
        self.grace_ns = self.est.grace  # alias for the _host_tick sweep
        self.tick_ns = max(1, _tick_ms_var.value) * 1_000_000
        self.next_ns = 0
        self.ticks = 0
        # corroboration signal EWMAs (us), fed by note_* (cold paths)
        self.rdv_skew_us = [0] * n
        self.qwait_us = [0] * n
        self.kv_rtt_us = [0] * n
        self.io_stalls = [0] * n
        # sdc convictions (DESIGN.md §25): unlike the graded signals
        # above, a conviction is decisive evidence — one poisons the
        # host straight to quarantined, no hysteresis
        self.sdc = [0] * n
        # state machine (all preallocated ints)
        self.score = [0] * n
        self.state = [0] * n
        self.up_streak = [0] * n
        self.down_streak = [0] * n
        self.pending = [0] * n  # transition latched, cold half collects
        self.excluded = [0] * n  # dead/rehydrating: server-maintained
        self.degraded_n = 0      # hosts at state >= 1 (controller reads)
        self.quarantined_n = 0
        self.sdc_n = 0           # hosts carrying an sdc conviction

    # -- signal ingestion (cold paths) ---------------------------------

    def note_beat(self, h: int, now_ns: int) -> None:
        """A host_beat op landed: feed the shared estimator (which
        also maintains the adaptive per-host grace)."""
        self.est.note(h, now_ns)

    def note_rdv_skew(self, h: int, us: int) -> None:
        """Cross-rank rendezvous-wait skew attributed to host ``h``
        (critpath phase tables / straggler gauges)."""
        if 0 <= h < self.hosts and us >= 0:
            cur = self.rdv_skew_us[h]
            self.rdv_skew_us[h] = cur + ((int(us) - cur) >> 1)

    def note_queue_wait(self, h: int, us: int) -> None:
        if 0 <= h < self.hosts and us >= 0:
            cur = self.qwait_us[h]
            self.qwait_us[h] = cur + ((int(us) - cur) >> 2)

    def note_kv_rtt(self, h: int, us: int) -> None:
        if 0 <= h < self.hosts and us >= 0:
            cur = self.kv_rtt_us[h]
            self.kv_rtt_us[h] = cur + ((int(us) - cur) >> 2)

    def note_io_stall(self, h: int, n: int = 1) -> None:
        if 0 <= h < self.hosts and n > 0:
            self.io_stalls[h] += int(n)

    def note_sdc(self, h: int, n: int = 1) -> None:
        """An integrity conviction (obs/integrity) landed on host
        ``h``: decisive — the next tick quarantines the host outright
        (a chip computing wrong answers cannot be widened around)."""
        if 0 <= h < self.hosts and n > 0:
            self.sdc[h] += int(n)
            c = 0
            for x in self.sdc:
                if x > 0:
                    c += 1
            self.sdc_n = c

    # -- the audited hot half ------------------------------------------

    def tick(self, now: int) -> int:
        # hotpath_audit-enforced (tools/hotpath_audit): rides the pool
        # heartbeat sweep next to DVMServer._host_tick.  Integer
        # compares and divides over preallocated lists only — no
        # allocation, no formatting; transitions are latched into
        # `pending` for the cold collect.
        if self.enabled == 0 or now < self.next_ns:
            return 0
        self.next_ns = now + self.tick_ns
        self.ticks += 1
        expect = self.expect_ns
        last = self.est.last_ns
        ewma = self.est.ewma_ns
        jit = self.est.jitter_ns
        skew = self.rdv_skew_us
        skew_budget = _skew_budget_var.value
        if skew_budget <= 0:
            skew_budget = 50000
        d_th = _degrade_var.value
        q_th = _quarantine_var.value
        trip = _trip_var.value
        if trip < 1:
            trip = 1
        clear = _clear_var.value
        if clear < 1:
            clear = 1
        score = self.score
        state = self.state
        ups = self.up_streak
        downs = self.down_streak
        pend = self.pending
        excl = self.excluded
        sdc = self.sdc
        n = self.hosts
        hit = 0
        deg = 0
        quar = 0
        sdcn = 0
        h = 0
        while h < n:
            if excl[h] == 1:
                # dead / rehydrating domains belong to the liveness
                # plane, not the gray-failure plane
                score[h] = 0
                ups[h] = 0
                h += 1
                continue
            if sdc[h] > 0:
                # sdc conviction: decisive, no hysteresis — wrong
                # answers are worse than slow ones, and the conviction
                # itself proves the chip is alive (DESIGN.md §25)
                sdcn += 1
                score[h] = 100
                ups[h] = 0
                downs[h] = 0
                if state[h] != QUARANTINED:
                    state[h] = QUARANTINED
                    pend[h] = 1
                    hit += 1
                deg += 1
                quar += 1
                h += 1
                continue
            if last[h] == 0:
                # never-beaten domains have no gray-failure evidence
                score[h] = 0
                ups[h] = 0
                h += 1
                continue
            # effective beat interval: the EWMA, or the OVERDUE gap if
            # a beat is already 3x late — detection must not wait for
            # a 10x-slowed beat to actually arrive
            eff = ewma[h]
            if eff <= 0:
                eff = expect
            since = now - last[h]
            if since > 3 * eff and since > 3 * expect:
                eff = since
            # slowness: percent of expected interval past 1x, capped
            s1 = eff * 100 // expect - 100
            if s1 < 0:
                s1 = 0
            elif s1 > 100:
                s1 = 100
            # jitter: half-weight corroboration
            s2 = jit[h] * 100 // expect
            if s2 > 50:
                s2 = 50
            # rdv_wait skew: half-weight corroboration
            s3 = skew[h] * 50 // skew_budget
            if s3 > 50:
                s3 = 50
            sc = s1 + (s2 >> 1) + (s3 >> 1)
            if sc > 100:
                sc = 100
            score[h] = sc
            cur = state[h]
            want = cur
            if sc >= q_th:
                want = QUARANTINED
            elif sc >= d_th:
                want = DEGRADED
            else:
                want = HEALTHY
            if want > cur:
                downs[h] = 0
                ups[h] += 1
                if ups[h] >= trip:
                    ups[h] = 0
                    state[h] = cur + 1  # one ladder rung per streak
                    pend[h] = 1
                    hit += 1
            elif want < cur:
                ups[h] = 0
                downs[h] += 1
                if downs[h] >= clear:
                    downs[h] = 0
                    state[h] = cur - 1
                    pend[h] = 1
                    hit += 1
            else:
                ups[h] = 0
                downs[h] = 0
            if state[h] >= DEGRADED:
                deg += 1
            if state[h] == QUARANTINED:
                quar += 1
            h += 1
        self.degraded_n = deg
        self.quarantined_n = quar
        self.sdc_n = sdcn
        return hit

    # -- the cold half --------------------------------------------------

    def collect(self) -> List[int]:
        """Drain latched transitions (host ids, in order).  The caller
        (DVMServer._health_collect) applies the mitigation ladder; the
        pvars and the leader-reroute mask are maintained here."""
        out: List[int] = []
        mask = 0
        nonhealthy = 0
        for h in range(self.hosts):
            if self.pending[h] == 1:
                self.pending[h] = 0
                out.append(h)
            if self.state[h] >= DEGRADED and self.excluded[h] == 0:
                mask |= 1 << h
                nonhealthy += 1
        set_degraded_mask(mask)
        lvl = _pv_host_health.read()
        if nonhealthy != lvl:
            _pv_host_health.add(nonhealthy - lvl)
        return out

    def note_quarantine(self) -> None:
        _pv_quarantines.add(1)

    def note_migration(self, n: int = 1) -> None:
        _pv_migrations.add(n)

    def exclude(self, h: int, flag: bool) -> None:
        """Dead / rehydrating domains leave the scoring sweep (the
        liveness plane owns them); re-inclusion resets the machine so
        a respawned host starts healthy with fresh estimates."""
        if not 0 <= h < self.hosts:
            return
        self.excluded[h] = 1 if flag else 0
        if flag:
            self.reset_host(h)

    def reset_host(self, h: int) -> None:
        if not 0 <= h < self.hosts:
            return
        self.state[h] = HEALTHY
        self.score[h] = 0
        self.up_streak[h] = 0
        self.down_streak[h] = 0
        self.pending[h] = 0
        self.rdv_skew_us[h] = 0
        self.qwait_us[h] = 0
        self.kv_rtt_us[h] = 0
        self.io_stalls[h] = 0
        self.sdc[h] = 0
        c = 0
        for x in self.sdc:
            if x > 0:
                c += 1
        self.sdc_n = c
        self.est.last_ns[h] = 0
        self.est.ewma_ns[h] = 0
        self.est.jitter_ns[h] = 0
        self.est.grace[h] = self.est.floor_ns

    def placement_ok(self, h: int) -> bool:
        """May NEW sessions place ranks on host ``h``?  Degraded and
        quarantined domains stop taking new placements (existing
        residents are handled by the mitigation ladder)."""
        if not 0 <= h < self.hosts:
            return False
        return self.state[h] == HEALTHY and self.excluded[h] == 0

    def widen_pct(self) -> int:
        """Deadline widening applied at shed admission for sessions
        touching a degraded host (>= 100; 100 = no widening)."""
        return max(100, _widen_var.value)

    def tripped(self, h: int) -> List[str]:
        """Signal names currently contributing to host ``h``'s score
        (diagnostics: top's health column, the doctor verdict)."""
        out: List[str] = []
        if not 0 <= h < self.hosts:
            return out
        if self.sdc[h] > 0:
            out.append("sdc")
        expect = self.expect_ns
        ew = self.est.ewma_ns[h]
        if ew > 0 and ew * 100 // expect > 150:
            out.append("beat_slow")
        if self.est.jitter_ns[h] * 100 // expect > 50:
            out.append("beat_jitter")
        budget = max(1, _skew_budget_var.value)
        if self.rdv_skew_us[h] * 100 // budget > 50:
            out.append("rdv_skew")
        if self.qwait_us[h] > 0 and out:
            out.append("queue_wait")
        if self.io_stalls[h] > 0:
            out.append("io_stall")
        return out

    def snapshot(self) -> List[Dict[str, Any]]:
        """Per-host health rows for the metrics RPC / top / doctor."""
        rows: List[Dict[str, Any]] = []
        for h in range(self.hosts):
            rows.append({
                "host": h,
                "state": STATE_NAMES[self.state[h]],
                "score": self.score[h],
                "beat_ewma_ms": self.est.ewma_ns[h] // 1_000_000,
                "beat_jitter_ms": self.est.jitter_ns[h] // 1_000_000,
                "grace_ms": self.est.grace[h] // 1_000_000,
                "rdv_skew_us": self.rdv_skew_us[h],
                "sdc": self.sdc[h],
                "signals": self.tripped(h),
                "excluded": bool(self.excluded[h]),
            })
        return rows
