"""Request-scoped trace context for the serving plane (DESIGN.md §23).

OpenTelemetry-style context propagation rebuilt on the surfaces the
repo already has: ``mint()`` produces a (trace id, parent span) pair
at ``DvmClient.attach``/``run``; the ids ride the length-framed DVM
RPC as two plain ints, land on the ``_Session`` server-side, are
stamped into each resident rank's Tracer as a per-job tag
(``Tracer.req_mark`` — two integer stores, the §16 cid-band cost
model), published into the session's KV namespace so remote-host
components can correlate, and annotate the admission / park / resume
/ shed / preempt flight events.  ``tools/traceview.py --job <tid>``
stitches all of it into one per-request waterfall.

Ids are 63-bit positive integers (they must fit the flight recorder's
and the tracer's signed ``array('q')`` columns) built from wall
nanoseconds, the pid, and a process-monotonic counter — unique across
the client fleet without an RNG, and meaningless to guess, which is
all a correlation key needs.  Span ids are small per-process
counters: a (tid, span) pair names one causal step under a request.

Everything is gated on ``obs_reqtrace_enable`` (off by default): when
off, ``mint()`` is never called, no RPC field is added, and the rank
hot path keeps its two-int-store worst case only for jobs that carry
a context.
"""

from __future__ import annotations

import itertools
import os
import time
from typing import Tuple

from ompi_tpu.mca.params import registry

_enable_var = registry.register(
    "obs", "reqtrace", "enable", False, bool,
    help="Mint a request trace context (trace id + parent span) at "
         "DvmClient attach/run and propagate it end-to-end: RPC "
         "fields, admission/park/resume flight events, per-job rank "
         "tracer tags, KV namespace, ckpt drain events.  Off = no "
         "context is minted and runs carry tag 0")

_MASK63 = (1 << 63) - 1

_span_n = itertools.count(1)


def enabled() -> bool:
    return bool(_enable_var.value)


def mint() -> Tuple[int, int]:
    """A fresh (trace id, parent span) pair.  The tid folds wall
    nanoseconds, the pid and a process counter into 63 bits; the span
    is this process's next span id.  Cold path (once per attach/run),
    so two clock-free int reads plus one time_ns is fine."""
    n = next(_span_n)
    tid = ((time.time_ns() & 0xFFFFFFFFFF) << 23) \
        ^ ((os.getpid() & 0x7FFFFF) << 16) ^ (n & 0xFFFF)
    tid &= _MASK63
    if tid == 0:
        tid = 1  # 0 means "no context" everywhere downstream
    return tid, n


def next_span() -> int:
    """The next span id under an existing trace (one per run RPC)."""
    return next(_span_n)


def fmt(tid: int) -> str:
    """Canonical display form of a trace id (hex, the --job syntax)."""
    return f"0x{tid:x}"


def parse(text: str) -> int:
    """Parse a --job argument: hex with 0x prefix, or decimal."""
    s = str(text).strip()
    return int(s, 16) if s.lower().startswith("0x") else int(s)
