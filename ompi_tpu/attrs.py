"""Attribute keyvals with copy/delete callbacks.

Re-design of ompi/attribute (ref: ompi/attribute/attribute.c — one
keyval registry serving comms, wins and datatypes; copy callbacks run
on dup, delete callbacks on overwrite/delete/free).

A keyval is an integer handle bound to (copy_fn, delete_fn,
extra_state).  copy_fn(obj, keyval, extra_state, value) -> value or
None (None = don't propagate, the flag=0 case); delete_fn(obj,
keyval, value, extra_state).  Predefined world attributes (TAG_UB,
WTIME_IS_GLOBAL, UNIVERSE_SIZE) use negative handles.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Dict, Optional, Tuple

# predefined keyval handles (ref: mpi.h MPI_TAG_UB et al.)
TAG_UB = -101
HOST = -102
IO = -103
WTIME_IS_GLOBAL = -104
UNIVERSE_SIZE = -106
APPNUM = -107
LASTUSEDCODE = -105

_registry: Dict[int, Tuple[Optional[Callable], Optional[Callable], Any]] = {}
_refs: Dict[int, int] = {}    # live attachments per keyval
_freed: set = set()           # freed-but-still-attached keyvals
_counter = itertools.count(1000)
_lock = threading.Lock()


def create_keyval(copy_fn: Optional[Callable] = None,
                  delete_fn: Optional[Callable] = None,
                  extra_state: Any = None) -> int:
    """MPI_{Comm,Win,Type}_create_keyval."""
    with _lock:
        kv = next(_counter)
        _registry[kv] = (copy_fn, delete_fn, extra_state)
    return kv


def free_keyval(keyval: int) -> None:
    """MPI_*_free_keyval: freeing is deferred while attributes are
    still attached — the (copy_fn, delete_fn, extra) entry stays live
    so later dup/free of holding objects still runs the callbacks
    (ref: ompi/attribute/attribute.c ompi_attr_free_keyval)."""
    with _lock:
        if keyval not in _registry:
            return
        if _refs.get(keyval, 0) > 0:
            _freed.add(keyval)
        else:
            _registry.pop(keyval, None)


def _ref(keyval: int, delta: int) -> None:
    if keyval < 0:
        return
    with _lock:
        n = _refs.get(keyval, 0) + delta
        if n <= 0:
            _refs.pop(keyval, None)
            if keyval in _freed:
                _freed.discard(keyval)
                _registry.pop(keyval, None)
        else:
            _refs[keyval] = n


def _entry(keyval: int):
    with _lock:
        e = _registry.get(keyval)
    if e is None and keyval >= 0:
        raise ValueError(f"invalid attribute keyval {keyval} "
                         "(MPI_ERR_KEYVAL)")
    return e or (None, None, None)


def set_attr(obj, keyval: int, value: Any) -> None:
    """Overwriting an existing value runs its delete callback first
    (ref: attribute.c set semantics).  Attaching through a freed
    keyval is erroneous (MPI_ERR_KEYVAL)."""
    _entry(keyval)
    with _lock:
        freed = keyval in _freed
    if freed:
        raise ValueError(f"attribute keyval {keyval} has been freed "
                         "(MPI_ERR_KEYVAL)")
    if keyval in obj.attrs:
        delete_attr(obj, keyval)
    obj.attrs[keyval] = value
    _ref(keyval, +1)


def get_attr(obj, keyval: int) -> Tuple[bool, Any]:
    """Returns (flag, value) like MPI_*_get_attr."""
    if keyval in obj.attrs:
        return True, obj.attrs[keyval]
    return False, None


def delete_attr(obj, keyval: int) -> None:
    copy_fn, delete_fn, extra = _entry(keyval)
    if keyval in obj.attrs:
        value = obj.attrs.pop(keyval)
        if delete_fn is not None:
            delete_fn(obj, keyval, value, extra)
        _ref(keyval, -1)


def copy_all(old, new) -> None:
    """Dup-time propagation: run each attribute's copy callback
    (ref: ompi_attr_copy_all)."""
    for keyval, value in list(old.attrs.items()):
        if keyval < 0:  # predefined attrs propagate as-is
            new.attrs[keyval] = value
            continue
        copy_fn, _d, extra = _entry(keyval)
        if copy_fn is None:
            continue  # MPI_NULL_COPY_FN: not propagated
        out = copy_fn(old, keyval, extra, value)
        if out is not None:
            new.attrs[keyval] = out
            _ref(keyval, +1)


def delete_all(obj) -> None:
    """Free-time teardown: run every delete callback
    (ref: ompi_attr_delete_all)."""
    for keyval in list(obj.attrs.keys()):
        if keyval < 0:
            obj.attrs.pop(keyval, None)
            continue
        delete_attr(obj, keyval)


def init_world_attrs(comm) -> None:
    """Predefined attributes on COMM_WORLD (ref: attribute.c
    ompi_attr_create_predefined)."""
    comm.attrs[TAG_UB] = 2**31 - 1
    comm.attrs[WTIME_IS_GLOBAL] = False
    comm.attrs[UNIVERSE_SIZE] = comm.state.size
    comm.attrs[APPNUM] = getattr(comm.state.rte, "appnum", 0)
