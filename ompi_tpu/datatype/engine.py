"""Datatype engine: MPI derived datatypes as strided-run descriptors.

Re-design of the reference's two-level datatype stack
(opal/datatype/opal_datatype.h:50-102 — 25 predefined base types and
(type, count, disp) descriptor vectors — plus ompi/datatype/* MPI
constructors).  Instead of the reference's loop/element bytecode
interpreted by a state machine, a committed datatype here is a flat
vector of **runs**:

    Run(disp, dtype, count, stride, nblocks)
      = for b in 0..nblocks-1: `count` contiguous elements of `dtype`
        at byte offset `disp + b*stride`

Regular nesting (contiguous-of-vector etc.) is collapsed at build time
(the analog of opal_datatype_optimize.c), so the host pack path is a
handful of vectorized numpy strided copies, and the device pack path
is a single gather with precomputed indices — both TPU/XLA-friendly
shapes of the same descriptor program.
"""

from __future__ import annotations

import numpy as np
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class Run:
    disp: int        # byte displacement of block 0
    dtype: np.dtype  # primitive element type
    count: int       # contiguous elements per block
    stride: int      # bytes between successive block starts
    nblocks: int     # number of blocks

    @property
    def block_bytes(self) -> int:
        return self.count * self.dtype.itemsize

    @property
    def packed_bytes(self) -> int:
        return self.block_bytes * self.nblocks

    def span(self) -> Tuple[int, int]:
        """(min_byte, max_byte_exclusive) touched in the typed buffer."""
        lo = self.disp
        hi = self.disp + (self.nblocks - 1) * self.stride + self.block_bytes
        if self.stride < 0:
            lo = self.disp + (self.nblocks - 1) * self.stride
            hi = self.disp + self.block_bytes
        return lo, hi


def _align(off: int, alignment: int) -> int:
    if alignment <= 1:
        return off
    return (off + alignment - 1) // alignment * alignment


class Datatype:
    """An MPI datatype.  Immutable once committed; constructors return
    new instances.  ``runs`` describe one element; consecutive elements
    are laid out ``extent`` bytes apart."""

    _next_id = [0]

    def __init__(self, runs: List[Run], lb: int, ub: int, name: str = "",
                 base: Optional[np.dtype] = None,
                 envelope: Optional[Tuple] = None) -> None:
        self.runs = runs
        self.lb = lb
        self.ub = ub
        self.name = name
        self.base = base  # set for predefined types
        # (combiner, integers, addresses, datatypes) — MPI_Type_get_contents
        # analog of the reference's args caching (ompi/datatype/ompi_datatype_args.c)
        self.envelope = envelope or ("NAMED", [], [], [])
        self.committed = False
        self.id = Datatype._next_id[0]
        Datatype._next_id[0] += 1

    # -- queries ---------------------------------------------------------
    @property
    def size(self) -> int:
        """Packed size in bytes (MPI_Type_size).  Cached: this sits on
        the per-message hot path and runs never change after commit
        (commit() invalidates)."""
        s = self.__dict__.get("_size")
        if s is None:
            s = sum(r.packed_bytes for r in self.runs)
            self.__dict__["_size"] = s
        return s

    @property
    def extent(self) -> int:
        return self.ub - self.lb

    @property
    def true_lb(self) -> int:
        if not self.runs:
            return 0
        return min(r.span()[0] for r in self.runs)

    @property
    def true_ub(self) -> int:
        if not self.runs:
            return 0
        return max(r.span()[1] for r in self.runs)

    @property
    def true_extent(self) -> int:
        return self.true_ub - self.true_lb

    @property
    def is_contiguous(self) -> bool:
        """True when `count` elements occupy count*size contiguous
        bytes.  Cached (hot path; see ``size``)."""
        c = self.__dict__.get("_contig")
        if c is None:
            if len(self.runs) != 1:
                c = False
            else:
                r = self.runs[0]
                c = ((r.nblocks == 1 or r.stride == r.block_bytes)
                     and r.disp == self.lb and self.extent == self.size)
            self.__dict__["_contig"] = c
        return c

    @property
    def is_predefined(self) -> bool:
        return self.base is not None

    @property
    def alignment(self) -> int:
        if not self.runs:
            return 1
        return max(r.dtype.alignment for r in self.runs)

    def commit(self) -> "Datatype":
        if not self.committed:
            self.runs = _optimize(self.runs)
            self.committed = True
            self.__dict__.pop("_size", None)
            self.__dict__.pop("_contig", None)
        return self

    def free(self) -> None:  # handles are GC'd; parity no-op
        pass

    def get_envelope(self):
        c, i, a, d = self.envelope
        return (len(i), len(a), len(d), c)

    def get_contents(self):
        return self.envelope

    def __repr__(self) -> str:
        return f"Datatype({self.name or self.envelope[0]}, size={self.size})"

    # -- element expansion ----------------------------------------------
    def runs_for_count(self, count: int) -> List[Run]:
        """Runs describing `count` consecutive elements of this type."""
        if count == 1:
            return self.runs
        if self.is_contiguous and len(self.runs) == 1:
            r = self.runs[0]
            total = r.count * r.nblocks * count
            return [Run(r.disp, r.dtype, total, total * r.dtype.itemsize, 1)]
        out: List[Run] = []
        ext = self.extent
        if len(self.runs) == 1:
            r = self.runs[0]
            # extend a single strided run across elements when regular
            if r.stride * r.nblocks == ext:
                return [Run(r.disp, r.dtype, r.count, r.stride,
                            r.nblocks * count)]
        # pack order is element-major (the MPI typemap repeated)
        for e in range(count):
            off = e * ext
            out += [Run(r.disp + off, r.dtype, r.count, r.stride, r.nblocks)
                    for r in self.runs]
        return _optimize(out)


def _optimize(runs: List[Run]) -> List[Run]:
    """Merge adjacent compatible runs (opal_datatype_optimize.c analog)."""
    out: List[Run] = []
    for r in runs:
        if r.nblocks == 0 or r.count == 0:
            continue
        # normalize single-block to stride == block_bytes
        if r.nblocks == 1 and r.stride != r.block_bytes:
            r = Run(r.disp, r.dtype, r.count, r.block_bytes, 1)
        if out:
            p = out[-1]
            if (p.dtype == r.dtype and p.nblocks == 1 and r.nblocks == 1
                    and r.disp == p.disp + p.block_bytes):
                out[-1] = Run(p.disp, p.dtype, p.count + r.count,
                              (p.count + r.count) * p.dtype.itemsize, 1)
                continue
            # fold equally-spaced identical blocks into one strided run
            if (p.dtype == r.dtype and p.count == r.count
                    and p.block_bytes != 0
                    and r.nblocks == 1 and p.stride != 0
                    and r.disp == p.disp + p.nblocks * p.stride):
                out[-1] = Run(p.disp, p.dtype, p.count, p.stride, p.nblocks + 1)
                continue
        out.append(r)
    return out


# ---------------------------------------------------------------------------
# Predefined datatypes (ref: ompi/datatype/ompi_datatype_internal.h tables)
# ---------------------------------------------------------------------------

_predefined: dict = {}


def _make_predefined(name: str, np_dtype) -> Datatype:
    dt = np.dtype(np_dtype)
    d = Datatype([Run(0, dt, 1, dt.itemsize, 1)], 0, dt.itemsize,
                 name=name, base=dt)
    d.commit()
    _predefined[name] = d
    return d


BYTE = _make_predefined("MPI_BYTE", np.uint8)
PACKED = _make_predefined("MPI_PACKED", np.uint8)
CHAR = _make_predefined("MPI_CHAR", np.int8)
SIGNED_CHAR = _make_predefined("MPI_SIGNED_CHAR", np.int8)
UNSIGNED_CHAR = _make_predefined("MPI_UNSIGNED_CHAR", np.uint8)
WCHAR = _make_predefined("MPI_WCHAR", np.int32)
SHORT = _make_predefined("MPI_SHORT", np.int16)
UNSIGNED_SHORT = _make_predefined("MPI_UNSIGNED_SHORT", np.uint16)
INT = _make_predefined("MPI_INT", np.int32)
UNSIGNED = _make_predefined("MPI_UNSIGNED", np.uint32)
LONG = _make_predefined("MPI_LONG", np.int64)
UNSIGNED_LONG = _make_predefined("MPI_UNSIGNED_LONG", np.uint64)
LONG_LONG = _make_predefined("MPI_LONG_LONG", np.int64)
UNSIGNED_LONG_LONG = _make_predefined("MPI_UNSIGNED_LONG_LONG", np.uint64)
INT8_T = _make_predefined("MPI_INT8_T", np.int8)
INT16_T = _make_predefined("MPI_INT16_T", np.int16)
INT32_T = _make_predefined("MPI_INT32_T", np.int32)
INT64_T = _make_predefined("MPI_INT64_T", np.int64)
UINT8_T = _make_predefined("MPI_UINT8_T", np.uint8)
UINT16_T = _make_predefined("MPI_UINT16_T", np.uint16)
UINT32_T = _make_predefined("MPI_UINT32_T", np.uint32)
UINT64_T = _make_predefined("MPI_UINT64_T", np.uint64)
FLOAT = _make_predefined("MPI_FLOAT", np.float32)
DOUBLE = _make_predefined("MPI_DOUBLE", np.float64)
LONG_DOUBLE = _make_predefined("MPI_LONG_DOUBLE", np.longdouble)
C_BOOL = _make_predefined("MPI_C_BOOL", np.bool_)
C_FLOAT_COMPLEX = _make_predefined("MPI_C_FLOAT_COMPLEX", np.complex64)
C_DOUBLE_COMPLEX = _make_predefined("MPI_C_DOUBLE_COMPLEX", np.complex128)
AINT = _make_predefined("MPI_AINT", np.int64)
OFFSET = _make_predefined("MPI_OFFSET", np.int64)
COUNT = _make_predefined("MPI_COUNT", np.int64)
# TPU-native additions (no reference analog: the reference has no
# accelerator dtypes of its own)
try:
    import ml_dtypes  # shipped with jax

    BFLOAT16 = _make_predefined("MPI_BFLOAT16", ml_dtypes.bfloat16)
    FLOAT16 = _make_predefined("MPI_FLOAT16", np.float16)
except Exception:  # pragma: no cover
    BFLOAT16 = None
    FLOAT16 = _make_predefined("MPI_FLOAT16", np.float16)


def _make_pair(name: str, first, second) -> Datatype:
    """MAXLOC/MINLOC pair types as numpy structured dtypes
    (ref: ompi_datatype_internal.h FLOAT_INT et al.)."""
    dt = np.dtype([("v", first), ("i", second)], align=True)
    d = Datatype([Run(0, dt, 1, dt.itemsize, 1)], 0, dt.itemsize,
                 name=name, base=dt)
    d.commit()
    _predefined[name] = d
    return d


FLOAT_INT = _make_pair("MPI_FLOAT_INT", np.float32, np.int32)
DOUBLE_INT = _make_pair("MPI_DOUBLE_INT", np.float64, np.int32)
LONG_INT = _make_pair("MPI_LONG_INT", np.int64, np.int32)
SHORT_INT = _make_pair("MPI_SHORT_INT", np.int16, np.int32)
TWOINT = _make_pair("MPI_2INT", np.int32, np.int32)
LONG_DOUBLE_INT = _make_pair("MPI_LONG_DOUBLE_INT", np.longdouble, np.int32)

# Fortran names mapped onto C layouts (ref: ompi_datatype_internal.h)
INTEGER = INT
REAL = FLOAT
DOUBLE_PRECISION = DOUBLE
COMPLEX = C_FLOAT_COMPLEX
DOUBLE_COMPLEX = C_DOUBLE_COMPLEX
LOGICAL = INT
CHARACTER = CHAR

LB_MARKER = Datatype([], 0, 0, name="MPI_LB")
UB_MARKER = Datatype([], 0, 0, name="MPI_UB")
DATATYPE_NULL = Datatype([], 0, 0, name="MPI_DATATYPE_NULL")


_canonical = {}
for _d in (BYTE, CHAR, UNSIGNED_CHAR, SHORT, UNSIGNED_SHORT, INT, UNSIGNED,
           LONG, UNSIGNED_LONG, FLOAT, DOUBLE, LONG_DOUBLE, C_BOOL,
           C_FLOAT_COMPLEX, C_DOUBLE_COMPLEX, FLOAT16):
    _canonical.setdefault(_d.base, _d)
if BFLOAT16 is not None:
    _canonical.setdefault(BFLOAT16.base, BFLOAT16)


def from_numpy_dtype(dt) -> Datatype:
    """Map a numpy/jax dtype to the canonical predefined Datatype."""
    dt = np.dtype(dt)
    d = _canonical.get(dt)
    if d is not None:
        return d
    for cand in _predefined.values():
        if cand.base is not None and cand.base == dt:
            return cand
    raise KeyError(f"no MPI datatype for numpy dtype {dt}")


def predefined_by_name(name: str) -> Datatype:
    return _predefined[name]


# ---------------------------------------------------------------------------
# Constructors (ref: ompi/mpi/c/type_* and ompi/datatype/ompi_datatype_create_*)
# ---------------------------------------------------------------------------

def dup(oldtype: Datatype) -> Datatype:
    d = Datatype(list(oldtype.runs), oldtype.lb, oldtype.ub,
                 name=oldtype.name,
                 envelope=("DUP", [], [], [oldtype]))
    return d


def contiguous(count: int, oldtype: Datatype) -> Datatype:
    runs = oldtype.runs_for_count(count)
    lb = oldtype.lb
    ub = oldtype.lb + count * oldtype.extent
    return Datatype(runs, lb, ub,
                    envelope=("CONTIGUOUS", [count], [], [oldtype]))


def vector(count: int, blocklength: int, stride: int,
           oldtype: Datatype) -> Datatype:
    """stride in elements of oldtype."""
    return _hvector(count, blocklength, stride * oldtype.extent, oldtype,
                    envelope=("VECTOR", [count, blocklength, stride], [],
                              [oldtype]))


def hvector(count: int, blocklength: int, stride_bytes: int,
            oldtype: Datatype) -> Datatype:
    return _hvector(count, blocklength, stride_bytes, oldtype,
                    envelope=("HVECTOR", [count, blocklength], [stride_bytes],
                              [oldtype]))


def _hvector(count, blocklength, stride_bytes, oldtype, envelope):
    block = oldtype.runs_for_count(blocklength)
    runs: List[Run] = []
    if len(block) == 1 and block[0].nblocks == 1:
        b = block[0]
        runs = [Run(b.disp, b.dtype, b.count, stride_bytes, count)]
    else:
        for i in range(count):
            off = i * stride_bytes
            runs += [Run(r.disp + off, r.dtype, r.count, r.stride, r.nblocks)
                     for r in block]
        runs = _optimize(runs)
    lb = oldtype.lb + min(0, (count - 1) * stride_bytes)
    ub = (oldtype.lb + max((count - 1) * stride_bytes, 0)
          + blocklength * oldtype.extent)
    return Datatype(runs, lb, ub, envelope=envelope)


def indexed(blocklengths: Sequence[int], displacements: Sequence[int],
            oldtype: Datatype) -> Datatype:
    disps = [d * oldtype.extent for d in displacements]
    return _hindexed(blocklengths, disps, oldtype,
                     envelope=("INDEXED",
                               [len(blocklengths), *blocklengths,
                                *displacements], [], [oldtype]))


def hindexed(blocklengths: Sequence[int], displacements: Sequence[int],
             oldtype: Datatype) -> Datatype:
    return _hindexed(blocklengths, list(displacements), oldtype,
                     envelope=("HINDEXED",
                               [len(blocklengths), *blocklengths],
                               list(displacements), [oldtype]))


def _hindexed(blocklengths, byte_disps, oldtype, envelope):
    runs: List[Run] = []
    lb = None
    ub = None
    for bl, bd in zip(blocklengths, byte_disps):
        if bl == 0:
            continue
        block = oldtype.runs_for_count(bl)
        runs += [Run(r.disp + bd, r.dtype, r.count, r.stride, r.nblocks)
                 for r in block]
        blo = oldtype.lb + bd
        bhi = oldtype.lb + bd + bl * oldtype.extent
        lb = blo if lb is None else min(lb, blo)
        ub = bhi if ub is None else max(ub, bhi)
    if lb is None:
        lb = ub = 0
    return Datatype(_optimize(runs), lb, ub, envelope=envelope)


def indexed_block(blocklength: int, displacements: Sequence[int],
                  oldtype: Datatype) -> Datatype:
    d = indexed([blocklength] * len(displacements), displacements, oldtype)
    d.envelope = ("INDEXED_BLOCK",
                  [len(displacements), blocklength, *displacements], [],
                  [oldtype])
    return d


def hindexed_block(blocklength: int, displacements: Sequence[int],
                   oldtype: Datatype) -> Datatype:
    d = hindexed([blocklength] * len(displacements), displacements, oldtype)
    d.envelope = ("HINDEXED_BLOCK", [len(displacements), blocklength],
                  list(displacements), [oldtype])
    return d


def struct(blocklengths: Sequence[int], displacements: Sequence[int],
           types: Sequence[Datatype]) -> Datatype:
    runs: List[Run] = []
    lb = None
    ub = None
    explicit_lb = None
    explicit_ub = None
    align = 1
    for bl, bd, t in zip(blocklengths, displacements, types):
        if t is LB_MARKER:
            explicit_lb = bd if explicit_lb is None else min(explicit_lb, bd)
            continue
        if t is UB_MARKER:
            explicit_ub = bd if explicit_ub is None else max(explicit_ub, bd)
            continue
        if bl == 0:
            continue
        align = max(align, t.alignment)
        block = t.runs_for_count(bl)
        runs += [Run(r.disp + bd, r.dtype, r.count, r.stride, r.nblocks)
                 for r in block]
        blo = t.lb + bd
        bhi = t.lb + bd + bl * t.extent
        lb = blo if lb is None else min(lb, blo)
        ub = bhi if ub is None else max(ub, bhi)
    if lb is None:
        lb = ub = 0
    if explicit_lb is not None:
        lb = explicit_lb
    if explicit_ub is not None:
        ub = explicit_ub
    else:
        # epsilon alignment padding, matching C struct layout
        ub = lb + _align(ub - lb, align)
    return Datatype(_optimize(runs), lb, ub,
                    envelope=("STRUCT", [len(blocklengths), *blocklengths],
                              list(displacements), list(types)))


ORDER_C = 56
ORDER_FORTRAN = 57


def subarray(sizes: Sequence[int], subsizes: Sequence[int],
             starts: Sequence[int], order: int, oldtype: Datatype) -> Datatype:
    """N-dim subarray (ref: ompi/datatype/ompi_datatype_create_subarray.c:
    built as nested vectors from the innermost dimension out)."""
    ndims = len(sizes)
    if order == ORDER_FORTRAN:
        sizes = list(reversed(sizes))
        subsizes = list(reversed(subsizes))
        starts = list(reversed(starts))
    # innermost (last) dimension: contiguous run of subsizes[-1]
    d = contiguous(subsizes[-1], oldtype) if subsizes[-1] != 1 else dup(oldtype)
    extent_inner = oldtype.extent * sizes[-1]
    for dim in range(ndims - 2, -1, -1):
        d = hvector(subsizes[dim], 1, extent_inner, d)
        extent_inner *= sizes[dim]
    # absolute offset of the start corner
    off = 0
    mult = oldtype.extent
    for dim in range(ndims - 1, -1, -1):
        off += starts[dim] * mult
        mult *= sizes[dim]
    full = np.prod(sizes) * oldtype.extent
    runs = [Run(r.disp + off, r.dtype, r.count, r.stride, r.nblocks)
            for r in d.runs]
    out = Datatype(_optimize(runs), 0, int(full),
                   envelope=("SUBARRAY",
                             [len(sizes), *sizes, *subsizes, *starts, order],
                             [], [oldtype]))
    return out


DISTRIBUTE_BLOCK = 121
DISTRIBUTE_CYCLIC = 122
DISTRIBUTE_NONE = 123
DISTRIBUTE_DFLT_DARG = -49767


def darray(size: int, rank: int, gsizes: Sequence[int],
           distribs: Sequence[int], dargs: Sequence[int],
           psizes: Sequence[int], order: int, oldtype: Datatype) -> Datatype:
    """HPF-style distributed array type
    (ref: ompi/datatype/ompi_datatype_create_darray.c).  Built by
    per-dimension recursion — innermost dimension first — where each
    level selects this rank's blocks along that dimension (hindexed
    over the previous level's type) and resizes to the dimension's
    full global span, so BLOCK, CYCLIC(b) and NONE all share one
    mechanism."""
    ndims = len(gsizes)
    # rank → grid coords is row-major regardless of `order` (MPI-3.1
    # §4.1.4: "the process grid is always assumed to be row-major";
    # matches ompi_datatype_create_darray.c)
    coords = []
    r = rank
    for d in range(ndims - 1, -1, -1):
        coords.insert(0, r % psizes[d])
        r //= psizes[d]
    t = oldtype
    dims_iter = range(ndims - 1, -1, -1) if order == ORDER_C \
        else range(ndims)
    for d in dims_iter:
        ext = t.extent
        g, p, c = gsizes[d], psizes[d], coords[d]
        if distribs[d] == DISTRIBUTE_NONE or p == 1:
            lens, offs = [g], [0]
        elif distribs[d] == DISTRIBUTE_BLOCK:
            b = dargs[d]
            if b == DISTRIBUTE_DFLT_DARG:
                b = -(-g // p)
            s = min(c * b, g)
            lens, offs = [max(0, min(s + b, g) - s)], [s * ext]
        elif distribs[d] == DISTRIBUTE_CYCLIC:
            b = dargs[d]
            if b == DISTRIBUTE_DFLT_DARG:
                b = 1
            total_blocks = -(-g // b)
            lens, offs = [], []
            for tb in range(c, total_blocks, p):
                lens.append(min(b, g - tb * b))
                offs.append(tb * b * ext)
        else:
            raise ValueError(f"unknown distribution {distribs[d]}")
        lens = [x for x in lens if x > 0] or [0]
        offs = offs[:len(lens)] if lens != [0] else [0]
        t = hindexed(lens, offs, t)
        t = resized(t, 0, g * ext)
    t.envelope = ("DARRAY", [size, rank, ndims, *gsizes, *distribs,
                             *dargs, *psizes, order], [], [oldtype])
    return t


def resized(oldtype: Datatype, lb: int, extent: int) -> Datatype:
    return Datatype(list(oldtype.runs), lb, lb + extent,
                    envelope=("RESIZED", [], [lb, extent], [oldtype]))
