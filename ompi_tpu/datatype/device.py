"""On-device datatype packing: the descriptor program as ONE XLA
gather.

The north-star item SURVEY §2.9.1 calls "datatype packing done
on-device": a committed datatype's run descriptors (engine.py) are
compiled once into an element-index vector, and packing a
device-resident buffer becomes ``buf[idx]`` — a single XLA gather the
compiler fuses into the collective that consumes it (reference
counterpart: the convertor pack loop feeding coll buffers,
opal/datatype/opal_convertor.h:131-137, which walks descriptors
element-wise on the host CPU).  Unpack is the mirrored scatter.

Eligibility: every run must use the same primitive dtype as the
buffer, with displacements/strides that are whole elements —
exactly the shapes MPI vector/indexed/subarray types of one base
type produce.  Mixed-type structs fall back to the host convertor
(they would need byte-level gathers that defeat XLA vectorization).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .engine import Datatype

_idx_cache: dict = {}
_dtype_cache: dict = {}


def element_indices(datatype: Datatype, count: int) -> Optional[np.ndarray]:
    """Element indices (into a flat element-typed buffer view) whose
    gather equals the datatype's packed stream for ``count`` elements,
    or None when the datatype is not device-packable.  Cached per
    (datatype id, count) — index construction is host-side and O(n),
    the device gather is the per-call cost."""
    key = (datatype.id, count)
    hit = _idx_cache.get(key)
    if hit is not None:
        return hit
    runs = datatype.runs_for_count(count)
    if not runs:
        return None
    item = runs[0].dtype.itemsize
    chunks = []
    for r in runs:
        if r.dtype != runs[0].dtype:
            return None  # mixed primitive types: host convertor
        if r.disp % item or r.stride % item:
            return None  # sub-element displacement: host convertor
        base = r.disp // item
        stride = r.stride // item
        # (nblocks, count) element grid -> flat packed order
        grid = (base
                + stride * np.arange(r.nblocks, dtype=np.int64)[:, None]
                + np.arange(r.count, dtype=np.int64)[None, :])
        chunks.append(grid.reshape(-1))
    idx = np.concatenate(chunks) if len(chunks) > 1 else chunks[0]
    if (idx < 0).any():
        return None  # negative displacement: host convertor owns it
    _idx_cache[key] = idx
    _dtype_cache[key] = runs[0].dtype
    return idx


def device_pack(datatype: Datatype, count: int, arr):
    """Pack a device-resident array through the datatype: one XLA
    gather (jittable; fuses into downstream collectives).  ``arr`` is
    the flat element-typed buffer the datatype addresses."""
    import jax.numpy as jnp

    idx = element_indices(datatype, count)
    if idx is None:
        raise ValueError(
            f"datatype {datatype.name or datatype.id} is not "
            f"device-packable (mixed types or sub-element layout)")
    base = _dtype_cache[(datatype.id, count)]
    if base != np.dtype(arr.dtype):
        raise ValueError(
            f"buffer dtype {arr.dtype} does not match datatype base "
            f"{base}")
    return jnp.take(arr.reshape(-1), jnp.asarray(idx), axis=0)


def device_unpack(datatype: Datatype, count: int, packed, out):
    """Scatter a packed stream back through the datatype into ``out``
    (a flat element-typed device array); returns the updated array
    (functional, XLA scatter)."""
    idx = element_indices(datatype, count)
    if idx is None:
        raise ValueError("datatype is not device-packable")
    import jax.numpy as jnp

    return out.reshape(-1).at[jnp.asarray(idx)].set(packed)


def is_device_packable(datatype: Datatype, count: int) -> bool:
    return element_indices(datatype, count) is not None


# ---------------------------------------------------------------------------
# segment packing for fused collectives (coll/fusion): N small payloads
# ride one flattened buffer; the offset table is host-side static so the
# pack/unpack slices bake into the fused executable
# ---------------------------------------------------------------------------

def segment_offsets(shapes):
    """Offset table for a flat concatenation of arrays with the given
    shapes: (offsets, lengths, total_elements).  Host-side and static —
    fused-collective bodies slice with these as compile-time constants
    (0-d shapes contribute one element)."""
    offs, lens = [], []
    total = 0
    for sh in shapes:
        n = 1
        for d in sh:
            n *= int(d)
        offs.append(total)
        lens.append(n)
        total += n
    return tuple(offs), tuple(lens), total


def pack_segments(arrays):
    """Flatten + concatenate payloads into one fused buffer.  Must be
    called INSIDE a jitted body: eager reshapes/concats each cost a
    device dispatch on the tunneled backend, which is exactly the
    constant fusion exists to amortize."""
    import jax.numpy as jnp

    return jnp.concatenate([a.reshape(-1) for a in arrays])


def unpack_segments(flat, shapes):
    """Mirror of pack_segments: slice the fused buffer back into the
    original shapes (static slices; fuses into the surrounding jit)."""
    offs, lens, _ = segment_offsets(shapes)
    return [flat[o:o + n].reshape(sh)
            for o, n, sh in zip(offs, lens, shapes)]
