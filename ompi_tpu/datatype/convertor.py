"""Convertor: resumable, positionable pack/unpack over a datatype.

Re-design of the reference convertor state machine
(opal/datatype/opal_convertor.h:69-137 — dt_stack_t explicit stack,
opal_convertor_pack/unpack, prepare_for_send/recv;
opal/datatype/opal_datatype_position.c for repositioning;
opal_datatype_checksum.h for checksummed variants;
opal_copy_functions_heterogeneous.c for endian conversion, which here
is the external32 mode).

Because committed datatypes are flat run vectors (see engine.py), the
"stack" collapses to (run index, block index, byte-within-block), and
whole-run copies vectorize through numpy strided views — the same
descriptor program the device path turns into one XLA gather.
"""

from __future__ import annotations

import zlib
from typing import List, Optional, Tuple, Union

import numpy as np

from .engine import Datatype, Run

Buffer = Union[np.ndarray, bytearray, memoryview, bytes]


def _byte_view(buf: Buffer, writable: bool) -> np.ndarray:
    """A flat uint8 view of `buf` without copying."""
    if isinstance(buf, np.ndarray):
        if buf.ndim == 0:
            buf = buf.reshape(1)
        if not buf.flags.c_contiguous:
            raise ValueError("buffer must be C-contiguous")
        if writable and not buf.flags.writeable:
            raise ValueError("buffer is read-only")
        return buf.view(np.uint8).reshape(-1)
    mv = memoryview(buf).cast("B")
    if writable and mv.readonly:
        raise ValueError("buffer is read-only")
    return np.frombuffer(mv, dtype=np.uint8) if mv.readonly \
        else np.asarray(mv)


class Convertor:
    """Packs/unpacks `count` elements of `datatype` living in `buf`.

    Modes: native (memcpy semantics) or external32 (big-endian
    canonical, MPI_Pack_external).  Optional crc32 checksum over the
    packed stream (the reference's *_checksum convertor variants).
    """

    def __init__(self, datatype: Datatype, count: int, buf: Buffer,
                 external32: bool = False, checksum: bool = False,
                 offset: int = 0) -> None:
        """`offset`: byte position within `buf` that plays the role of
        the MPI buffer pointer — datatypes with negative lb/displacements
        address bytes before it (C pointers can; numpy views cannot, so
        the origin is explicit here)."""
        self.datatype = datatype
        self.count = count
        self.external32 = external32
        self.checksum = checksum
        self.offset = offset
        self.crc = 0
        self.runs: List[Run] = datatype.runs_for_count(count) if count else []
        self._cum: List[int] = []
        total = 0
        for r in self.runs:
            total += r.packed_bytes
            self._cum.append(total)
        self.packed_size = total
        self.position = 0
        self._buf = buf

    # -- internals -------------------------------------------------------
    def _locate(self, pos: int) -> Tuple[int, int, int]:
        """(run_idx, block_idx, byte_in_block) for packed offset pos."""
        lo = 0
        for i, cum in enumerate(self._cum):
            if pos < cum:
                within = pos - lo
                bb = self.runs[i].block_bytes
                return i, within // bb, within % bb
            lo = cum
        return len(self.runs), 0, 0

    def _check_span(self, base: np.ndarray, r: Run) -> int:
        """Bounds-check run r against the buffer; returns its absolute
        disp.  as_strided performs no checking of its own, so this is
        the memory-safety gate for both pack and unpack."""
        disp = self.offset + r.disp
        slo, shi = r.span()
        if self.offset + slo < 0:
            raise IndexError(
                "datatype addresses bytes before the buffer origin; "
                "pass offset= to Convertor")
        if self.offset + shi > len(base):
            raise IndexError(
                f"datatype spans {self.offset + shi} bytes but buffer "
                f"has only {len(base)}")
        return disp

    @staticmethod
    def _sub_run(r: Run, plo: int, phi: int):
        """Restrict run r to packed-byte range [plo, phi): returns
        (sub_run, byte_lo, byte_hi) where byte_* slice the sub-run's
        packed image.  Keeps pipelined chunking O(chunk), not O(run)."""
        bb = r.block_bytes
        b0 = plo // bb
        b1 = (phi - 1) // bb
        sub = Run(r.disp + b0 * r.stride, r.dtype, r.count, r.stride,
                  b1 - b0 + 1)
        return sub, plo - b0 * bb, phi - b0 * bb

    def _run_bytes(self, base: np.ndarray, r: Run) -> np.ndarray:
        """Packed byte image of a whole run (view-free copy)."""
        disp = self._check_span(base, r)
        if r.stride < 0:
            parts = [base[disp + b * r.stride:
                          disp + b * r.stride + r.block_bytes]
                     for b in range(r.nblocks)]
            out = np.concatenate(parts)
        elif r.nblocks == 1 or r.stride == r.block_bytes:
            out = base[disp:disp + r.packed_bytes].copy()
        else:
            v = np.lib.stride_tricks.as_strided(
                base[disp:], shape=(r.nblocks, r.block_bytes),
                strides=(r.stride, 1))
            out = np.ascontiguousarray(v).reshape(-1)
        if self.external32 and r.dtype.itemsize > 1:
            arr = out.view(r.dtype)
            out = arr.astype(r.dtype.newbyteorder(">")).view(np.uint8)
        return out

    def _run_store(self, base: np.ndarray, r: Run, data: np.ndarray) -> None:
        """Scatter a full run's packed bytes back into the typed buffer."""
        if self.external32 and r.dtype.itemsize > 1:
            arr = data.view(r.dtype.newbyteorder(">"))
            data = arr.astype(r.dtype).view(np.uint8)
        disp = self._check_span(base, r)
        if r.stride < 0:
            for b in range(r.nblocks):
                dst = disp + b * r.stride
                base[dst:dst + r.block_bytes] = \
                    data[b * r.block_bytes:(b + 1) * r.block_bytes]
        elif r.nblocks == 1 or r.stride == r.block_bytes:
            base[disp:disp + r.packed_bytes] = data
        else:
            v = np.lib.stride_tricks.as_strided(
                base[disp:], shape=(r.nblocks, r.block_bytes),
                strides=(r.stride, 1))
            v[:] = data.reshape(r.nblocks, r.block_bytes)

    # -- public API ------------------------------------------------------
    def set_position(self, pos: int) -> None:
        """Reposition the pack/unpack stream (pipelined rendezvous,
        ref: opal_datatype_position.c)."""
        if pos < 0 or pos > self.packed_size:
            raise ValueError("position out of range")
        self.position = pos

    @property
    def done(self) -> bool:
        return self.position >= self.packed_size

    def pack(self, max_bytes: Optional[int] = None) -> bytes:
        """Pack up to max_bytes from the current position; advances."""
        base = _byte_view(self._buf, writable=False)
        start = self.position
        end = self.packed_size if max_bytes is None \
            else min(self.packed_size, start + max_bytes)
        if end <= start:
            return b""
        out = np.empty(end - start, dtype=np.uint8)
        pos = start
        ri, bi, byte = self._locate(start)
        run_lo = self._cum[ri - 1] if ri > 0 else 0
        while pos < end and ri < len(self.runs):
            r = self.runs[ri]
            run_hi = self._cum[ri]
            lo = max(pos, run_lo)
            hi = min(end, run_hi)
            if lo == run_lo and hi == run_hi:
                img = self._run_bytes(base, r)
            else:
                sub, blo, bhi = self._sub_run(r, lo - run_lo, hi - run_lo)
                img = self._run_bytes(base, sub)[blo:bhi]
            out[pos - start:hi - start] = img
            pos = hi
            run_lo = run_hi
            ri += 1
        data = out.tobytes()
        self.position = end
        if self.checksum:
            self.crc = zlib.crc32(data, self.crc)
        return data

    def pack_bytes(self, max_bytes: Optional[int] = None) -> bytes:
        return self.pack(max_bytes)

    def unpack(self, data: bytes) -> int:
        """Unpack bytes at the current position; advances; returns
        bytes consumed."""
        base = _byte_view(self._buf, writable=True)
        src = np.frombuffer(data, dtype=np.uint8)
        start = self.position
        end = min(self.packed_size, start + len(src))
        if end <= start:
            return 0
        pos = start
        ri, _, _ = self._locate(start)
        run_lo = self._cum[ri - 1] if ri > 0 else 0
        while pos < end and ri < len(self.runs):
            r = self.runs[ri]
            run_hi = self._cum[ri]
            lo = max(pos, run_lo)
            hi = min(end, run_hi)
            if lo == run_lo and hi == run_hi:
                self._run_store(base, r, src[lo - start:hi - start])
            else:
                # partial run: read-modify-write only the touched blocks
                sub, blo, bhi = self._sub_run(r, lo - run_lo, hi - run_lo)
                img = self._run_bytes(base, sub)
                img[blo:bhi] = src[lo - start:hi - start]
                self._run_store(base, sub, img)
            pos = hi
            run_lo = run_hi
            ri += 1
        if self.checksum:
            self.crc = zlib.crc32(data[:end - start], self.crc)
        self.position = end
        return end - start


class ContigConvertor:
    """Fast-path convertor: contiguous datatype over a contiguous
    buffer collapses pack/unpack to flat byte-range copies (the
    reference's contiguous-convertor shortcut that skips the stack
    machine entirely, ref: opal_convertor.h:254-262
    opal_convertor_prepare_for_send's CONVERTOR_NO_OP path).

    ``pack`` returns zero-copy memoryviews of the user buffer — legal
    because MPI forbids touching the buffer while a request that still
    streams from it is pending; eager sends that complete immediately
    must use ``pack_bytes`` (the payload may sit in a transport queue
    after completion).
    """

    __slots__ = ("datatype", "count", "packed_size", "position", "_view",
                 "checksum", "crc", "external32")

    def __init__(self, view, datatype, count) -> None:
        self._view = view  # uint8 ndarray view over the packed range
        self.datatype = datatype
        self.count = count
        self.packed_size = len(view)
        self.position = 0
        self.checksum = False
        self.external32 = False
        self.crc = 0

    def set_position(self, pos: int) -> None:
        if pos < 0 or pos > self.packed_size:
            raise ValueError("position out of range")
        self.position = pos

    @property
    def done(self) -> bool:
        return self.position >= self.packed_size

    def pack(self, max_bytes: Optional[int] = None):
        start = self.position
        end = self.packed_size if max_bytes is None \
            else min(self.packed_size, start + max_bytes)
        self.position = end
        if end <= start:
            return b""
        return memoryview(self._view[start:end])

    def pack_bytes(self, max_bytes: Optional[int] = None) -> bytes:
        out = self.pack(max_bytes)
        return out if isinstance(out, bytes) else out.tobytes()

    def unpack(self, data) -> int:
        start = self.position
        n = min(self.packed_size - start, len(data))
        if n <= 0:
            return 0
        src = np.frombuffer(data, dtype=np.uint8, count=n) \
            if isinstance(data, bytes) else \
            np.frombuffer(memoryview(data)[:n], dtype=np.uint8)
        self._view[start:start + n] = src
        self.position = start + n
        return n


def make_convertor(datatype: Datatype, count: int, buf: Buffer,
                   offset: int = 0, writable: bool = False):
    """Pick the cheapest convertor for (datatype, buf): the flat
    fast path when both are contiguous, the full stack machine
    otherwise."""
    if count and datatype.is_contiguous and datatype.lb == 0:
        try:
            view = _byte_view(buf, writable=writable)
        except (ValueError, TypeError, BufferError):
            view = None
        if view is not None:
            need = offset + count * datatype.size
            if need <= view.shape[0]:
                return ContigConvertor(view[offset:need], datatype, count)
    return Convertor(datatype, count, buf, offset=offset)


def pack(datatype: Datatype, count: int, buf: Buffer,
         external32: bool = False) -> bytes:
    """One-shot MPI_Pack."""
    return Convertor(datatype, count, buf, external32=external32).pack()


def unpack(datatype: Datatype, count: int, buf: Buffer, data: bytes,
           external32: bool = False) -> int:
    """One-shot MPI_Unpack."""
    return Convertor(datatype, count, buf, external32=external32).unpack(data)
