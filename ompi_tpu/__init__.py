"""ompi_tpu: a TPU-native message-passing framework with the
capabilities of Open MPI (see SURVEY.md for the reference map and
docs/DESIGN.md for the architecture).

Quick start (process-ranks, launched by our mpirun):

    # prog.py
    import ompi_tpu
    comm = ompi_tpu.init()
    ...
    ompi_tpu.finalize()

    $ python -m ompi_tpu.tools.mpirun -np 4 prog.py

or thread-ranks mapped onto local accelerator devices:

    from ompi_tpu.testing import run_ranks
    run_ranks(8, fn, devices=True)
"""

from __future__ import annotations

from typing import Optional

__version__ = "0.1.0"

_finalized_once = False


def init(device=None):
    """MPI_Init analog: bootstrap this process's rank and return
    COMM_WORLD (ref: ompi/mpi/c/init.c → ompi_mpi_init.c)."""
    from ompi_tpu.runtime import state as statemod
    from ompi_tpu.runtime.init import mpi_init
    from ompi_tpu.runtime.rte import make_rte

    existing = statemod.maybe_current()
    if existing is not None and existing.initialized \
            and not existing.finalized:
        return existing.comm_world
    from ompi_tpu.runtime.rte import HybridRTE

    rte = make_rte()
    st = statemod.ProcState(rte.rank, rte.size, rte)
    if device is None:
        # hybrid launch: the app shell pre-assigned this rank-thread a
        # local chip (mpirun --ranks-per-proc, see tools/hostrun.py)
        device = getattr(rte, "default_device", None)
    mpi_init(st, device=device)  # publishes into rte.world itself
    # process-wide publication is a convenience for single-rank
    # processes only; with co-resident rank-threads it would hand an
    # arbitrary rank's state to non-rank threads (last writer wins)
    # instead of the clean not-initialized error
    statemod.set_current(st, process_wide=not isinstance(rte, HybridRTE))
    return st.comm_world


def finalize() -> None:
    """MPI_Finalize analog (ref: ompi_mpi_finalize.c:101)."""
    from ompi_tpu.runtime import state as statemod
    from ompi_tpu.runtime.init import mpi_finalize

    global _finalized_once
    st = statemod.maybe_current()
    if st is not None and st.initialized and not st.finalized:
        if st.serve_resident:
            # DVM-resident session (tools/dvm): the world outlives the
            # program.  Finalize degrades to a run boundary — flush
            # deferred fused batches and meet the peers — so the next
            # program attached to this session starts from a quiet,
            # still-warm world.  Real teardown happens at session
            # detach, when the pool clears serve_resident.
            from ompi_tpu.coll import fusion as _fusion
            _fusion.flush_state(st)
            st.rte.fence()
            return
        mpi_finalize(st)
        _finalized_once = True


def attach_buffer(size_or_buf) -> None:
    """MPI_Buffer_attach for this rank (Bsend backing store)."""
    from ompi_tpu.pml.persistent import attach_buffer as _attach
    from ompi_tpu.runtime import state as statemod

    _attach(statemod.current(), size_or_buf)


def detach_buffer() -> int:
    """MPI_Buffer_detach: drains pending buffered sends."""
    from ompi_tpu.pml.persistent import detach_buffer as _detach
    from ompi_tpu.runtime import state as statemod

    return _detach(statemod.current())


def get_parent():
    """MPI_Comm_get_parent: in a spawned job, the intercommunicator
    to the spawning processes; None otherwise."""
    from ompi_tpu.comm.dpm import get_parent as _gp
    from ompi_tpu.runtime import state as statemod

    return _gp(statemod.current().comm_world)


def open_port() -> str:
    from ompi_tpu.comm.dpm import open_port as _op
    from ompi_tpu.runtime import state as statemod

    return _op(statemod.current())


def publish_name(service: str, port: str) -> None:
    from ompi_tpu.comm.dpm import publish_name as _pn
    from ompi_tpu.runtime import state as statemod

    _pn(statemod.current(), service, port)


def lookup_name(service: str) -> str:
    from ompi_tpu.comm.dpm import lookup_name as _ln
    from ompi_tpu.runtime import state as statemod

    return _ln(statemod.current(), service)


def initialized() -> bool:
    from ompi_tpu.runtime import state as statemod

    st = statemod.maybe_current()
    return st is not None and st.initialized


def finalized() -> bool:
    """MPI_Finalized: True once finalize() has completed (the state
    itself is dropped from current() at finalize, so track it here)."""
    return _finalized_once
