"""Persistent requests + buffered-send machinery.

Re-design of the reference's persistent request path (ref:
ompi/mpi/c/send_init.c, recv_init.c, start.c, startall.c — pml ob1
reuses one request descriptor across starts) and the attached-buffer
Bsend engine (ref: ompi/mpi/c/buffer_attach.c, bsend.c;
ompi/runtime/ompi_mpi_preconnect.c-adjacent bsend allocator in
ompi/mca/pml/base/pml_base_bsend.c: user attaches one buffer, sends
carve regions, regions free on completion).

A persistent request here is a restartable wrapper: each start()
launches a fresh pml isend/irecv on the stored argument set; wait/
test delegate to the active inner request.  That matches the MPI
object model (INACTIVE → start → ACTIVE → completion → INACTIVE)
without complicating the ob1 fast path.
"""

from __future__ import annotations

import threading
from typing import List, Optional

import numpy as np

from ompi_tpu.pml.request import Request, Status


class PersistentRequest(Request):
    """MPI_Send_init / MPI_Recv_init result; start() re-arms it."""

    KIND_SEND = "send"
    KIND_RECV = "recv"

    def __init__(self, comm, kind: str, buf, count, datatype, peer: int,
                 tag: int, mode=None) -> None:
        super().__init__(comm.state.progress)
        self.persistent = True
        self.active = False
        self.complete = True     # inactive: wait() returns immediately
        self._comm = comm
        self._kind = kind
        self._args = (buf, count, datatype, peer, tag)
        self._mode = mode
        self._inner: Optional[Request] = None

    def start(self) -> "PersistentRequest":
        if self.active and self._inner is not None \
                and not self._inner.complete:
            raise RuntimeError(
                "MPI_Start on an active persistent request")
        buf, count, datatype, peer, tag = self._args
        pml = self._comm.state.pml
        if self._kind == self.KIND_SEND:
            if self._mode == "buffered":
                self._inner = ibsend(self._comm, buf, count, datatype,
                                     peer, tag)
            elif self._mode is not None:
                self._inner = pml.isend(buf, count, datatype, peer, tag,
                                        self._comm, self._mode)
            else:
                self._inner = pml.isend(buf, count, datatype, peer, tag,
                                        self._comm)
        else:
            self._inner = pml.irecv(buf, count, datatype, peer, tag,
                                    self._comm)
        self.active = True
        self.complete = False
        return self

    # delegate completion to the inner request; on completion the
    # persistent request becomes inactive-but-complete (restartable)
    def _sync_inner(self) -> None:
        if self._inner is not None and self._inner.complete \
                and not self.complete:
            self.status = self._inner.status
            self.complete = True
            self.active = False

    def test(self) -> bool:
        if self._inner is not None and not self._inner.complete:
            self._inner.test()
        self._sync_inner()
        return self.complete

    def wait(self, timeout: Optional[float] = None) -> Status:
        if self._inner is not None and not self.complete:
            self._inner.wait(timeout)
            self._sync_inner()
        return self.status

    def cancel(self) -> None:
        if self._inner is not None:
            self._inner.cancel()

    def free(self) -> None:
        self._inner = None


def start_all(reqs: List[PersistentRequest]) -> None:
    """MPI_Startall (ref: ompi/mpi/c/startall.c)."""
    for r in reqs:
        r.start()


# ---------------------------------------------------------------------------
# buffered sends (MPI_Buffer_attach / MPI_Bsend)
# ---------------------------------------------------------------------------

BSEND_OVERHEAD = 64  # per-message bookkeeping allowance (MPI_BSEND_OVERHEAD)


class BsendBuffer:
    """The single attached buffer; regions are carved per Bsend and
    recycled when the underlying send completes (swept on demand,
    like pml_base_bsend's allocator)."""

    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        self._in_use = 0
        self._pending: List[tuple] = []  # (nbytes, request)
        self._lock = threading.Lock()

    def _sweep(self) -> None:
        done = [(n, r) for n, r in self._pending if r.complete]
        for item in done:
            self._pending.remove(item)
            self._in_use -= item[0]

    def alloc(self, nbytes: int, progress) -> bool:
        with self._lock:
            self._sweep()
            total = nbytes + BSEND_OVERHEAD
            if self._in_use + total > self.capacity:
                # one progress push, then retry once — completions may
                # be sitting unswept
                progress.progress()
                self._sweep()
                if self._in_use + total > self.capacity:
                    return False
            self._in_use += total
            return True

    def record(self, nbytes: int, req) -> None:
        with self._lock:
            self._pending.append((nbytes + BSEND_OVERHEAD, req))

    def release(self, nbytes: int) -> None:
        """Back out a reservation whose send never launched."""
        with self._lock:
            self._in_use -= nbytes + BSEND_OVERHEAD

    def drain(self) -> None:
        """Block until every buffered send completes (detach rule)."""
        while True:
            with self._lock:
                self._sweep()
                pending = list(self._pending)
            if not pending:
                return
            pending[0][1].wait()


def attach_buffer(state, size_or_buf) -> None:
    """MPI_Buffer_attach: one buffer per process (rank)."""
    if getattr(state, "bsend_buffer", None) is not None:
        raise RuntimeError("a bsend buffer is already attached "
                           "(MPI_ERR_BUFFER)")
    size = size_or_buf if isinstance(size_or_buf, int) \
        else np.asarray(size_or_buf).nbytes
    state.bsend_buffer = BsendBuffer(size)


def detach_buffer(state) -> int:
    """MPI_Buffer_detach: blocks until pending buffered sends drain."""
    buf = getattr(state, "bsend_buffer", None)
    if buf is None:
        raise RuntimeError("no bsend buffer attached (MPI_ERR_BUFFER)")
    buf.drain()
    state.bsend_buffer = None
    return buf.capacity


def ibsend(comm, buf, count, datatype, dst: int, tag: int) -> Request:
    """Copy into the attached buffer, then a normal isend of the copy
    — the user buffer is reusable the moment this returns."""
    from ompi_tpu.coll.buffers import typed

    state = comm.state
    bb = getattr(state, "bsend_buffer", None)
    if bb is None:
        raise RuntimeError("MPI_Bsend without an attached buffer "
                           "(MPI_ERR_BUFFER)")
    tb = typed(buf, count, datatype)
    nbytes = tb.arr.nbytes
    if not bb.alloc(nbytes, state.progress):
        raise RuntimeError(
            f"bsend buffer exhausted: need {nbytes + BSEND_OVERHEAD} "
            f"bytes (MPI_ERR_BUFFER)")
    # typed() already packed strided/derived buffers into a fresh
    # array; only a zero-copy contiguous view needs the defensive copy
    copy = tb.arr if tb._copied else np.array(tb.arr, copy=True)
    from ompi_tpu.coll.buffers import mpi_dtype_of
    try:
        req = state.pml.isend(copy, copy.size, mpi_dtype_of(copy), dst,
                              tag, comm)
    except BaseException:
        bb.release(nbytes)  # the reservation would otherwise leak
        raise
    bb.record(nbytes, req)
    return req


def bsend(comm, buf, count, datatype, dst: int, tag: int) -> None:
    ibsend(comm, buf, count, datatype, dst, tag)
    # MPI_Bsend returns once the message is buffered — it already is
