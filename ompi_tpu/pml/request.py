"""Request lifecycle + completion (ref: ompi/request/request.h:381-432
— wait blocks on wait_sync, completion via atomic state transition;
test/wait{,all,any,some} in ompi/mpi/c/).
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

from ompi_tpu.runtime.progress import Progress, WaitSync

ANY_SOURCE = -1
ANY_TAG = -1
PROC_NULL = -2

SUCCESS = 0
ERR_TRUNCATE = 15
ERR_PENDING = 19

# ULFM classes: a request drained with one of these must RAISE from
# wait (the op cannot have delivered data; silently returning a
# status would let the app consume garbage from a dead peer)
_ULFM_CODES = (75, 76, 77)  # PROC_FAILED, PROC_FAILED_PENDING, REVOKED


class Status:
    __slots__ = ("source", "tag", "error", "count", "cancelled")

    def __init__(self) -> None:
        self.source = ANY_SOURCE
        self.tag = ANY_TAG
        self.error = SUCCESS
        self.count = 0
        self.cancelled = False

    def get_count(self, datatype) -> int:
        if datatype.size == 0:
            return 0
        if self.count % datatype.size:
            return -1  # MPI_UNDEFINED
        return self.count // datatype.size

    def __repr__(self) -> str:
        return (f"Status(src={self.source}, tag={self.tag}, "
                f"err={self.error}, count={self.count})")


class Request:
    """Base request; owned (progressed) by the rank that created it."""

    def __init__(self, progress: Progress) -> None:
        self._progress = progress
        self._sync = WaitSync(1)
        self.status = Status()
        self.complete = False
        self.cancelled = False
        self.persistent = False
        self.active = True

    def _complete(self) -> None:
        self.complete = True
        self._sync.signal()

    def test(self) -> bool:
        if not self.complete:
            self._progress.progress()
        return self.complete

    def wait(self, timeout: Optional[float] = None) -> Status:
        if self._progress.interrupt is not None:
            # armed interrupts (ft recovery, ulfm rank_kill) must fire
            # even when the request completed inline: fast tcp/shm
            # paths may never enter the spin loop below, and a rank
            # that never runs progress can never be killed
            self._progress.progress()
        if not self.complete:
            self._sync.wait(self._progress, timeout)
        if not self.complete:
            raise TimeoutError("request wait timed out")
        if self.status.error in _ULFM_CODES:
            from ompi_tpu import errhandler as _eh
            raise _eh.MPIException(self.status.error)
        return self.status

    def cancel(self) -> None:
        """Best-effort MPI_Cancel (only unmatched receives succeed;
        matched/sent requests run to normal completion, per MPI)."""
        canceller = getattr(self, "_canceller", None)
        if canceller is not None and not self.complete:
            canceller(self)

    def free(self) -> None:
        pass


class CompletedRequest(Request):
    """Immediately-complete request (send-to-PROC_NULL etc.)."""

    def __init__(self, progress: Progress, count: int = 0) -> None:
        super().__init__(progress)
        self.status.count = count
        self._complete()


def wait_all(reqs: List[Request], timeout: Optional[float] = None
             ) -> List[Status]:
    deadline = None if timeout is None else time.monotonic() + timeout
    for r in reqs:
        t = None if deadline is None else max(0.0, deadline - time.monotonic())
        r.wait(t)
    return [r.status for r in reqs]


# pollers go through r.test(), not the raw `complete` flag: wrapper
# requests (e.g. PersistentRequest) sync their outer state there

def wait_any(reqs: List[Request]) -> int:
    if not reqs:
        return -1
    while True:
        for i, r in enumerate(reqs):
            if r.complete or r.test():
                return i


def wait_some(reqs: List[Request]) -> List[int]:
    if not reqs:
        return []
    while True:
        done = [i for i, r in enumerate(reqs) if r.complete or r.test()]
        if done:
            return done


def test_all(reqs: List[Request]) -> bool:
    return all(r.complete or r.test() for r in reqs)


def test_any(reqs: List[Request]):
    """MPI_Testany analog: (index, status) of one completed request,
    or (-1, None) when none is ready ((-1, None) also for [] like
    wait_any's empty guard)."""
    if not reqs:
        return -1, None
    for i, r in enumerate(reqs):
        if r.complete or r.test():
            return i, r.status
    return -1, None


def test_some(reqs: List[Request]) -> List[int]:
    """MPI_Testsome analog: indices completed right now (may be
    empty; never blocks)."""
    return [i for i, r in enumerate(reqs) if r.complete or r.test()]


def request_get_status(req: Request):
    """MPI_Request_get_status: (flag, status) without freeing."""
    done = req.complete or req.test()
    return done, (req.status if done else None)


class Grequest(Request):
    """Generalized request (ref: ompi/mpi/c/grequest_start.c): the
    user signals completion via .complete_now(); query_fn fills the
    status at completion-query time, free_fn/cancel_fn at the
    respective lifecycle points."""

    def __init__(self, progress: Progress, query_fn=None, free_fn=None,
                 cancel_fn=None, extra_state=None) -> None:
        super().__init__(progress)
        self._query_fn = query_fn
        self._free_fn = free_fn
        self._cancel_fn = cancel_fn
        self._extra = extra_state

    def complete_now(self) -> None:
        """MPI_Grequest_complete."""
        if self._query_fn is not None:
            self._query_fn(self._extra, self.status)
        self._complete()

    def cancel(self) -> None:
        if self._cancel_fn is not None:
            self._cancel_fn(self._extra, self.complete)
        super().cancel()

    def free(self) -> None:
        if self._free_fn is not None:
            self._free_fn(self._extra)
        super().free()
