"""Request lifecycle + completion (ref: ompi/request/request.h:381-432
— wait blocks on wait_sync, completion via atomic state transition;
test/wait{,all,any,some} in ompi/mpi/c/).
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

from ompi_tpu.runtime.progress import Progress, WaitSync

ANY_SOURCE = -1
ANY_TAG = -1
PROC_NULL = -2

SUCCESS = 0
ERR_TRUNCATE = 15
ERR_PENDING = 19


class Status:
    __slots__ = ("source", "tag", "error", "count", "cancelled")

    def __init__(self) -> None:
        self.source = ANY_SOURCE
        self.tag = ANY_TAG
        self.error = SUCCESS
        self.count = 0
        self.cancelled = False

    def get_count(self, datatype) -> int:
        if datatype.size == 0:
            return 0
        if self.count % datatype.size:
            return -1  # MPI_UNDEFINED
        return self.count // datatype.size

    def __repr__(self) -> str:
        return (f"Status(src={self.source}, tag={self.tag}, "
                f"err={self.error}, count={self.count})")


class Request:
    """Base request; owned (progressed) by the rank that created it."""

    def __init__(self, progress: Progress) -> None:
        self._progress = progress
        self._sync = WaitSync(1)
        self.status = Status()
        self.complete = False
        self.cancelled = False
        self.persistent = False
        self.active = True

    def _complete(self) -> None:
        self.complete = True
        self._sync.signal()

    def test(self) -> bool:
        if not self.complete:
            self._progress.progress()
        return self.complete

    def wait(self, timeout: Optional[float] = None) -> Status:
        if not self.complete:
            self._sync.wait(self._progress, timeout)
        if not self.complete:
            raise TimeoutError("request wait timed out")
        return self.status

    def cancel(self) -> None:
        """Best-effort MPI_Cancel (only unmatched receives succeed;
        matched/sent requests run to normal completion, per MPI)."""
        canceller = getattr(self, "_canceller", None)
        if canceller is not None and not self.complete:
            canceller(self)

    def free(self) -> None:
        pass


class CompletedRequest(Request):
    """Immediately-complete request (send-to-PROC_NULL etc.)."""

    def __init__(self, progress: Progress, count: int = 0) -> None:
        super().__init__(progress)
        self.status.count = count
        self._complete()


def wait_all(reqs: List[Request], timeout: Optional[float] = None
             ) -> List[Status]:
    deadline = None if timeout is None else time.monotonic() + timeout
    for r in reqs:
        t = None if deadline is None else max(0.0, deadline - time.monotonic())
        r.wait(t)
    return [r.status for r in reqs]


# pollers go through r.test(), not the raw `complete` flag: wrapper
# requests (e.g. PersistentRequest) sync their outer state there

def wait_any(reqs: List[Request]) -> int:
    if not reqs:
        return -1
    while True:
        for i, r in enumerate(reqs):
            if r.complete or r.test():
                return i


def wait_some(reqs: List[Request]) -> List[int]:
    if not reqs:
        return []
    while True:
        done = [i for i, r in enumerate(reqs) if r.complete or r.test()]
        if done:
            return done


def test_all(reqs: List[Request]) -> bool:
    return all(r.complete or r.test() for r in reqs)
