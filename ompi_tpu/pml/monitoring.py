"""pml/monitoring: interposition layer counting traffic per peer.

Re-design of ompi/mca/pml/monitoring (ref: pml_monitoring.h:26-41 —
a pml component that layers itself over the real pml and counts
messages/bytes per destination, splitting user traffic from internal
"filtered" traffic by tag sign; results surface as MPI_T pvars and a
dumpable traffic matrix, cf. test/monitoring/monitoring_prof.c +
profile2mat.pl).

Enable with ``--mca pml_monitoring_enable 1`` (or programmatically via
``registry.set``); mpi_init then wraps the selected pml engine.  The
wrapper delegates everything it doesn't instrument, so ob1 internals
(matching, rndv, progress) are untouched — interposition, not
modification, exactly like the reference.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ompi_tpu.mca.params import registry
from ompi_tpu.pml.request import ANY_TAG

enable_var = registry.register(
    "pml", "monitoring", "enable", False, bool,
    help="Interpose the monitoring layer over the selected pml and "
         "count per-peer messages/bytes (user vs internal traffic)")

dump_path_var = registry.register(
    "pml", "monitoring", "dump_path", "", str,
    help="Prefix for the finalize-time traffic-matrix dump: each rank "
         "writes {path}.{rank}.prof ('src dst msgs bytes' lines) and "
         "rank 0 aggregates them into {path}_msg.mat / _size.mat / "
         "_avg.mat (profile2mat.pl semantics)")


def _internal(tag: int) -> bool:
    """Internal traffic posts exact negative tags; ANY_TAG (-1) is a
    user-side wildcard, never an internal tag."""
    return tag < 0 and tag != ANY_TAG


class _Matrix:
    """Per-peer counters: messages and bytes, user vs internal."""

    def __init__(self, size: int) -> None:
        self.msgs = [0] * size
        self.bytes = [0] * size
        self.filtered_msgs = [0] * size
        self.filtered_bytes = [0] * size

    def count(self, peer: int, nbytes: int, internal: bool) -> None:
        if internal:
            self.filtered_msgs[peer] += 1
            self.filtered_bytes[peer] += nbytes
        else:
            self.msgs[peer] += 1
            self.bytes[peer] += nbytes


def _current_monitor() -> Optional["MonitoringPml"]:
    """The calling thread-rank's monitoring layer, if interposed.
    Pvar getters resolve through here so the process-global registry
    serves every rank (each thread-rank reads ITS matrix)."""
    from ompi_tpu.runtime import state as statemod
    st = statemod.maybe_current()
    pml = getattr(st, "pml", None) if st is not None else None
    return pml if isinstance(pml, MonitoringPml) else None


def _row(attr_outer: str, attr_inner: str):
    def getter():
        mon = _current_monitor()
        if mon is None:
            return []
        return list(getattr(getattr(mon, attr_outer), attr_inner))
    return getter


# pvars registered once at import (ref: the reference registers its
# pvars at component init; values resolve per-rank at read time)
registry.register_pvar("pml", "monitoring", "messages_count",
                       "Messages sent per peer (user traffic)",
                       "size", getter=_row("sent", "msgs"))
registry.register_pvar("pml", "monitoring", "messages_size",
                       "Bytes sent per peer (user traffic)",
                       "size", getter=_row("sent", "bytes"))
registry.register_pvar("pml", "monitoring", "filtered_count",
                       "Internal (tag<0) messages sent per peer",
                       "size", getter=_row("sent", "filtered_msgs"))
registry.register_pvar("pml", "monitoring", "filtered_size",
                       "Internal (tag<0) bytes sent per peer",
                       "size", getter=_row("sent", "filtered_bytes"))


class MonitoringPml:
    """Wraps the real pml; counts on the send and receive paths."""

    def __init__(self, pml, state) -> None:
        self._pml = pml
        self._state = state
        self.sent = _Matrix(state.size)
        self.recvd = _Matrix(state.size)
        # per-instance: each thread-rank only mutates its own matrix
        self._lock = threading.Lock()

    # -- instrumented entry points --------------------------------------
    def _peer_global(self, comm, peer: int) -> Optional[int]:
        if peer is None or peer < 0 or peer >= comm.size:
            return None
        return comm.group[peer]

    def _count_send(self, comm, dst, count, datatype, tag) -> None:
        g = self._peer_global(comm, dst)
        if g is None:
            return
        with self._lock:
            self.sent.count(g, count * datatype.size, _internal(tag))

    def _count_recv_status(self, comm, status) -> None:
        if status is None or status.source is None or status.source < 0:
            return
        g = self._peer_global(comm, status.source)
        if g is None:
            return
        with self._lock:
            self.recvd.count(g, status.count, _internal(status.tag))

    def send(self, buf, count, datatype, dst, tag, comm, *a, **kw):
        self._count_send(comm, dst, count, datatype, tag)
        return self._pml.send(buf, count, datatype, dst, tag, comm,
                              *a, **kw)

    def isend(self, buf, count, datatype, dst, tag, comm, *a, **kw):
        self._count_send(comm, dst, count, datatype, tag)
        return self._pml.isend(buf, count, datatype, dst, tag, comm,
                               *a, **kw)

    def recv(self, buf, count, datatype, src, tag, comm, *a, **kw):
        st = self._pml.recv(buf, count, datatype, src, tag, comm, *a, **kw)
        self._count_recv_status(comm, st)
        return st

    # irecv completion is asynchronous; count at post time with the
    # posted size (upper bound), like the reference counts at the pml
    # entry rather than at completion
    def irecv(self, buf, count, datatype, src, tag, comm, *a, **kw):
        req = self._pml.irecv(buf, count, datatype, src, tag, comm,
                              *a, **kw)
        g = self._peer_global(comm, src) if src is not None and src >= 0 \
            else None
        if g is not None:
            with self._lock:
                self.recvd.count(g, count * datatype.size, _internal(tag))
        return req

    # everything else passes straight through (probe/improbe/mrecv/
    # add_procs/progress/state_comm_peer/cancel...)
    def __getattr__(self, name):
        return getattr(self._pml, name)

    # -- reporting -------------------------------------------------------
    def matrix_rows(self) -> Dict[str, List[int]]:
        with self._lock:
            return {
                "sent_msgs": list(self.sent.msgs),
                "sent_bytes": list(self.sent.bytes),
                "sent_filtered_msgs": list(self.sent.filtered_msgs),
                "sent_filtered_bytes": list(self.sent.filtered_bytes),
                "recv_msgs": list(self.recvd.msgs),
                "recv_bytes": list(self.recvd.bytes),
            }

    def dump(self, path: str) -> None:
        """One 'src dst msgs bytes' line per nonzero peer (the
        profile2mat.pl input format)."""
        me = self._state.rank
        with open(path, "w") as fh:
            for peer in range(self._state.size):
                if self.sent.msgs[peer] or self.sent.bytes[peer]:
                    fh.write(f"{me} {peer} {self.sent.msgs[peer]} "
                             f"{self.sent.bytes[peer]}\n")


def count_offload(comm, nbytes: int) -> None:
    """Count a collective that bypassed the pml entirely (sm/device
    rendezvous: the collective happens in shared memory or on-device,
    ref coll/sm's shared segment which the reference's pml/monitoring
    also cannot see).  We do better than the reference here: the coll
    modules report the traffic the pml WOULD have carried — one
    internal message of ``nbytes`` to every other member — so the
    observability story survives the offload fast paths."""
    pml = getattr(comm.state, "pml", None)
    if not isinstance(pml, MonitoringPml):
        return
    me = comm.rank
    with pml._lock:
        for r in range(comm.size):
            if r != me:
                pml.sent.count(comm.group[r], nbytes, True)


def maybe_wrap(pml, state):
    """Called from mpi_init after pml selection (the reference winning
    component interposes the same way at init)."""
    if registry.lookup("pml", "monitoring", "enable", False):
        return MonitoringPml(pml, state)
    return pml


def _find_monitor(state) -> Optional[MonitoringPml]:
    """Unwrap the pml interposition chain (vprotocol may sit on top of
    monitoring) down to the MonitoringPml layer, if present."""
    pml = getattr(state, "pml", None)
    seen = 0
    while pml is not None and seen < 8:
        if isinstance(pml, MonitoringPml):
            return pml
        pml = pml.__dict__.get("_pml")
        seen += 1
    return None


def finalize_dump(state) -> None:
    """Per-rank finalize-time dump (called from mpi_finalize BEFORE the
    fence so every rank's .prof exists when rank 0 aggregates)."""
    path = registry.lookup("pml", "monitoring", "dump_path", "")
    if not path:
        return
    mon = _find_monitor(state)
    if mon is None:
        return
    try:
        mon.dump(f"{path}.{state.rank}.prof")
    except OSError:
        pass  # an unwritable dump path must not break finalize


def finalize_aggregate(state) -> None:
    """Rank 0 merges the per-rank .prof files into the three matrices
    (called AFTER the fence — all dumps are on disk by then)."""
    path = registry.lookup("pml", "monitoring", "dump_path", "")
    if not path or _find_monitor(state) is None:
        return
    world = getattr(state, "comm_world", None)
    if world is None or world.rank != 0:
        return
    try:
        profile2mat(path)
    except (OSError, ValueError):
        pass


def profile2mat(prefix: str) -> Dict[str, List[List[float]]]:
    """test/monitoring/profile2mat.pl analog: glob {prefix}.*.prof,
    parse 'src dst msgs bytes' lines, and write three N x N
    space-separated matrices — {prefix}_msg.mat (message counts),
    {prefix}_size.mat (byte totals), {prefix}_avg.mat (bytes/msg).
    Returns the matrices for tests."""
    import glob as _glob

    entries: List[tuple] = []
    nmax = -1
    for fname in sorted(_glob.glob(f"{prefix}.*.prof")):
        with open(fname) as fh:
            for line in fh:
                parts = line.split()
                if len(parts) != 4:
                    continue
                src, dst, msgs, nbytes = (int(parts[0]), int(parts[1]),
                                          int(parts[2]), int(parts[3]))
                entries.append((src, dst, msgs, nbytes))
                nmax = max(nmax, src, dst)
    n = nmax + 1
    msg = [[0] * n for _ in range(n)]
    size = [[0] * n for _ in range(n)]
    for src, dst, msgs, nbytes in entries:
        msg[src][dst] += msgs
        size[src][dst] += nbytes
    avg = [[(size[i][j] / msg[i][j] if msg[i][j] else 0.0)
            for j in range(n)] for i in range(n)]
    for suffix, mat in (("_msg", msg), ("_size", size), ("_avg", avg)):
        with open(f"{prefix}{suffix}.mat", "w") as fh:
            for row in mat:
                fh.write(" ".join(f"{v:g}" for v in row) + "\n")
    return {"msg": msg, "size": size, "avg": avg}
