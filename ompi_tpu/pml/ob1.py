"""PML ob1: the point-to-point matching + protocol engine.

Re-design of ompi/mca/pml/ob1 (protocol ladder ref:
pml_ob1_sendreq.h:354-399 and pml_ob1_sendreq.c:404-453,667,716-747;
matching ref: pml_ob1_recvfrag.c:102-186,510-558 — posted-recv queues,
unexpected queue, per-peer sequence ordering with a cant-match list).

Protocols:
  * eager  — packed payload ≤ btl.eager_limit rides in one MATCH frag;
    the send request completes locally (buffered semantics).
  * eager-sync — MATCH_SYNC requires a SYNC_ACK on match (MPI_Ssend).
  * rendezvous — RNDV carries the first eager_limit bytes + total
    size + sender request id; the receiver matches, unpacks the head,
    replies ACK; the sender streams the rest as FRAG segments of
    max_send_size, each positioned by packed offset (pipelined through
    the resumable convertor; the reference's RDMA PUT/GET schedule
    collapses to this because co-located ranks share memory and
    remote ones go through a streaming transport).

Concurrency model: actor-style.  All matching state belongs to the
owning rank; peers only append to ``inbox`` (a lock-free deque) and
ring the doorbell.  The owner drains the inbox inside its progress
sweep.  This replaces ob1's fine-grained matching locks.
"""

from __future__ import annotations

import itertools
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ompi_tpu import memchecker, peruse
from ompi_tpu import trace as _trace
from ompi_tpu.datatype.convertor import Convertor, make_convertor
from ompi_tpu.mca.base import Component, frameworks
from ompi_tpu.mca.params import registry
from .request import (ANY_SOURCE, ANY_TAG, PROC_NULL, ERR_TRUNCATE,
                      CompletedRequest, Request, Status)

pml_framework = frameworks.create("ompi", "pml")

# interned trace ids as module constants: the span call sites pass
# small ints, never strings, on the hot path
_CAT_P2P = _trace.CAT_P2P
_NAME_SEND = _trace.NAME_SEND
_NAME_RECV = _trace.NAME_RECV
_CAT_PHASE = _trace.CAT_PHASE
_NAME_PH_RDV = _trace.NAME_PH_RDV
_HIST_RDV = _trace.HIST_RDV_WAIT

registry.register(
    "pml", "ob1", "rsend_is_standard", True, bool,
    help="Ready sends are executed as standard sends (the reference's "
         "ob1 behavior): a missing matching receive is NOT detected, "
         "so erroneous ready-mode programs run silently.  Read-only "
         "declaration for ompi_info.")

# Send modes
MODE_STANDARD = 0
MODE_SYNC = 1
MODE_READY = 2
MODE_BUFFERED = 3

# Frag kinds (tuple tag at index 0)
MATCH = "M"
MATCH_OBJ = "MO"   # opaque-object payload (device arrays, btl/tpu)
MATCH_SYNC = "MS"
RNDV = "R"
ACK = "A"
SYNC_ACK = "SA"
FRAG = "F"
VACK = "VA"        # vprotocol consumed-seq receiver ack (log GC)
MSEG = "MG"        # segmented MATCH: vprotocol replay of payloads
#                    larger than one transport frame (a raw MATCH
#                    bigger than the shm ring can never be pushed;
#                    ADVICE r4).  Reassembled BEFORE sequencing, then
#                    dispatched as a normal MATCH / MATCH_OBJ.


class SendRequest(Request):
    __slots__ = ("conv", "req_id", "total", "dst", "cid", "acked",
                 "mc_crc", "tr")

    def __init__(self, progress, conv, req_id, dst, cid=-1):
        super().__init__(progress)
        self.conv = conv
        self.req_id = req_id
        self.total = conv.packed_size
        self.dst = dst           # GLOBAL rank (failure matching)
        self.cid = cid           # communicator id (revoke matching)
        self.tr = None  # (t0_ns, cid, src, tag, seq) while traced


class RecvRequest(Request):
    __slots__ = ("conv", "req_id", "src", "tag", "cid", "matched",
                 "expected", "received", "incoming", "_canceller",
                 "_held", "tr")

    def __init__(self, progress, conv, req_id, src, tag, cid):
        super().__init__(progress)
        self._canceller = None
        self.tr = None  # [t0_ns, cid, src, tag, seq] while traced
        self.conv = conv
        self.req_id = req_id
        self.src = src
        self.tag = tag
        self.cid = cid
        self.matched = False
        self.expected = 0   # bytes that will actually arrive
        self.received = 0   # contiguous coverage watermark
        self.incoming = 0   # sender's total (for truncation check)
        self._held = None   # out-of-order coverage intervals {pos: end}


class UnexpectedMsg:
    """A matched-nothing incoming message buffered for a future recv
    (or probe/mprobe)."""

    __slots__ = ("kind", "cid", "src", "tag", "seq", "total", "sreq_id",
                 "payload", "arrival")
    _arrival_counter = itertools.count()

    def __init__(self, kind, cid, src, tag, seq, total, sreq_id, payload):
        self.kind = kind
        self.cid = cid
        self.src = src
        self.tag = tag
        self.seq = seq
        self.total = total
        self.sreq_id = sreq_id
        self.payload = payload
        self.arrival = next(UnexpectedMsg._arrival_counter)


class PmlOb1:
    """One matching engine per rank."""

    def __init__(self, state) -> None:
        self.state = state
        self.inbox: deque = deque()
        self.endpoints: List = []   # filled by add_procs
        self._req_counter = itertools.count(1)
        self._send_reqs: Dict[int, SendRequest] = {}
        self._recv_reqs: Dict[int, RecvRequest] = {}
        # matching state, keyed per communicator cid
        self._posted: Dict[int, List[RecvRequest]] = {}
        self._unexpected: Dict[int, List[UnexpectedMsg]] = {}
        self._send_seq: Dict[Tuple[int, int], int] = {}     # (cid,dst)->seq
        self._next_seq: Dict[Tuple[int, int], int] = {}     # (cid,src)->seq
        self._cant_match: Dict[Tuple[int, int], Dict[int, UnexpectedMsg]] = {}
        # (cid, src, seq, gsrc) -> [bytearray, filled]: in-progress
        # segmented replay reassembly (MSEG; vprotocol only)
        self._mseg: Dict[tuple, list] = {}
        # (cid, src, seq) triples an uncoordinated restart expects to
        # be REDELIVERED by vprotocol replay although their sequence
        # slot was consumed pre-snapshot (the message was in the
        # unexpected queue at capture; payload not snapshotted — the
        # sender's log carries it)
        self._replay_want: set = set()
        self.pvar_sent = registry.register_pvar(
            "pml", "ob1", f"bytes_sent_r{state.rank}")
        self.pvar_recv = registry.register_pvar(
            "pml", "ob1", f"bytes_recv_r{state.rank}")
        # checkpoint/restart bookmark counters (crcp/bkmrk analog,
        # ref: ompi/mca/crcp/bkmrk/crcp_bkmrk_pml.c): user-tag message
        # envelopes sent to / arrived from each GLOBAL rank.  Quiesce
        # drains until every pair's counts match (see ompi_tpu/cr).
        self.cr_sent: Dict[int, int] = {}
        self.cr_arrived: Dict[int, int] = {}
        # span tracer cached once (mpi_init attaches it before pml
        # selection): the p2p hot paths pay one is-None check when
        # tracing is off — the peruse-flag discipline
        self._tracer = getattr(state, "tracer", None)
        # ULFM state, same caching discipline; u.active only flips
        # once the first failure/revoke record arrives, so the
        # healthy-path cost is one attribute fetch + one falsy check
        self._ulfm = getattr(state, "ulfm", None)
        state.progress.register(self.progress)

    # -- wiring ----------------------------------------------------------
    def add_procs(self, endpoints) -> None:
        self.endpoints = endpoints

    def _ep(self, peer_global: int):
        ep = self.endpoints[peer_global]
        if ep is None:
            raise RuntimeError(f"no btl route to rank {peer_global}")
        return ep

    # -- send ------------------------------------------------------------
    def _envelope(self, dst, tag, comm):
        """Shared send-side bookkeeping: rank check + translation,
        per-(cid,dst) sequencing, C/R sent counting.  Returns
        (gdst, endpoint, seq)."""
        if not 0 <= dst < len(comm.group):
            # comm.group is the p2p translation table: the membership
            # for intracomms, the REMOTE group for intercomms
            raise ValueError(
                f"invalid rank {dst} for {len(comm.group)}-rank "
                "destination group (MPI_ERR_RANK)")
        gdst = comm.group[dst]
        ep = self._ep(gdst)
        key = (comm.cid, dst)
        seq = self._send_seq.get(key, 0)
        self._send_seq[key] = seq + 1
        if tag >= 0:
            self.cr_sent[gdst] = self.cr_sent.get(gdst, 0) + 1
        return gdst, ep, seq

    def isend(self, buf, count, datatype, dst, tag, comm,
              mode=MODE_STANDARD, offset: int = 0) -> Request:
        if dst == PROC_NULL:
            return CompletedRequest(self.state.progress)
        u = self._ulfm
        if u is not None and u.active:
            u.poll()
            u.check_peer(comm, dst)
        # convertor construction FIRST: an argument error must not
        # consume the (cid,dst) sequence number (a burned seq wedges
        # the channel — the receiver can never match past the hole)
        conv = make_convertor(datatype, count, buf, offset=offset)
        gdst, ep, seq = self._envelope(dst, tag, comm)
        btl = ep.btl
        cid = comm.cid
        src = comm.rank
        req_id = next(self._req_counter)
        req = SendRequest(self.state.progress, conv, req_id, gdst, cid)
        req.status.count = conv.packed_size
        self.pvar_sent.add(conv.packed_size)
        if peruse.enabled:
            peruse.fire("req_activate", kind="send", cid=cid, peer=dst,
                        tag=tag, bytes=conv.packed_size)
        if self._tracer is not None:
            # the match-id components (identical on the receiver's
            # span) ride as ints; the mid string traceview stitches
            # on is synthesized at snapshot time, off the hot path
            t0 = self._tracer.start_sampled(_CAT_P2P)
            if t0:
                req.tr = (t0, cid, src, tag, seq)

        gsrc = self.state.rank  # global sender id (C/R bookkeeping)
        if conv.packed_size <= btl.eager_limit and mode != MODE_SYNC:
            # pack_bytes: the request completes NOW, but the frag may
            # sit in a transport queue — the payload must own its bytes
            payload = conv.pack_bytes()
            ep.send((MATCH, cid, src, tag, seq, gsrc, payload))
            req._complete()
            if peruse.enabled:
                peruse.fire("req_complete", kind="send",
                            bytes=req.total)
            if req.tr is not None:
                self._trace_p2p_end(req, _NAME_SEND, req.total)
        elif conv.packed_size <= btl.eager_limit:  # sync eager
            payload = conv.pack_bytes()
            self._send_reqs[req_id] = req
            ep.send((MATCH_SYNC, cid, src, tag, seq, gsrc,
                     req_id, payload))
        else:
            if memchecker.enabled():
                req.mc_crc = memchecker.send_checksum(conv)
            head = conv.pack_bytes(btl.eager_limit)
            self._send_reqs[req_id] = req
            ep.send((RNDV, cid, src, tag, seq, gsrc,
                     conv.packed_size, req_id, head))
        return req

    def send(self, buf, count, datatype, dst, tag, comm,
             mode=MODE_STANDARD, offset: int = 0) -> Status:
        return self.isend(buf, count, datatype, dst, tag, comm, mode,
                          offset).wait()

    # -- opaque-object channel (device payloads; btl/tpu shim) ----------
    def isend_obj(self, obj, dst, tag, comm) -> None:
        """Eager send of an opaque payload object: same envelope and
        sequencing as byte messages, but a DISTINCT kind (MATCH_OBJ)
        so object messages can never bind a posted byte receive (and
        byte probes never steal them).  The object rides by reference
        through inproc and host-stages (pickle) across processes."""
        if dst == PROC_NULL:
            return
        gdst, ep, seq = self._envelope(dst, tag, comm)
        ep.send((MATCH_OBJ, comm.cid, comm.rank, tag, seq,
                 self.state.rank, obj))

    def poll_obj_any(self, tag):
        """Non-blocking: pop one buffered object message with ``tag``
        from ANY communicator's unexpected queue (no progress call —
        this runs INSIDE a progress sweep).  The btl/tpu pull
        protocol services its PULL requests this way: an active-
        message handler in the reference (ref:
        ompi/mca/osc/pt2pt's AM dispatch), a progress-driven poll
        here."""
        for lst in self._unexpected.values():
            for m in lst:
                if m.kind == MATCH_OBJ and m.tag == tag:
                    lst.remove(m)
                    return m
        return None

    def recv_obj(self, src, tag, comm):
        """Blocking matched receive of an object message (kind
        MATCH_OBJ only) returning the UnexpectedMsg with its payload
        uninterpreted (no convertor)."""
        if src == PROC_NULL:
            return None
        while True:
            self.state.progress.progress()
            best = self._find_unexpected(comm.cid, src, tag,
                                         want_obj=True)
            if best is not None:
                self._unexpected[comm.cid].remove(best)
                return best
            self.state.progress.idle_tick()

    # -- recv ------------------------------------------------------------
    def irecv(self, buf, count, datatype, src, tag, comm,
              offset: int = 0) -> RecvRequest:
        if src == PROC_NULL:
            r = CompletedRequest(self.state.progress)
            r.status.source = PROC_NULL
            r.status.tag = ANY_TAG
            return r
        u = self._ulfm
        if u is not None and u.active:
            u.poll()
            u.check_peer(comm, src)
        conv = make_convertor(datatype, count, buf, offset=offset,
                              writable=True) \
            if buf is not None else Convertor(datatype, 0, b"")
        req_id = next(self._req_counter)
        req = RecvRequest(self.state.progress, conv, req_id, src, tag,
                          comm.cid)
        req._canceller = self.cancel_recv
        self._recv_reqs[req_id] = req
        if peruse.enabled:
            peruse.fire("req_activate", kind="recv", cid=comm.cid,
                        peer=src, tag=tag, bytes=conv.packed_size)
        if self._tracer is not None:
            # match-id ints filled at match time (_bind) once the
            # sender's src/seq are known
            t0 = self._tracer.start_sampled(_CAT_P2P)
            if t0:
                req.tr = [t0, 0, 0, 0, 0]
        if memchecker.enabled() and buf is not None:
            memchecker.poison_recv(conv)
        # match against buffered unexpected messages first
        msg = self._match_unexpected(req)
        if msg is not None:
            self._bind(req, msg)
        else:
            self._posted.setdefault(comm.cid, []).append(req)
        return req

    def recv(self, buf, count, datatype, src, tag, comm,
             offset: int = 0) -> Status:
        return self.irecv(buf, count, datatype, src, tag, comm,
                          offset).wait()

    # -- probe -----------------------------------------------------------
    def iprobe(self, src, tag, comm) -> Optional[Status]:
        self.state.progress.progress()
        msg = self._find_unexpected(comm.cid, src, tag)
        if msg is None:
            return None
        st = Status()
        st.source = msg.src
        st.tag = msg.tag
        st.count = msg.total
        return st

    def probe(self, src, tag, comm) -> Status:
        while True:
            st = self.iprobe(src, tag, comm)
            if st is not None:
                return st
            self.state.progress.idle_tick()

    def improbe(self, src, tag, comm):
        """Matched probe: removes the message from matching
        (ref: ompi/message mprobe)."""
        self.state.progress.progress()
        msg = self._find_unexpected(comm.cid, src, tag)
        if msg is None:
            return None
        self._unexpected[comm.cid].remove(msg)
        return msg

    def mrecv(self, buf, count, datatype, msg, comm) -> Status:
        req_id = next(self._req_counter)
        conv = make_convertor(datatype, count, buf, writable=True)
        req = RecvRequest(self.state.progress, conv, req_id, msg.src,
                          msg.tag, comm.cid)
        self._recv_reqs[req_id] = req
        self._bind(req, msg)
        return req.wait()

    # -- matching internals ----------------------------------------------
    def _matchable(self, cid: int, src: int, seq: int) -> bool:
        return self._next_seq.get((cid, src), 0) == seq

    def _find_unexpected(self, cid, src, tag,
                         want_obj: bool = False) -> Optional[UnexpectedMsg]:
        # messages here already consumed their sequence number at
        # arrival dispatch; FIFO per source is preserved by arrival
        # order, so match the earliest arrival only.  ``want_obj``
        # selects the object channel (MATCH_OBJ) vs byte messages —
        # the two never match each other's receives.
        best = None
        for m in self._unexpected.get(cid, []):
            # ANY_TAG never matches reserved internal (negative) tags
            if (m.kind == MATCH_OBJ) == want_obj and \
               (src == ANY_SOURCE or m.src == src) and \
               (m.tag == tag or (tag == ANY_TAG and m.tag >= 0)):
                if best is None or m.arrival < best.arrival:
                    best = m
        return best

    def _match_unexpected(self, req: RecvRequest) -> Optional[UnexpectedMsg]:
        m = self._find_unexpected(req.cid, req.src, req.tag)
        if m is not None:
            self._unexpected[req.cid].remove(m)
        return m

    def _match_posted(self, cid, src, tag) -> Optional[RecvRequest]:
        posted = self._posted.get(cid, [])
        for req in posted:
            if req.cancelled:
                continue
            if (req.src == ANY_SOURCE or req.src == src) and \
               (req.tag == tag or (req.tag == ANY_TAG and tag >= 0)):
                posted.remove(req)
                return req
        return None

    def _advance_seq(self, cid, src) -> None:
        key = (cid, src)
        self._next_seq[key] = self._next_seq.get(key, 0) + 1
        if self._mseg:
            # straggler MSEG duplicates may have re-seeded a partial
            # reassembly for a seq that just got consumed (its full
            # assembly dispatched from _cant_match); such an entry can
            # never complete — purge it so cr_capture's in-flight
            # guard only fires for genuinely undeliverable messages
            nxt = self._next_seq[key]
            stale = [k for k in self._mseg
                     if k[0] == cid and k[1] == src and k[2] < nxt
                     and (cid, src, k[2]) not in self._replay_want]
            for k in stale:
                del self._mseg[k]
        # an out-of-order frag may now be matchable
        held = self._cant_match.get(key)
        if held:
            nxt = held.pop(self._next_seq[key], None)
            if nxt is not None:
                self._dispatch_arrival(nxt)

    def _bind(self, req: RecvRequest, msg: UnexpectedMsg) -> None:
        """Attach a matched incoming message to a recv request and run
        the receive-side protocol."""
        req.matched = True
        req.incoming = msg.total
        req.status.source = msg.src
        req.status.tag = msg.tag
        if req.tr is not None:
            rt = req.tr
            rt[1] = msg.cid
            rt[2] = msg.src
            rt[3] = msg.tag
            rt[4] = msg.seq
        capacity = req.conv.packed_size
        req.expected = min(msg.total, capacity)
        if msg.total > capacity:
            req.status.error = ERR_TRUNCATE
        self.pvar_recv.add(req.expected)
        head = msg.payload
        take = min(len(head), capacity)
        if take:
            req.conv.unpack(head[:take])
        req.received = len(head)  # count sender-sent bytes incl. dropped
        req.status.count = min(req.received, capacity)
        if msg.kind == MATCH_SYNC:
            ep = self._ep(self.state_comm_peer(msg.cid, msg.src))
            ep.send((SYNC_ACK, msg.sreq_id))
        if msg.kind == RNDV:
            gsrc = self.state_comm_peer(msg.cid, msg.src)
            ep = self._ep(gsrc)
            ep.send((ACK, msg.sreq_id, req.req_id))
        if req.received >= msg.total:
            req.status.count = min(msg.total, capacity)
            self._finish_recv(req)

    def _trace_p2p_end(self, req, name_id: int, nbytes: int) -> None:
        """Close a p2p span (activate → complete); feeds the
        p2p_complete latency histogram through the tracer."""
        t0, cid, src, tag, seq = req.tr
        req.tr = None
        self._tracer.end(t0, name_id, _CAT_P2P, cid, src, tag, seq,
                         nbytes)

    def _finish_recv(self, req: RecvRequest) -> None:
        self._recv_reqs.pop(req.req_id, None)
        req._complete()
        if peruse.enabled:
            peruse.fire("req_complete", kind="recv",
                        bytes=req.status.count)
        if req.tr is not None:
            self._trace_p2p_end(req, _NAME_RECV, req.status.count)

    def state_comm_peer(self, cid: int, comm_rank: int) -> int:
        comm = self.state.comms.get(cid)
        return comm.group[comm_rank]

    # -- inbox dispatch --------------------------------------------------
    def progress(self) -> int:
        n = 0
        while self.inbox:
            try:
                frag = self.inbox.popleft()
            except IndexError:
                break
            self._handle(frag)
            n += 1
        return n

    def _handle(self, frag: tuple) -> None:
        kind = frag[0]
        if kind in (MATCH, MATCH_OBJ, MATCH_SYNC, RNDV):
            if kind in (MATCH, MATCH_OBJ):
                _, cid, src, tag, seq, gsrc, payload = frag
                msg = UnexpectedMsg(kind, cid, src, tag, seq,
                                    len(payload), None, payload)
            elif kind == MATCH_SYNC:
                _, cid, src, tag, seq, gsrc, sreq_id, payload = frag
                msg = UnexpectedMsg(kind, cid, src, tag, seq,
                                    len(payload), sreq_id, payload)
            else:
                _, cid, src, tag, seq, gsrc, total, sreq_id, payload = frag
                msg = UnexpectedMsg(kind, cid, src, tag, seq, total,
                                    sreq_id, payload)
            # the envelope carries the sender's GLOBAL rank so C/R
            # bookkeeping never depends on resolving the cid locally
            # (the comm may be freed, reserved-None, or not yet built).
            # Count AFTER the sequence gate: transport-duplicate
            # envelopes (reconnect resends) must not inflate arrived.
            if self._dispatch_arrival(msg) and tag >= 0:
                self.cr_arrived[gsrc] = self.cr_arrived.get(gsrc, 0) + 1
        elif kind == ACK:
            _, sreq_id, rreq_id = frag
            self._send_rest(sreq_id, rreq_id)
        elif kind == SYNC_ACK:
            _, sreq_id = frag
            req = self._send_reqs.pop(sreq_id, None)
            if req is not None:
                req._complete()
                if peruse.enabled:
                    peruse.fire("req_complete", kind="send",
                                bytes=req.total)
                if req.tr is not None:
                    self._trace_p2p_end(req, _NAME_SEND, req.total)
        elif kind == FRAG:
            _, rreq_id, pos, payload = frag
            self._recv_segment(rreq_id, pos, payload)
        elif kind == MSEG:
            self._handle_mseg(frag)
        elif kind == VACK:
            # receiver-ack for the vprotocol sender log (GC); rides
            # the btl UNSEQUENCED — an ack must never consume a
            # sequence slot (it would itself need logging).  Ignored
            # unless a pessimist layer installed its handler.
            h = getattr(self, "vack_handler", None)
            if h is not None:
                h(frag[1])

    def _handle_mseg(self, frag: tuple) -> None:
        """Reassemble a segmented replay MATCH.  Segments are
        position-addressed (transports may interleave rails); the
        assembled message enters matching exactly as a single MATCH
        frame would — including the duplicate-sequence drop for
        receivers that already consumed it.

        Duplicate segments (a tcp reconnect resends every frame not
        provably written) must not double-count: coverage is tracked
        per position, mirroring _recv_segment's discipline.  And a
        segment for an already-consumed sequence number is dropped
        BEFORE assembly — after a completed reassembly advanced the
        sequence, straggler duplicates would otherwise re-seed a
        stale partial entry that lives forever."""
        _, cid, src, tag, seq, gsrc, total, kindcode, pos, chunk = frag
        if seq < self._next_seq.get((cid, src), 0) and \
                (cid, src, seq) not in self._replay_want:
            return  # consumed seq: this whole message is a duplicate
        key = (cid, src, seq, gsrc)
        entry = self._mseg.get(key)
        if entry is None:
            entry = self._mseg[key] = [bytearray(total), 0, set()]
        buf, got, seen = entry
        if pos in seen:
            return  # duplicated segment (transport resend): one replay
        #           chunks at a fixed stride, so positions identify
        #           segments exactly
        seen.add(pos)
        buf[pos:pos + len(chunk)] = chunk
        entry[1] = got + len(chunk)
        if entry[1] < total:
            return
        del self._mseg[key]
        if kindcode == 1:
            import pickle
            payload = pickle.loads(bytes(buf))
            msg = UnexpectedMsg(MATCH_OBJ, cid, src, tag, seq,
                                len(payload), None, payload)
        else:
            payload = bytes(buf)
            msg = UnexpectedMsg(MATCH, cid, src, tag, seq,
                                len(payload), None, payload)
        if self._dispatch_arrival(msg) and tag >= 0:
            self.cr_arrived[gsrc] = self.cr_arrived.get(gsrc, 0) + 1

    def _dispatch_arrival(self, msg: UnexpectedMsg) -> bool:
        """Sequence-gate an arrived envelope into matching.  Returns
        False when the message is a transport-duplicate that will
        never reach matching (its sequence slot was already consumed,
        or an identical copy is already parked) — callers must NOT
        count such arrivals in the C/R bookmark, or a reconnect
        resend permanently poisons the quiesce sent/arrived balance."""
        key = (msg.cid, msg.src)
        if not self._matchable(msg.cid, msg.src, msg.seq):
            if msg.seq < self._next_seq.get(key, 0):
                want = (msg.cid, msg.src, msg.seq)
                if want in self._replay_want:
                    # vprotocol replay of a message whose sequence
                    # slot was consumed before an uncoordinated
                    # snapshot: deliver to matching WITHOUT
                    # re-sequencing (its slot is already burned)
                    self._replay_want.discard(want)
                    self._match_or_buffer(msg)
                    return True
                # already-consumed sequence: a reconnect-resent
                # duplicate envelope.  Drop it — parking it in
                # _cant_match would leak it forever (its seq can
                # never become next; ADVICE r3 #3)
                return False
            held = self._cant_match.setdefault(key, {})
            dup = msg.seq in held
            held[msg.seq] = msg
            return not dup
        if self._replay_want:
            # normally-sequenced redelivery: the want entry is served
            self._replay_want.discard((msg.cid, msg.src, msg.seq))
        self._advance_seq(msg.cid, msg.src)
        self._match_or_buffer(msg)
        return True

    def _match_or_buffer(self, msg: UnexpectedMsg) -> None:
        if msg.kind == MATCH_OBJ:
            # object messages wait for recv_obj; a posted byte recv
            # must never bind one (its payload is not a buffer)
            self._unexpected.setdefault(msg.cid, []).append(msg)
            return
        req = self._match_posted(msg.cid, msg.src, msg.tag)
        if req is not None:
            if peruse.enabled:
                peruse.fire("req_match", cid=msg.cid, peer=msg.src,
                            tag=msg.tag, bytes=msg.total)
            self._bind(req, msg)
        else:
            if peruse.enabled:
                peruse.fire("req_match_unex", cid=msg.cid,
                            peer=msg.src, tag=msg.tag, bytes=msg.total)
            self._unexpected.setdefault(msg.cid, []).append(msg)

    def _send_rest(self, sreq_id: int, rreq_id: int) -> None:
        req = self._send_reqs.pop(sreq_id, None)
        if req is None:
            return
        tr = self._tracer
        if tr is not None and tr.phase and req.tr is not None:
            # host-path rendezvous wait (RNDV sent at isend, ACK just
            # arrived): rides the p2p span's sampling decision — no
            # second start_sampled, req.tr stays armed for the send
            # span closed below (docs/DESIGN.md §18)
            t0, cid, src, tag, seq = req.tr
            dur = tr.end(t0, _NAME_PH_RDV, _CAT_PHASE, cid, seq,
                         req.total)
            tr.hist_add(_HIST_RDV, dur * 1e-9)
        ep = self._ep(req.dst)
        btl = ep.btl
        conv = req.conv
        while not conv.done:
            pos = conv.position
            payload = conv.pack_bytes(btl.max_send_size)
            # position-addressed: stripes across same-tier rails
            # (receiver coverage is interval-based, order-free)
            ep.send_striped((FRAG, rreq_id, pos, payload))
        if memchecker.enabled():
            memchecker.verify_send(
                conv, getattr(req, "mc_crc", None),
                f"rendezvous send req {sreq_id}")
        req._complete()
        if peruse.enabled:
            peruse.fire("req_complete", kind="send", bytes=req.total)
        if req.tr is not None:
            self._trace_p2p_end(req, _NAME_SEND, req.total)

    def _recv_segment(self, rreq_id: int, pos: int, payload: bytes) -> None:
        req = self._recv_reqs.get(rreq_id)
        if req is None:
            return
        capacity = req.conv.packed_size
        if pos < capacity:
            take = min(len(payload), capacity - pos)
            req.conv.set_position(pos)
            req.conv.unpack(payload[:take])
        # coverage as watermark + held intervals: duplicated segments
        # (transport reconnect resends) never double-count, and a
        # segment arriving AHEAD of the watermark (a reconnected
        # conn's resend processed before the old conn's in-flight
        # data — the selector may interleave the two) is remembered
        # and merged once the gap fills, instead of silently dropped
        # (which stalled the recv forever; ADVICE r3 #1).  A LOST
        # segment (the unrecoverable kernel-buffer window of a dead
        # connection) still leaves a hole forever — the recv fails
        # stop via timeout instead of completing with one
        if pos <= req.received:
            req.received = max(req.received, pos + len(payload))
            held = req._held
            if held:
                # merge any held intervals the new watermark reaches
                while True:
                    nxt = [p for p in held if p <= req.received]
                    if not nxt:
                        break
                    for p in nxt:
                        end = held.pop(p)
                        if end > req.received:
                            req.received = end
        else:
            if req._held is None:
                req._held = {}
            end = pos + len(payload)
            if end > req._held.get(pos, 0):
                req._held[pos] = end
        if req.received >= req.incoming:
            req.status.count = min(req.incoming, capacity)
            self._finish_recv(req)

    # -- checkpoint/restart hooks (ompi_tpu/cr; crcp/bkmrk analog) -------
    def cr_pending_sends(self) -> int:
        """Send requests whose payload is not fully on the wire yet
        (rendezvous streams, sync-eager awaiting ACK)."""
        return len(self._send_reqs)

    def cr_capture(self) -> List[tuple]:
        """Snapshot the in-flight state a quiesced rank may legally
        hold: buffered-eager user messages in the unexpected queues.
        Everything else must be drained — a stuck rendezvous or
        out-of-order hold at quiesce is a protocol violation worth a
        loud failure, not a silent bad snapshot."""
        if self._send_reqs:
            raise RuntimeError(
                "cr_capture with pending send requests (quiesce bug)")
        if any(self._cant_match.values()):
            raise RuntimeError(
                "cr_capture with out-of-order frags held (messages "
                "still in flight)")
        if self._mseg:
            raise RuntimeError(
                "cr_capture with a partially reassembled replay "
                "message (sender died mid-replay?) — the message is "
                "neither capturable nor deliverable")
        msgs = []
        for cid, lst in self._unexpected.items():
            for m in sorted(lst, key=lambda u: u.arrival):
                if m.tag < 0:
                    # post-quiesce traffic from the checkpoint's own
                    # machinery (a faster rank's seq-Bcast fan-out can
                    # land here before we capture): leave it in place —
                    # it is consumed by OUR upcoming phase, never
                    # snapshotted
                    continue
                if m.kind == MATCH_OBJ:
                    from ompi_tpu.btl.tpu import _XferHdr
                    if isinstance(m.payload, _XferHdr):
                        # chunked-transfer header whose DATA is parked
                        # on the sender (captured there by the tpu
                        # rndv engine's cr_capture); snapshot the
                        # metadata so the pull protocol resumes after
                        # restart
                        h = m.payload
                        msgs.append((cid, m.src, m.tag, m.total,
                                     "xferhdr",
                                     (h.xfer_id, tuple(h.shape),
                                      h.dtype, h.nbytes, h.chunk)))
                        continue
                    # in-flight device payload (send_arr completed,
                    # recv_arr pending): host-stage it into the
                    # snapshot; restore reinjects it as an object
                    # message whose array is reborn on device at
                    # recv_arr time
                    msgs.append((cid, m.src, m.tag, m.total, "obj",
                                 np.asarray(m.payload.arr)))
                    continue
                if m.kind != MATCH:
                    raise RuntimeError(
                        f"cr_capture: {m.kind} message unmatched at "
                        "quiesce (sender's request could not have "
                        "completed — user requests must complete "
                        "before checkpoint)")
                msgs.append((cid, m.src, m.tag, m.total, "bytes",
                             bytes(m.payload)))
        return msgs

    def cr_capture_lenient(self) -> List[tuple]:
        """Uncoordinated (vprotocol) snapshot: record the (cid, src,
        seq) of every arrived-but-unconsumed message instead of its
        payload — the sender's log redelivers them after restart
        (replay_want bypasses the stale-seq drop).  Out-of-order
        holds are recorded too (replay covers the gap before them).
        Locally-incomplete requests are an app-contract violation
        either way."""
        if self._send_reqs:
            raise RuntimeError(
                "uncoordinated checkpoint with locally-incomplete "
                "send requests (wait/test them first)")
        for req in self._recv_reqs.values():
            if req.matched and not req.complete:
                raise RuntimeError(
                    "uncoordinated checkpoint with a matched, "
                    "partially-received request (wait it first)")
        want = []
        for cid, lst in self._unexpected.items():
            for m in lst:
                want.append((cid, m.src, m.seq))
        for (cid, src), held in self._cant_match.items():
            for seq in held:
                want.append((cid, src, seq))
        return want

    def cr_restore(self, msgs: List[tuple]) -> None:
        """Reinject snapshot-carried eager messages as fresh arrivals.
        Sequence numbers restart from zero on both sides after a
        restart, so reinjection bypasses sequencing (these envelopes
        already consumed their pre-checkpoint sequence slots)."""
        for entry in msgs:
            if len(entry) == 5:
                # pre-object-channel snapshot (5-tuple, bytes only)
                cid, src, tag, total, payload = entry
                kind = "bytes"
            else:
                cid, src, tag, total, kind, payload = entry
            if kind == "xferhdr":
                from ompi_tpu.btl.tpu import _XferHdr
                xid, shape, dtype, nbytes, chunk = payload
                m = UnexpectedMsg(MATCH_OBJ, cid, src, tag, 0, total,
                                  None,
                                  _XferHdr(xid, shape, dtype, nbytes,
                                           chunk))
            elif kind == "obj":
                from ompi_tpu.btl.tpu import DeviceArrayPayload
                m = UnexpectedMsg(MATCH_OBJ, cid, src, tag, 0, total,
                                  None, DeviceArrayPayload(payload))
            else:
                m = UnexpectedMsg(MATCH, cid, src, tag, 0, total,
                                  None, payload)
            self._unexpected.setdefault(cid, []).append(m)

    # -- live recovery (runtime/ft.py) -----------------------------------
    def ft_reset(self) -> None:
        """Epoch reset: drop every piece of matching and sequence
        state.  Both ends of every channel restart at zero — the
        snapshot all ranks reload has no in-flight traffic by quiesce
        construction, and stale transport bytes died with their
        connections in the btl reset that precedes this."""
        self.inbox.clear()
        self._send_reqs.clear()
        self._recv_reqs.clear()
        self._posted.clear()
        self._unexpected.clear()
        self._send_seq.clear()
        self._next_seq.clear()
        self._cant_match.clear()
        self._mseg.clear()
        self._replay_want.clear()
        self.cr_sent.clear()
        self.cr_arrived.clear()

    def ft_reset_peer(self, granks, comms) -> None:
        """Respawn rejoin (ft/respawn): a replaced rank restarts its
        pml at zero, so BOTH directions of every channel naming it
        must forget their sequence state — the survivor's next send
        to it carries seq 0 again, and seq 0 from it matches instead
        of parking in _cant_match behind the dead predecessor's
        counters.  Narrower than ft_reset: survivor<->survivor
        channels keep their live sequences (thread worlds never
        reset those — there is no transport flush to cover them)."""
        granks = set(granks)
        for comm in comms.values():
            if comm is None:
                continue
            group = list(comm.group)
            for r, g in enumerate(group):
                if g not in granks:
                    continue
                self._send_seq.pop((comm.cid, r), None)
                self._next_seq.pop((comm.cid, r), None)
                self._cant_match.pop((comm.cid, r), None)
                pend = self._unexpected.get(comm.cid)
                if pend:
                    self._unexpected[comm.cid] = [
                        m for m in pend if m.src != r]
                for key in [k for k in self._mseg
                            if k[0] == comm.cid and k[1] == r]:
                    del self._mseg[key]
        for g in granks:
            self.cr_sent.pop(g, None)
            self.cr_arrived.pop(g, None)

    # -- ULFM drain (ompi_tpu/ft/ulfm) ------------------------------------
    def ulfm_sweep(self, failed, revoked) -> int:
        """Complete every parked request naming a failed peer or a
        revoked communicator with the matching ULFM error class
        (Request.wait raises it) instead of hanging forever.  Called
        from UlfmState._ingest whenever a failure/revoke record is
        ingested — the drain half of detect → report."""
        from ompi_tpu import errhandler as _eh
        n = 0
        for req in list(self._send_reqs.values()):
            err = 0
            group = self._ulfm_group(req.cid)
            if group is not None and (req.cid, group) in revoked:
                err = _eh.ERR_REVOKED
            elif req.dst in failed:
                err = _eh.ERR_PROC_FAILED
            if err:
                self._send_reqs.pop(req.req_id, None)
                req.status.error = err
                req._complete()
                if req.tr is not None:
                    self._trace_p2p_end(req, _NAME_SEND, 0)
                n += 1
        for req in list(self._recv_reqs.values()):
            err = 0
            group = self._ulfm_group(req.cid)
            if group is not None:
                src = req.status.source if req.matched else req.src
                if (req.cid, group) in revoked:
                    err = _eh.ERR_REVOKED
                elif src == ANY_SOURCE:
                    # simplification vs the reference: a parked
                    # wildcard receive completes with the PENDING
                    # class rather than staying pending until
                    # failure_ack (there is no re-park here)
                    if any(g in failed for g in group):
                        err = _eh.ERR_PROC_FAILED_PENDING
                elif 0 <= src < len(group) and group[src] in failed:
                    err = _eh.ERR_PROC_FAILED
            if err:
                posted = self._posted.get(req.cid, [])
                if req in posted:
                    posted.remove(req)
                self._recv_reqs.pop(req.req_id, None)
                req.status.error = err
                req._complete()
                if req.tr is not None:
                    self._trace_p2p_end(req, _NAME_RECV, 0)
                n += 1
        return n

    def _ulfm_group(self, cid: int):
        comm = self.state.comms.get(cid)
        return None if comm is None else tuple(comm.group)

    # -- cancel ----------------------------------------------------------
    def cancel_recv(self, req: RecvRequest) -> bool:
        posted = self._posted.get(req.cid, [])
        if req in posted:
            posted.remove(req)
            req.cancelled = True
            req.status.cancelled = True
            self._recv_reqs.pop(req.req_id, None)
            req._complete()
            return True
        return False


class Ob1Component(Component):
    name = "ob1"
    priority = 20

    def query(self, state=None):
        return (self.priority, PmlOb1)


pml_framework.add_component(Ob1Component())
