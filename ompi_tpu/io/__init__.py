"""MPI-IO (io/ompio analog; SURVEY.md §2.5 io/fs/fbtl/fcoll/sharedfp).

    from ompi_tpu import io as mpiio
    f = mpiio.open(comm, "data.bin", mpiio.MODE_CREATE | mpiio.MODE_RDWR)
    f.write_at(comm.rank * n, arr)
    f.close()
"""

from ompi_tpu.io.file import (  # noqa: F401
    File, open, delete,
    MODE_APPEND, MODE_CREATE, MODE_DELETE_ON_CLOSE, MODE_EXCL,
    MODE_RDONLY, MODE_RDWR, MODE_SEQUENTIAL, MODE_UNIQUE_OPEN,
    MODE_WRONLY, SEEK_CUR, SEEK_END, SEEK_SET,
)
from ompi_tpu.io.view import FileView  # noqa: F401
