"""MPI-IO file objects (io/ompio analog).

Re-design of ompi/mca/io/ompio (ref: io_ompio_file_open.c,
io_ompio_file_read.c/write.c; sub-framework split per SURVEY.md §2.5:
fs = filesystem open/size ops, fbtl = individual byte transfer
[posix pread/pwrite here], fcoll = collective algorithms
[ompi_tpu.io.fcoll two-phase], sharedfp = shared file pointer [an
osc fetch_and_op counter owned by rank 0, the sharedfp/sm idea with
the window replacing the shared-memory segment]).

Positions are maintained in etype units like MPI file pointers;
views map them to file bytes (ompi_tpu.io.view).  Data moves through
the same TypedBuf packing the collectives use, so derived memory
datatypes and derived filetypes compose.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional, Tuple

import numpy as np

from ompi_tpu.coll.buffers import TypedBuf, typed
from ompi_tpu.datatype import engine as dtmod
from ompi_tpu.io.view import FileView
from ompi_tpu.pml.request import CompletedRequest, Status

# MPI open-mode bits (mpi.h values)
MODE_CREATE = 1
MODE_RDONLY = 2
MODE_WRONLY = 4
MODE_RDWR = 8
MODE_DELETE_ON_CLOSE = 16
MODE_UNIQUE_OPEN = 32
MODE_EXCL = 64
MODE_APPEND = 128
MODE_SEQUENTIAL = 256

SEEK_SET = 0
SEEK_CUR = 1
SEEK_END = 2


# user data representations (MPI_Register_datarep, ref:
# ompi/mpi/c/register_datarep.c + ompi/mca/io/base registration):
# name -> (read_fn, write_fn, extent_fn, extra_state).  Conversion
# functions take (filebytes_or_userbytes, datatype, count, extra)
# and return converted bytes of the SAME length (length-changing
# representations are out of scope, as in the reference's ompio,
# which rejects datareps it cannot serve).
_datareps: dict = {}


def register_datarep(name: str, read_fn=None, write_fn=None,
                     extent_fn=None, extra_state=None) -> None:
    if name in ("native", "external32", "internal") \
            or name in _datareps:
        raise ValueError(
            f"datarep {name!r} already defined (MPI_ERR_DUP_DATAREP)")
    _datareps[name] = (read_fn, write_fn, extent_fn, extra_state)


def _posix_flags(amode: int) -> int:
    if amode & MODE_RDWR:
        flags = os.O_RDWR
    elif amode & MODE_WRONLY:
        flags = os.O_WRONLY
    else:
        flags = os.O_RDONLY
    if amode & MODE_CREATE:
        flags |= os.O_CREAT
    if amode & MODE_EXCL:
        flags |= os.O_EXCL
    # MODE_APPEND is NOT mapped to O_APPEND: Linux pwrite ignores its
    # offset on O_APPEND fds; MPI's append semantics are "file
    # pointers start at end-of-file", handled in File.__init__
    return flags


class File:
    """One collectively-opened file (MPI_File)."""

    def __init__(self, comm, filename: str, amode: int,
                 info=None) -> None:
        from ompi_tpu import errhandler as _eh
        self.comm = comm
        self.filename = filename
        self.amode = amode
        # accepts an ompi_tpu.info.Info or a plain mapping
        self.info = dict(info.items()) if hasattr(info, "items") \
            else dict(info or {})
        self.errhandler = _eh.ERRORS_RETURN
        self.attrs = {}
        self._datarep = "native"
        self.state = comm.state
        self._lock = threading.Lock()
        # fs: open is collective; every rank opens its own descriptor
        # (ufs model), errors surfaced on all ranks via an agreement
        err = 0
        self.fd = -1
        try:
            self.fd = os.open(filename, _posix_flags(amode), 0o644)
        except OSError:
            err = 1
        errs = np.array([err], dtype=np.int64)
        tot = np.zeros(1, dtype=np.int64)
        from ompi_tpu.op import op as opmod
        comm.Allreduce(errs, tot, opmod.SUM)
        if tot[0]:
            if self.fd >= 0:
                os.close(self.fd)
            raise OSError(
                f"collective open of {filename!r} failed on "
                f"{int(tot[0])} rank(s) (MPI_ERR_IO)")
        self.view = FileView()
        self.pos = 0            # individual fp, etype units
        self._closed = False
        # sharedfp: rank 0 exposes the counter through a window on a
        # dup (internal traffic must not alias user comm traffic).
        # The ROMIO-style info hint "sharedfp" => "false" skips the
        # sub-framework entirely: no dup, no window, and no per-sweep
        # AM polling for the file's whole lifetime — callers that
        # never touch shared file pointers (the checkpoint engine)
        # keep the hot path clean.
        self._sp_comm = None
        self._sp_win = None
        self._sp_mem = np.zeros(1, dtype=np.int64)
        if str(self.info.get("sharedfp", "true")).lower() not in (
                "false", "0", "disable"):
            from ompi_tpu.osc import window as oscmod
            self._sp_comm = comm.dup(name=f"file-{id(self):x}")
            self._sp_win = oscmod.create(self._sp_comm,
                                         self._sp_mem if comm.rank == 0
                                         else np.zeros(0,
                                                       dtype=np.int64))
        if amode & MODE_APPEND:
            # MPI_MODE_APPEND: individual + shared fps start at EOF
            self.pos = self._size_etypes()
            if comm.rank == 0:
                self._sp_mem[0] = self.pos
            comm.Barrier()

    # -- fs ops ----------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self.comm.Barrier()
        if self._sp_win is not None:
            self._sp_win.free()
            self._sp_comm.free()
        os.close(self.fd)
        if self.amode & MODE_DELETE_ON_CLOSE and self.comm.rank == 0:
            try:
                os.unlink(self.filename)
            except OSError:
                pass
        self._closed = True

    def ft_abandon(self) -> None:
        """LOCAL close for fault paths: the job just lost ranks, so
        ``close``'s barrier and the sharedfp window's free handshake
        are not an option.  Drops the fd and abandons the window (its
        wildcard receive must not survive into the recovered epoch —
        see Window.abandon); the dup'd comm is left for GC."""
        if self._closed:
            return
        self._closed = True
        if self._sp_win is not None:
            self._sp_win.abandon()
        try:
            os.close(self.fd)
        except OSError:
            pass

    def get_size(self) -> int:
        return os.fstat(self.fd).st_size

    def get_amode(self) -> int:
        return self.amode

    def get_group(self):
        return self.comm.group_obj()

    def get_info(self):
        from ompi_tpu.info import Info
        out = Info()
        for k, v in self.info.items():
            out.set(k, v)
        return out

    def set_info(self, info) -> None:
        items = info.items() if hasattr(info, "items") else \
            dict(info or {}).items()
        for k, v in items:
            self.info[k] = v

    def get_byte_offset(self, offset: int) -> int:
        """MPI_File_get_byte_offset: view-relative etype offset ->
        absolute byte offset."""
        segs = self.view.map_bytes(offset, max(1, self.view.etype.size))
        return segs[0][0] if segs else self.view.disp

    def get_type_extent(self, datatype) -> int:
        return datatype.extent

    def get_atomicity(self) -> bool:
        return False  # per-op posix pread/pwrite; no cross-rank atomic mode

    def set_atomicity(self, flag: bool) -> None:
        if flag:
            raise ValueError(
                "atomic mode is not supported (MPI_ERR_UNSUPPORTED_"
                "OPERATION)")

    def set_size(self, size: int) -> None:
        os.ftruncate(self.fd, size)

    def preallocate(self, size: int) -> None:
        if self.get_size() < size:
            os.ftruncate(self.fd, size)

    def sync(self) -> None:
        os.fsync(self.fd)

    # -- views -----------------------------------------------------------
    def set_view(self, disp: int = 0, etype=None, filetype=None,
                 datarep: str = "native") -> None:
        if datarep not in ("native", "external32") \
                and datarep not in _datareps:
            raise ValueError(f"unsupported datarep {datarep!r}")
        self.view = FileView(disp, etype, filetype)
        self.pos = 0
        self._datarep = datarep

    def get_view(self):
        return (self.view.disp, self.view.etype, self.view.filetype)

    # -- individual fp ---------------------------------------------------
    def seek(self, offset: int, whence: int = SEEK_SET) -> None:
        if whence == SEEK_SET:
            new = offset
        elif whence == SEEK_CUR:
            new = self.pos + offset
        else:
            new = self._size_etypes() + offset
        if new < 0:  # validate before mutating: pos stays usable
            raise ValueError("seek before file start (MPI_ERR_ARG)")
        self.pos = new

    def get_position(self) -> int:
        return self.pos

    def _size_etypes(self) -> int:
        return self.get_size() // max(1, self.view.etype.size)

    # -- fbtl: segment IO ------------------------------------------------
    def _pread_segs(self, segs: List[Tuple[int, int]]) -> bytes:
        data, _ = self._pread_segs_counted(segs)
        return data

    def _pread_segs_counted(self, segs: List[Tuple[int, int]]
                            ) -> Tuple[bytes, int]:
        """(zero-padded data, actually-read byte count) — the count is
        what MPI_Get_count must report so EOF is detectable."""
        out = bytearray()
        actual = 0
        for off, ln in segs:
            chunk = os.pread(self.fd, ln, off)
            actual += len(chunk)
            if len(chunk) < ln:           # short read past EOF: zeros
                chunk = chunk + b"\0" * (ln - len(chunk))
            out += chunk
        return bytes(out), actual

    def _pwrite_segs(self, segs: List[Tuple[int, int]],
                     data: memoryview) -> int:
        o = 0
        for off, ln in segs:
            os.pwrite(self.fd, data[o:o + ln], off)
            o += ln
        return o

    # -- individual read/write -------------------------------------------
    def _spec(self, spec):
        from ompi_tpu.comm.communicator import Communicator
        return Communicator._spec(spec)

    def read_at(self, offset: int, spec) -> Status:
        buf, count, dt = self._spec(spec)
        tb = typed(buf, count, dt, writable=True)
        segs = self.view.map_bytes(offset, tb.arr.nbytes)
        data, actual = self._pread_segs_counted(segs)
        rep = _datareps.get(self._datarep)
        if rep is not None and rep[0] is not None:
            before = len(data)
            data = rep[0](bytes(data), dt, count, rep[3])
            if len(data) != before:
                raise ValueError(
                    f"datarep {self._datarep!r} read conversion "
                    "changed the byte length (unsupported)")
        tb.arr.view(np.uint8)[:len(data)] = np.frombuffer(
            data, dtype=np.uint8)
        tb.flush()
        st = Status()
        st.count = actual
        return st

    def write_at(self, offset: int, spec) -> Status:
        buf, count, dt = self._spec(spec)
        tb = typed(buf, count, dt)
        raw = tb.arr.view(np.uint8).data
        rep = _datareps.get(self._datarep)
        if rep is not None and rep[1] is not None:
            conv = rep[1](bytes(raw), dt, count, rep[3])
            if len(conv) != len(raw):
                raise ValueError(
                    f"datarep {self._datarep!r} write conversion "
                    "changed the byte length (unsupported)")
            raw = memoryview(conv)
        segs = self.view.map_bytes(offset, tb.arr.nbytes)
        n = self._pwrite_segs(segs, raw)
        st = Status()
        st.count = n
        return st

    def read(self, spec) -> Status:
        st = self.read_at(self.pos, spec)
        self.pos += st.count // max(1, self.view.etype.size)
        return st

    def write(self, spec) -> Status:
        st = self.write_at(self.pos, spec)
        self.pos += st.count // max(1, self.view.etype.size)
        return st

    # nonblocking: the posix fbtl completes synchronously (the
    # reference's fbtl/posix without aio does the same under the
    # request veneer)
    def iread(self, spec):
        st = self.read(spec)
        return _done_req(self.comm, st)

    def iwrite(self, spec):
        st = self.write(spec)
        return _done_req(self.comm, st)

    def iread_at(self, offset: int, spec):
        return _done_req(self.comm, self.read_at(offset, spec))

    def iwrite_at(self, offset: int, spec):
        return _done_req(self.comm, self.write_at(offset, spec))

    # -- shared fp --------------------------------------------------------
    def _sp_required(self) -> None:
        if self._sp_win is None:
            raise RuntimeError(
                "shared file pointers were disabled by the "
                "'sharedfp' info hint at open (MPI_ERR_UNSUPPORTED_"
                "OPERATION)")

    def _shared_fetch_add(self, delta: int) -> int:
        from ompi_tpu.op import op as opmod
        from ompi_tpu.osc.window import LOCK_SHARED
        self._sp_required()
        result = np.zeros(1, dtype=np.int64)
        self._sp_win.lock(0, LOCK_SHARED)
        self._sp_win.fetch_and_op(delta, result, 0, 0, opmod.SUM)
        self._sp_win.unlock(0)
        return int(result[0])

    def seek_shared(self, offset: int, whence: int = SEEK_SET) -> None:
        """Collective; all ranks must give the same offset."""
        from ompi_tpu.op import op as opmod
        from ompi_tpu.osc.window import LOCK_EXCLUSIVE
        self._sp_required()
        self.comm.Barrier()
        if self.comm.rank == 0:
            if whence == SEEK_CUR:
                offset += int(self._sp_mem[0])
            elif whence == SEEK_END:
                offset += self._size_etypes()
            result = np.zeros(1, dtype=np.int64)
            self._sp_win.lock(0, LOCK_EXCLUSIVE)
            self._sp_win.fetch_and_op(offset, result, 0, 0, opmod.REPLACE)
            self._sp_win.unlock(0)
        self.comm.Barrier()

    def get_position_shared(self) -> int:
        return self._shared_fetch_add(0)

    def read_shared(self, spec) -> Status:
        buf, count, dt = self._spec(spec)
        nbytes = count * dt.size
        pos = self._shared_fetch_add(
            nbytes // max(1, self.view.etype.size))
        return self.read_at(pos, spec)

    def write_shared(self, spec) -> Status:
        buf, count, dt = self._spec(spec)
        nbytes = count * dt.size
        pos = self._shared_fetch_add(
            nbytes // max(1, self.view.etype.size))
        return self.write_at(pos, spec)

    # ordered = shared-fp collective: ranks get rank-ordered slots via
    # exscan of their sizes from the current shared position
    # (ref: sharedfp read_ordered semantics)
    def _ordered_pos(self, nbytes: int) -> int:
        from ompi_tpu.op import op as opmod
        self._sp_required()  # symmetric raise BEFORE any collective
        mine = np.array([nbytes // max(1, self.view.etype.size)],
                        dtype=np.int64)
        pref = np.zeros(1, dtype=np.int64)
        self.comm.Exscan(mine, pref, opmod.SUM)
        total = np.zeros(1, dtype=np.int64)
        self.comm.Allreduce(mine, total, opmod.SUM)
        if self.comm.rank == 0:
            pref[0] = 0
        base = 0
        if self.comm.rank == 0:
            base = self._shared_fetch_add(int(total[0]))
        b = np.array([base], dtype=np.int64)
        self.comm.Bcast(b, root=0)
        return int(b[0] + pref[0])

    def read_ordered(self, spec) -> Status:
        buf, count, dt = self._spec(spec)
        pos = self._ordered_pos(count * dt.size)
        return self.read_at(pos, spec)

    def write_ordered(self, spec) -> Status:
        buf, count, dt = self._spec(spec)
        pos = self._ordered_pos(count * dt.size)
        return self.write_at(pos, spec)

    # -- collectives (fcoll two-phase) -----------------------------------
    def read_at_all(self, offset: int, spec) -> Status:
        from ompi_tpu.io import fcoll
        return fcoll.read_all(self, offset, spec)

    def write_at_all(self, offset: int, spec) -> Status:
        from ompi_tpu.io import fcoll
        return fcoll.write_all(self, offset, spec)

    def read_all(self, spec) -> Status:
        st = self.read_at_all(self.pos, spec)
        self.pos += st.count // max(1, self.view.etype.size)
        return st

    def write_all(self, spec) -> Status:
        st = self.write_at_all(self.pos, spec)
        self.pos += st.count // max(1, self.view.etype.size)
        return st

    # -- nonblocking collectives + shared-fp -------------------------
    # (the fcoll exchange is synchronous inside, like romio's
    # deferred-open collectives at this altitude; the request is born
    # complete)
    def iread_all(self, spec):
        return _done_req(self.comm, self.read_all(spec))

    def iwrite_all(self, spec):
        return _done_req(self.comm, self.write_all(spec))

    def iread_at_all(self, offset: int, spec):
        return _done_req(self.comm, self.read_at_all(offset, spec))

    def iwrite_at_all(self, offset: int, spec):
        return _done_req(self.comm, self.write_at_all(offset, spec))

    def iread_shared(self, spec):
        return _done_req(self.comm, self.read_shared(spec))

    def iwrite_shared(self, spec):
        return _done_req(self.comm, self.write_shared(spec))

    # -- split-phase collectives (ref: ompi/mpi/c/file_read_all_begin.c
    # family): begin runs the collective, end returns its status; at
    # most one split collective may be active per file handle (the
    # MPI rule), which we enforce.
    def _begin(self, kind: str, st: Status) -> None:
        if getattr(self, "_split", None) is not None:
            raise RuntimeError(
                "a split collective is already active on this file "
                "(MPI_ERR_OTHER)")
        self._split = (kind, st)

    def _end(self, kind: str) -> Status:
        cur = getattr(self, "_split", None)
        if cur is None or cur[0] != kind:
            raise RuntimeError(
                f"no matching {kind}_begin active (MPI_ERR_OTHER)")
        self._split = None
        return cur[1]

    def read_all_begin(self, spec) -> None:
        self._begin("read_all", self.read_all(spec))

    def read_all_end(self, buf=None) -> Status:
        return self._end("read_all")

    def write_all_begin(self, spec) -> None:
        self._begin("write_all", self.write_all(spec))

    def write_all_end(self, buf=None) -> Status:
        return self._end("write_all")

    def read_at_all_begin(self, offset: int, spec) -> None:
        self._begin("read_at_all", self.read_at_all(offset, spec))

    def read_at_all_end(self, buf=None) -> Status:
        return self._end("read_at_all")

    def write_at_all_begin(self, offset: int, spec) -> None:
        self._begin("write_at_all", self.write_at_all(offset, spec))

    def write_at_all_end(self, buf=None) -> Status:
        return self._end("write_at_all")

    def read_ordered_begin(self, spec) -> None:
        self._begin("read_ordered", self.read_ordered(spec))

    def read_ordered_end(self, buf=None) -> Status:
        return self._end("read_ordered")

    def write_ordered_begin(self, spec) -> None:
        self._begin("write_ordered", self.write_ordered(spec))

    def write_ordered_end(self, buf=None) -> Status:
        return self._end("write_ordered")


def _done_req(comm, st: Status) -> CompletedRequest:
    r = CompletedRequest(comm.state.progress, st.count)
    r.status = st
    return r


def open(comm, filename: str, amode: int = MODE_RDONLY,
         info=None) -> File:  # noqa: A001 (MPI_File_open)
    return File(comm, filename, amode, info)


def delete(filename: str) -> None:
    os.unlink(filename)


from ompi_tpu import errhandler as _eh_mod  # noqa: E402

_eh_mod.attach_api(File)
