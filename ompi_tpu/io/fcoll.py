"""fcoll/two_phase: collective read/write aggregation.

Re-design of ompio's two-phase component (ref: ompi/mca/fcoll/
two_phase/fcoll_two_phase_file_write_all.c:41,58-70 — ROMIO's
exchange-and-write: the aggregate byte range touched by all ranks is
partitioned among aggregator ranks; each compute rank ships the
pieces of its request that fall in an aggregator's partition; the
aggregator does one contiguous read-modify-write per partition
instead of every rank issuing scattered syscalls).

The number of aggregators comes from the ``io_fcoll_num_aggregators``
MCA variable (0 = one per rank, the ufs default for single-host).
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ompi_tpu.coll.buffers import typed
from ompi_tpu.mca.params import registry
from ompi_tpu.pml.request import Status

num_agg_var = registry.register(
    "io", "fcoll", "num_aggregators", 0, int,
    help="Aggregator count for two-phase collective IO "
         "(0 = every rank aggregates)")

T_META = -141
T_DATA = -142
T_BACK = -143


def _plan(file, offset: int, nbytes: int):
    """Per-rank segment list + the global partition among aggregators.
    Collective: every rank learns the aggregate [lo, hi) range."""
    comm = file.comm
    segs = file.view.map_bytes(offset, nbytes)
    # interleaved views (extent < true_ub) can emit out-of-order
    # offsets across tiles, so the hull needs min/max, not ends
    lo = min(o for o, _ in segs) if segs else np.iinfo(np.int64).max
    hi = max(o + ln for o, ln in segs) if segs else 0
    from ompi_tpu.op import op as opmod
    mine = np.array([lo, -hi], dtype=np.int64)
    mn = np.empty(2, dtype=np.int64)
    comm.Allreduce(mine, mn, opmod.MIN)
    glo, ghi = int(mn[0]), int(-mn[1])
    if ghi <= glo:
        return segs, glo, ghi, 0, [], 0
    nagg = registry.lookup("io", "fcoll", "num_aggregators", 0) or comm.size
    span = ghi - glo
    # never create an empty partition: an aggregator that owns no
    # bytes would skip its receive loop and strand the metadata sends
    nagg = max(1, min(nagg, comm.size, span))
    base, rem = divmod(span, nagg)
    bounds = [glo + a * base + min(a, rem) for a in range(nagg + 1)]
    parts = [(bounds[a], bounds[a + 1]) for a in range(nagg)]
    return segs, glo, ghi, nagg, parts, bounds


def _chunk_fn(bounds):
    from bisect import bisect_right

    def chunk_of(pos: int) -> int:
        return min(bisect_right(bounds, pos) - 1, len(bounds) - 2)
    return chunk_of


def _split_for_aggregators(segs, parts, nagg: int, chunk_of):
    """Slice this rank's (off, len) segments by aggregator partition;
    returns per-aggregator (offsets[], lens[], data-ranges[])."""
    per: List[List[Tuple[int, int, int]]] = [[] for _ in range(nagg)]
    dpos = 0
    for off, ln in segs:
        left = ln
        cur = off
        while left > 0:
            a = chunk_of(cur)
            pend = parts[a][1]
            take = min(left, pend - cur)
            per[a].append((cur, take, dpos))
            dpos += take
            cur += take
            left -= take
    return per


def _pack_meta(items) -> np.ndarray:
    """[n, off0, ln0, off1, ln1, ...] int64 wire vector."""
    meta = np.zeros(1 + 2 * len(items), dtype=np.int64)
    meta[0] = len(items)
    for i, (off, ln, _dpos) in enumerate(items):
        meta[1 + 2 * i] = off
        meta[2 + 2 * i] = ln
    return meta


def _iter_meta(meta: np.ndarray):
    """Yield (off, ln) pairs from a packed meta vector."""
    for i in range(int(meta[0])):
        yield int(meta[1 + 2 * i]), int(meta[2 + 2 * i])


def _recv_meta(pml, src: int, comm) -> np.ndarray:
    """Meta vectors are variable length: probe for the size first."""
    from ompi_tpu.datatype import engine as dtmod
    st = pml.probe(src, T_META, comm)
    n = st.count // 8
    meta = np.empty(n, dtype=np.int64)
    pml.recv(meta, n, dtmod.INT64_T, src, T_META, comm)
    return meta


def _merge_intervals(ivs):
    ivs.sort()
    out = []
    for lo, hi in ivs:
        if out and lo <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], hi))
        else:
            out.append((lo, hi))
    return out


def _interval_lookup(merged):
    """merged disjoint (lo, hi) intervals → fn(off) = (index, off-lo).
    Callers guarantee every queried (off, len) lies wholly inside one
    interval (pieces/requests were merged from the same inputs)."""
    from bisect import bisect_right
    starts = [lo for lo, _ in merged]

    def locate(off: int):
        i = bisect_right(starts, off) - 1
        return i, off - starts[i]
    return locate


def write_all(file, offset: int, spec) -> Status:
    comm = file.comm
    buf, count, dt = file._spec(spec)
    tb = typed(buf, count, dt)
    raw = np.ascontiguousarray(tb.arr).view(np.uint8)
    segs, glo, ghi, nagg, parts, bounds = _plan(file, offset, raw.nbytes)
    if nagg == 0:  # nobody writes anything
        return Status()
    chunk_of = _chunk_fn(bounds)

    per = _split_for_aggregators(segs, parts, nagg, chunk_of)
    pml = comm.state.pml
    from ompi_tpu.datatype import engine as dtmod

    # ship metadata + data to each aggregator (including self, via pml)
    reqs = []
    for a in range(nagg):
        items = per[a]
        payload = bytearray()
        for off, ln, dpos in items:
            payload += raw[dpos:dpos + ln].tobytes()
        meta = _pack_meta(items)
        reqs.append(pml.isend(meta, meta.size, dtmod.INT64_T, a, T_META,
                              comm))
        data = np.frombuffer(bytes(payload), dtype=np.uint8)
        reqs.append(pml.isend(data, data.size, dtmod.BYTE, a, T_DATA,
                              comm))

    # aggregator role: collect every rank's pieces, then allocate one
    # buffer per *covered* interval (never the whole partition span —
    # sparse writes at far-apart offsets must not allocate span/nagg
    # bytes) and write only those intervals.  Holes are never touched,
    # so no read-modify-write (and no pread on WRONLY files).
    if comm.rank < nagg:
        pieces: List[Tuple[int, np.ndarray]] = []  # (abs_off, bytes)
        covered = []
        for src in range(comm.size):
            meta = _recv_meta(pml, src, comm)
            total = sum(ln for _, ln in _iter_meta(meta))
            data = np.empty(total, dtype=np.uint8)
            pml.recv(data, total, dtmod.BYTE, src, T_DATA, comm)
            o = 0
            for off, ln in _iter_meta(meta):
                pieces.append((off, data[o:o + ln]))
                covered.append((off, off + ln))
                o += ln
        merged = _merge_intervals(covered)
        if merged:
            locate = _interval_lookup(merged)
            regions = [bytearray(hi - lo) for lo, hi in merged]
            for off, piece in pieces:  # later sources win, as received
                i, o = locate(off)
                regions[i][o:o + len(piece)] = piece.data
            for (lo, hi), region in zip(merged, regions):
                file._pwrite_segs([(lo, hi - lo)], memoryview(region))
    for r in reqs:
        r.wait()
    comm.Barrier()  # write_all is collective: data visible on return
    st = Status()
    st.count = raw.nbytes
    return st


def read_all(file, offset: int, spec) -> Status:
    comm = file.comm
    buf, count, dt = file._spec(spec)
    tb = typed(buf, count, dt, writable=True)
    nbytes = tb.arr.nbytes
    segs, glo, ghi, nagg, parts, bounds = _plan(file, offset, nbytes)
    if nagg == 0:
        return Status()
    chunk_of = _chunk_fn(bounds)

    per = _split_for_aggregators(segs, parts, nagg, chunk_of)
    pml = comm.state.pml
    from ompi_tpu.datatype import engine as dtmod

    # request phase: send each aggregator the wanted (off, len) list
    reqs = []
    for a in range(nagg):
        meta = _pack_meta(per[a])
        reqs.append(pml.isend(meta, meta.size, dtmod.INT64_T, a, T_META,
                              comm))

    # serve phase: aggregator collects every request list first, preads
    # only the union of requested intervals (never the whole partition
    # — sparse reads must not allocate or read span/nagg bytes), and
    # answers each rank from memory.  Per-interval actual read counts
    # from _pread_segs_counted give true EOF byte counts, which travel
    # back with the data so Status.count matches the individual path.
    if comm.rank < nagg:
        metas = [_recv_meta(pml, src, comm) for src in range(comm.size)]
        wanted = _merge_intervals(
            [(off, off + ln) for m in metas for off, ln in _iter_meta(m)])
        locate = _interval_lookup(wanted)
        regions: List[bytes] = []
        avail: List[int] = []          # readable end of each interval
        for lo, hi in wanted:
            data_i, actual = file._pread_segs_counted([(lo, hi - lo)])
            regions.append(data_i)
            avail.append(lo + actual)
        for src, meta in enumerate(metas):
            # response = 8-byte true-count header + the padded data,
            # one message (the count must not double T_BACK traffic)
            resp = bytearray(8)
            got = 0
            for off, ln in _iter_meta(meta):
                i, o = locate(off)
                resp += regions[i][o:o + ln]
                got += max(0, min(off + ln, avail[i]) - off)
            resp[:8] = np.int64(got).tobytes()
            arr = np.frombuffer(bytes(resp), dtype=np.uint8)
            reqs.append(pml.isend(arr, arr.size, dtmod.BYTE, src, T_BACK,
                                  comm))

    # gather phase: collect the slices back, in aggregator order
    out = np.empty(nbytes, dtype=np.uint8)
    true_count = 0
    for a in range(nagg):
        items = per[a]
        total = sum(ln for _, ln, _ in items)
        data = np.empty(total + 8, dtype=np.uint8)
        pml.recv(data, total + 8, dtmod.BYTE, a, T_BACK, comm)
        true_count += int(data[:8].view(np.int64)[0])
        data = data[8:]
        o = 0
        for off, ln, dpos in items:
            out[dpos:dpos + ln] = data[o:o + ln]
            o += ln
    tb.arr.view(np.uint8)[:] = out
    tb.flush()
    for r in reqs:
        r.wait()
    comm.Barrier()
    st = Status()
    st.count = true_count
    return st
