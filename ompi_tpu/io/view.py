"""File views: mapping (disp, etype, filetype) to file byte segments.

Re-design of ompio's view machinery (ref: ompi/mca/io/ompio/
io_ompio_file_set_view.c + the segment decoding in
io_ompio.c:ompi_io_ompio_decode_datatype — the filetype is flattened
once into an (offset, length) iovec per tile; tiles repeat every
``extent`` bytes in the file; only bytes inside segments are visible
through the view).

The flattening reuses the datatype engine's Run descriptors
(ompi_tpu.datatype.engine) instead of a separate decoder.
"""

from __future__ import annotations

from bisect import bisect_right
from typing import List, Tuple

from ompi_tpu.datatype import engine as dtmod


def _flatten(datatype) -> List[Tuple[int, int]]:
    """Merged, sorted (offset, nbytes) segments of one filetype tile."""
    segs: List[Tuple[int, int]] = []
    for r in datatype.runs:
        for b in range(r.nblocks):
            segs.append((r.disp + b * r.stride, r.block_bytes))
    segs.sort()
    merged: List[Tuple[int, int]] = []
    for off, ln in segs:
        if merged and merged[-1][0] + merged[-1][1] == off:
            merged[-1] = (merged[-1][0], merged[-1][1] + ln)
        else:
            merged.append((off, ln))
    return merged


class FileView:
    """disp + repeating filetype tiles; positions are in etype units
    (the MPI file-pointer unit)."""

    def __init__(self, disp: int = 0, etype=None, filetype=None) -> None:
        self.disp = disp
        self.etype = etype if etype is not None else dtmod.BYTE
        self.filetype = filetype if filetype is not None else self.etype
        if self.filetype.size % self.etype.size:
            raise ValueError("filetype size must be a multiple of etype "
                             "size (MPI_ERR_ARG)")
        self.segs = _flatten(self.filetype)
        self.tile_bytes = sum(ln for _, ln in self.segs)  # data per tile
        # the filetype's extent IS the tile stride — a resized type may
        # legally have extent < true_ub as long as consecutive tiles'
        # data segments interleave without overlapping
        self.tile_extent = self.filetype.extent
        if self.tile_bytes != self.filetype.size:
            raise ValueError("overlapping filetype segments")
        self._check_tile_overlap()
        # prefix sums of segment data bytes for O(log n) seek
        self._prefix = [0]
        for _, ln in self.segs:
            self._prefix.append(self._prefix[-1] + ln)

    def _check_tile_overlap(self) -> None:
        """Tiles repeat every ``extent`` bytes, so byte b of tile k
        lands at b + k*extent: two tiles collide iff two data bytes of
        one tile are congruent mod extent.  Fold every segment into
        [0, extent) and require the folded intervals to be disjoint —
        this accepts legal interleavings (e.g. data [0,4)+[12,16) with
        extent 8) and rejects genuine overlaps (MPI_ERR_TYPE)."""
        if not self.segs:
            return
        e = self.tile_extent
        if e <= 0 or self.tile_bytes > e:
            raise ValueError(
                f"filetype tiles overlap: {self.tile_bytes} data bytes "
                f"per tile exceed the {e}-byte tile extent (MPI_ERR_TYPE)")
        folded: List[Tuple[int, int]] = []
        for off, ln in self.segs:
            off %= e
            while ln > 0:
                take = min(ln, e - off)
                folded.append((off, take))
                ln -= take
                off = 0
        folded.sort()
        for (o1, l1), (o2, _) in zip(folded, folded[1:]):
            if o1 + l1 > o2:
                raise ValueError(
                    "filetype tiles overlap: data bytes at offsets "
                    f"{o2} and {o1}+{l1} collide mod the {e}-byte "
                    "extent (MPI_ERR_TYPE)")

    def is_contiguous(self) -> bool:
        return (len(self.segs) == 1
                and self.tile_extent == self.tile_bytes)

    def map_bytes(self, pos_etypes: int, nbytes: int
                  ) -> List[Tuple[int, int]]:
        """Absolute file (offset, nbytes) segments for `nbytes` of data
        starting at file pointer `pos_etypes` (etype units)."""
        if nbytes == 0 or self.tile_bytes == 0:
            return []
        start = pos_etypes * self.etype.size  # data-space byte position
        if self.is_contiguous():
            return [(self.disp + self.segs[0][0]
                     + (start // self.tile_bytes) * self.tile_extent
                     + start % self.tile_bytes, nbytes)] \
                if self.tile_bytes else []
        out: List[Tuple[int, int]] = []
        tile, within = divmod(start, self.tile_bytes)
        # locate the segment containing `within`
        i = bisect_right(self._prefix, within) - 1
        remaining = nbytes
        while remaining > 0:
            if i >= len(self.segs):
                tile += 1
                i = 0
                within = 0
            seg_off, seg_len = self.segs[i]
            skip = within - self._prefix[i]
            take = min(seg_len - skip, remaining)
            abs_off = self.disp + tile * self.tile_extent + seg_off + skip
            if out and out[-1][0] + out[-1][1] == abs_off:
                out[-1] = (out[-1][0], out[-1][1] + take)
            else:
                out.append((abs_off, take))
            remaining -= take
            i += 1
            within = self._prefix[i] if i < len(self.segs) else 0
        return out
