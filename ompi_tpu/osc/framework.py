"""osc framework: per-window component selection (``osc_select``).

Mirrors the coll framework's device reroute at window granularity
(ref: ompi/mca/osc/base/osc_base_init.c ompi_osc_base_select — every
component is queried per window and the highest priority wins):

    device   priority 40   the window COMMITS TO THE MESH — either
                           Win_create over a device-committed buffer
                           or Win_allocate minting one — and the
                           comm's ranks own distinct devices
    pt2pt    priority 10   always usable (host AM over the pml)

``--mca osc <list>`` (``registry.set("osc", "pt2pt")``) restricts the
candidates exactly like ``--mca coll``.  The verdict is cached per
comm under ``comm.__dict__["_osc_pick"]`` and registered in
``ulfm.SELECTION_CACHE_KEYS`` so shrink/respawn epochs re-decide.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ompi_tpu.mca.base import Component, frameworks
from ompi_tpu.mca.params import registry

osc_framework = frameworks.create("ompi", "osc")


def _is_device_committed(memory) -> bool:
    """True when the window memory is already a device array (the
    Win_create-over-hbm case)."""
    if memory is None or isinstance(memory, np.ndarray):
        return False
    from ompi_tpu.coll.device import _is_jax_array
    return _is_jax_array(memory)


class Pt2ptComponent(Component):
    name = "pt2pt"
    priority = 10

    def register_params(self, framework) -> None:
        self._pri_var = registry.register(
            "osc", "pt2pt", "priority", 10, int,
            help="Selection priority of the host AM osc component")

    def query(self, comm, memory, mint):  # noqa: ARG002
        return (self._pri_var.value, self)

    def build(self, comm, memory, disp_unit, name, info, mint):
        from ompi_tpu.osc import window as _w
        if mint:
            return _w.allocate(comm, memory, disp_unit or 1, name)
        if memory is not None and not isinstance(memory, np.ndarray):
            # device buffer routed here by --mca osc pt2pt: snapshot
            # to host so the AM window still works
            memory = np.ascontiguousarray(np.asarray(memory))
        if disp_unit is None:
            disp_unit = memory.dtype.itemsize \
                if memory is not None and memory.size else 1
        return _w.Window(comm, memory, disp_unit, name, info=info)


class DeviceComponent(Component):
    name = "device"
    priority = 40

    def register_params(self, framework) -> None:
        self._pri_var = registry.register(
            "osc", "device", "priority", 40, int,
            help="Selection priority of the device-memory osc "
                 "component (wins when the window commits to the "
                 "comm's mesh)")

    def query(self, comm, memory, mint):
        if comm.mesh() is None:
            return None
        if not mint and not _is_device_committed(memory):
            return None
        return (self._pri_var.value, self)

    def build(self, comm, memory, disp_unit, name, info, mint):
        from ompi_tpu.osc import device as _d
        if mint:
            return _d.allocate(comm, memory, disp_unit or 1, name)
        if disp_unit is None:
            itemsize = getattr(
                getattr(memory, "dtype", None), "itemsize", 1)
            disp_unit = itemsize if getattr(memory, "size", 0) else 1
        return _d.DeviceWindow(comm, memory, disp_unit, name, info=info)


osc_framework.add_component(Pt2ptComponent())
osc_framework.add_component(DeviceComponent())


def osc_select(comm, memory=None, mint: bool = False) -> Component:
    """The per-window component decision, cached per (mint, committed)
    shape on the comm (ulfm purges ``_osc_pick`` across epochs)."""
    pick = comm.__dict__.get("_osc_pick")
    if pick is None:
        pick = {}
        comm.__dict__["_osc_pick"] = pick
    key = (bool(mint), _is_device_committed(memory))
    comp = pick.get(key)
    if comp is None:
        comp, _payload = osc_framework.select_one(comm, memory, mint)
        pick[key] = comp
    return comp


def win_create(comm, memory, disp_unit=None, name: str = "",
               info=None):
    comp = osc_select(comm, memory, mint=False)
    return comp.build(comm, memory, disp_unit, name, info, mint=False)


def win_allocate(comm, nbytes: int, disp_unit: int = 1,
                 name: str = ""):
    comp = osc_select(comm, None, mint=True)
    return comp.build(comm, nbytes, disp_unit, name, None, mint=True)
