"""One-sided RMA (MPI-3 windows).

Two components behind a real osc framework decision (framework.py):
``pt2pt`` — host AM over the pml (window.py) — and ``device`` —
windows backed by device shards on the comm's mesh (device.py).
``create``/``allocate`` route through ``osc_select``; the host-only
entry points (dynamic/shared windows) stay pt2pt."""

from .window import (LOCK_EXCLUSIVE, LOCK_SHARED, Window,
                     allocate_shared, create_dynamic, shared_query)
from .framework import osc_framework, osc_select


def create(comm, memory, disp_unit=None, name: str = "", info=None):
    """MPI_Win_create through component selection: a device-committed
    buffer on a mesh-capable comm gets the device window."""
    return _fw.win_create(comm, memory, disp_unit, name, info)


def allocate(comm, nbytes: int, disp_unit: int = 1, name: str = ""):
    """MPI_Win_allocate through component selection: mints a
    mesh-committed shard when the comm has a device mesh."""
    return _fw.win_allocate(comm, nbytes, disp_unit, name)


from ompi_tpu.osc import framework as _fw  # noqa: E402

__all__ = ["Window", "create", "allocate", "create_dynamic",
           "allocate_shared", "shared_query", "osc_framework",
           "osc_select", "LOCK_SHARED", "LOCK_EXCLUSIVE"]
