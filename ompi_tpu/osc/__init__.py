"""One-sided RMA (MPI-3 windows) — see window.py."""

from .window import (LOCK_EXCLUSIVE, LOCK_SHARED, Window, allocate,
                     create)

__all__ = ["Window", "create", "allocate", "LOCK_SHARED",
           "LOCK_EXCLUSIVE"]
