"""One-sided communication (MPI-3 RMA windows).

Re-design of ompi/mca/osc/pt2pt (ref: osc_pt2pt active-message
protocol; osc/rdma lock algorithms osc_rdma_lock.h:18-49; API surface
ompi/mpi/c/put.c:81, win.c).  The reference implements RMA either as
true btl put/get (osc/rdma) or as an active-message protocol over the
pml (osc/pt2pt); here the pt2pt design is the universal path: every
RMA op is an eager control message (+payload) on a *dup'ed*
communicator, applied by the target inside its progress loop.

Completion leans on the pml's per-(src,dst) FIFO ordering:
- UNLOCK/FLUSH acks are sent by the target after processing, so the
  ack proves every earlier op from that origin was applied;
- PSCW COMPLETE messages arrive after all the origin's ops, so
  Win_wait just counts COMPLETEs;
- fence exchanges per-target op counts (alltoall) and waits until the
  cumulative applied counter reaches the cumulative expectation (the
  osc/pt2pt fence algorithm).

Atomicity of accumulate/fetch-ops comes free: the AM handler applies
messages serially in the target's progress loop.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

import numpy as np

from ompi_tpu import obs as _obs
from ompi_tpu.datatype import engine as dtmod
from ompi_tpu.mca.params import registry
from ompi_tpu.op import op as opmod

# message types
(PUT, GET, ACC, GET_ACC, CAS, LOCK, UNLOCK, FLUSH, PSCW_COMPLETE,
 PSCW_POST) = range(1, 11)

# reserved tags on the window's dup'ed comm
T_CTRL = -451
T_DATA = -452
_REPLY_BASE = -500
_REPLY_SPAN = 1000

LOCK_SHARED = 1
LOCK_EXCLUSIVE = 2

HDR_N = 10  # int64 header words: [mtype, origin, disp, count, dtnum,
#             opcode, reply_tag, payload_bytes, extra, reserved]

# wire op table (index = wire opcode)
_WIRE_OPS: List[opmod.Op] = [
    opmod.SUM, opmod.PROD, opmod.MAX, opmod.MIN, opmod.BAND, opmod.BOR,
    opmod.BXOR, opmod.LAND, opmod.LOR, opmod.LXOR, opmod.MAXLOC,
    opmod.MINLOC, opmod.REPLACE, opmod.NO_OP,
]
_OP_CODE = {id(op): i for i, op in enumerate(_WIRE_OPS)}

# numpy dtype <-> wire code (dtype.num is numpy-internal; use our own)
_WIRE_DTYPES = [np.dtype(t) for t in (
    np.uint8, np.int8, np.int16, np.uint16, np.int32, np.uint32,
    np.int64, np.uint64, np.float32, np.float64, np.complex64,
    np.complex128, np.bool_)]
_DT_CODE = {dt: i for i, dt in enumerate(_WIRE_DTYPES)}


# osc pvar surface, shared by BOTH components (pt2pt and device):
# band-scoped so dvm sessions get exact per-session attribution
pv_puts = _obs.scoped_pvar(
    "osc", "", "puts", help="RMA put/rput operations issued")
pv_gets = _obs.scoped_pvar(
    "osc", "", "gets", help="RMA get/rget operations issued")
pv_accs = _obs.scoped_pvar(
    "osc", "", "accs",
    help="RMA accumulate/get_accumulate/fetch_and_op operations issued")
pv_cas = _obs.scoped_pvar(
    "osc", "", "cas", help="RMA compare_and_swap operations issued")
pv_bytes_put = _obs.scoped_pvar(
    "osc", "", "bytes_put", help="Origin bytes moved by put/rput")
pv_bytes_got = _obs.scoped_pvar(
    "osc", "", "bytes_got", help="Origin bytes moved by get/rget")
pv_lock_wait = registry.register_pvar(
    "osc", "", "lock_wait_us", var_class="highwatermark",
    help="Worst time (us) an origin waited for a passive-target lock "
         "grant — contention and rma_delay injection both surface "
         "here")


def _op_code(op: opmod.Op) -> int:
    code = _OP_CODE.get(id(op))
    if code is None:
        raise ValueError(f"op {op} not supported on RMA windows "
                         "(user-defined ops are not addressable on the wire)")
    return code


class _Pending:
    """An incoming message whose payload recv is in flight."""

    __slots__ = ("hdr", "src", "buf", "req")

    def __init__(self, hdr, src, buf, req) -> None:
        self.hdr = hdr
        self.src = src
        self.buf = buf
        self.req = req


class Window:
    """MPI_Win over a local memory region (ref: ompi/win/win.c)."""

    def __init__(self, comm, memory: Optional[np.ndarray],
                 disp_unit: int = 1, name: str = "",
                 info=None) -> None:
        from ompi_tpu import errhandler as _eh
        base = comm.dup(name or f"win-{id(self):x}")
        self.comm = base
        self.rank = base.rank
        self.size = base.size
        self.errhandler = _eh.ERRORS_RETURN
        self.attrs = {}
        self.info = info
        self.state = comm.state  # errhandler dispatch needs the rte
        self._dynamic = False
        self._attached: List[Tuple[int, np.ndarray]] = []
        if memory is None:
            memory = np.zeros(0, dtype=np.uint8)
        if not (isinstance(memory, np.ndarray) and memory.flags.c_contiguous):
            raise ValueError("window memory must be a contiguous ndarray")
        self._mem = memory.reshape(-1).view(np.uint8)
        self.memory = memory
        self.disp_unit = disp_unit
        # AM engine state
        self._hdr_buf = np.empty(HDR_N, dtype=np.int64)
        self._hdr_req = None
        self._pending: Optional[_Pending] = None
        self._applied_total = 0
        self._expected_total = 0
        self._pscw_complete: Dict[int, int] = {}
        self._pscw_posted: Dict[int, int] = {}
        # lock state (target side)
        self._lock_mode = 0
        self._lock_holders: set = set()
        self._lock_queue: Deque[Tuple[int, int, int]] = deque()
        # origin-side epoch tracking
        self._ops_sent = np.zeros(self.size, dtype=np.int64)
        self._out_reqs: List[Any] = []
        self._reply_ctr = 0
        self._post_group: Optional[List[int]] = None
        self._start_group: Optional[List[int]] = None
        self._freed = False
        self._progress = base.state.progress
        try:
            from ompi_tpu import ft_inject as _fi
            self._inj = _fi.rma_injector(base.rank)
        except Exception:  # noqa: BLE001 — fault plan optional
            self._inj = None
        self._post_hdr_recv()
        self._progress.register(self._am_progress)
        base.Barrier()  # window exists everywhere before any op

    # -- wire helpers ----------------------------------------------------

    def _pml(self):
        return self.comm.state.pml

    def _post_hdr_recv(self) -> None:
        self._hdr_req = self._pml().irecv(
            self._hdr_buf, HDR_N, dtmod.INT64_T, -1, T_CTRL, self.comm)

    def _send_hdr(self, target: int, mtype: int, disp: int = 0,
                  count: int = 0, dtnum: int = 0, opcode: int = 0,
                  reply_tag: int = 0, payload: Optional[np.ndarray] = None,
                  extra: int = 0) -> None:
        nbytes = 0 if payload is None else payload.nbytes
        hdr = np.array([mtype, self.rank, disp, count, dtnum, opcode,
                        reply_tag, nbytes, extra, 0], dtype=np.int64)
        self._out_reqs.append(self._pml().isend(
            hdr, HDR_N, dtmod.INT64_T, target, T_CTRL, self.comm))
        if payload is not None and nbytes:
            pb = np.ascontiguousarray(payload).view(np.uint8).reshape(-1)
            self._out_reqs.append(self._pml().isend(
                pb, pb.size, dtmod.BYTE, target, T_DATA, self.comm))

    def _new_reply_tag(self) -> int:
        self._reply_ctr += 1
        return _REPLY_BASE - (self._reply_ctr % _REPLY_SPAN)

    def _recv_reply(self, nbytes: int, src: int, tag: int):
        buf = np.empty(max(nbytes, 0), dtype=np.uint8)
        req = self._pml().irecv(buf, buf.size, dtmod.BYTE, src, tag,
                                self.comm)
        return buf, req

    # -- target-side apply ----------------------------------------------

    def _am_progress(self) -> int:
        events = 0
        while True:
            if self._pending is not None:
                if not self._pending.req.complete:
                    return events
                p, self._pending = self._pending, None
                self._apply(p.hdr, p.src, p.buf)
                self._post_hdr_recv()
                events += 1
                continue
            if self._hdr_req is None or not self._hdr_req.complete:
                return events
            if self._hdr_req.status.error:
                # a peer died while the wildcard header receive was
                # parked (ulfm_sweep error-completes it, buffer
                # untouched): the window cannot make progress until
                # recovery frees or abandons it — park instead of
                # parsing a zeroed header as an RMA message
                self._hdr_req = None
                return events
            hdr = self._hdr_buf.copy()
            src = self._hdr_req.status.source
            self._hdr_req = None
            nbytes = int(hdr[7])
            if nbytes:
                buf, req = self._recv_reply(nbytes, src, T_DATA)
                self._pending = _Pending(hdr, src, buf, req)
                continue
            self._apply(hdr, src, None)
            self._post_hdr_recv()
            events += 1

    def _region(self, disp: int, count: int, dtnum: int) -> np.ndarray:
        dt = _WIRE_DTYPES[dtnum]
        need = count * dt.itemsize
        if self._dynamic:
            # dynamic windows (ref: osc MPI_Win_create_dynamic):
            # disp is the target-side ABSOLUTE address (from
            # MPI_Get_address); resolve against attached regions
            for base, arr in self._attached:
                if base <= disp and disp + need <= base + arr.nbytes:
                    off = disp - base
                    return arr.reshape(-1).view(np.uint8)[
                        off:off + need].view(dt)
            raise ValueError(
                f"RMA at address {disp} hits no attached region "
                "(MPI_ERR_RMA_RANGE)")
        off = disp * self.disp_unit
        view = self._mem[off: off + need]
        return view.view(dt)

    # -- dynamic windows (ref: ompi/mpi/c/win_create_dynamic.c) ---------
    def attach(self, memory: np.ndarray) -> None:
        if not self._dynamic:
            raise ValueError("attach on a non-dynamic window "
                             "(MPI_ERR_RMA_ATTACH)")
        if not (isinstance(memory, np.ndarray)
                and memory.flags.c_contiguous):
            # a non-contiguous view would make _region's flat view a
            # COPY and remote stores would vanish silently
            raise ValueError("attached memory must be a contiguous "
                             "ndarray (MPI_ERR_RMA_ATTACH)")
        self._attached.append((memory.ctypes.data, memory))

    def detach(self, memory: np.ndarray) -> None:
        base = memory.ctypes.data
        self._attached = [(b, a) for b, a in self._attached
                          if b != base]

    def _apply(self, hdr: np.ndarray, src: int,
               payload: Optional[np.ndarray]) -> None:
        if self._inj is not None:
            d = self._inj.maybe_delay()
            if d:
                time.sleep(d)  # ft_inject rma_delay: slow AM handler
        mtype = int(hdr[0])
        origin, disp, count = int(hdr[1]), int(hdr[2]), int(hdr[3])
        dtnum, opcode = int(hdr[4]), int(hdr[5])
        reply_tag = int(hdr[6])
        if payload is None and mtype in (PUT, ACC, GET_ACC, CAS):
            payload = np.empty(0, dtype=np.uint8)  # zero-count op
        if mtype == PUT:
            region = self._region(disp, count, dtnum)
            region[:] = payload.view(_WIRE_DTYPES[dtnum])
            self._applied_total += 1
        elif mtype == GET:
            region = self._region(disp, count, dtnum)
            data = np.ascontiguousarray(region).view(np.uint8).reshape(-1)
            self._pml().isend(data.copy(), data.size, dtmod.BYTE, origin,
                              reply_tag, self.comm)
            self._applied_total += 1
        elif mtype == ACC:
            region = self._region(disp, count, dtnum)
            incoming = payload.view(_WIRE_DTYPES[dtnum])
            op = _WIRE_OPS[opcode]
            region[:] = op.reduce(incoming, region.copy())
            self._applied_total += 1
        elif mtype == GET_ACC:
            region = self._region(disp, count, dtnum)
            old = np.ascontiguousarray(region).copy()
            op = _WIRE_OPS[opcode]
            incoming = payload.view(_WIRE_DTYPES[dtnum])
            region[:] = op.reduce(incoming, region.copy())
            ob = old.view(np.uint8).reshape(-1)
            self._pml().isend(ob, ob.size, dtmod.BYTE, origin, reply_tag,
                              self.comm)
            self._applied_total += 1
        elif mtype == CAS:
            region = self._region(disp, 1, dtnum)
            dt = _WIRE_DTYPES[dtnum]
            cmp_val = payload[: dt.itemsize].view(dt)
            new_val = payload[dt.itemsize:].view(dt)
            old = region.copy()
            if old[0] == cmp_val[0]:
                region[0] = new_val[0]
            ob = old.view(np.uint8).reshape(-1)
            self._pml().isend(ob, ob.size, dtmod.BYTE, origin, reply_tag,
                              self.comm)
            self._applied_total += 1
        elif mtype == LOCK:
            self._lock_request(origin, opcode, reply_tag)
        elif mtype == UNLOCK:
            self._unlock_request(origin, reply_tag)
        elif mtype == FLUSH:
            # FIFO ordering: everything the origin sent before this
            # flush has been applied already — ack immediately
            self._pml().isend(np.zeros(0, np.uint8), 0, dtmod.BYTE,
                              origin, reply_tag, self.comm)
        elif mtype == PSCW_COMPLETE:
            self._pscw_complete[origin] = \
                self._pscw_complete.get(origin, 0) + 1
        elif mtype == PSCW_POST:
            self._pscw_posted[origin] = \
                self._pscw_posted.get(origin, 0) + 1
        else:
            raise RuntimeError(f"bad RMA message type {mtype}")

    # -- target-side lock service (ref: osc_rdma_lock.h queueing) --------

    def _grant(self, origin: int, reply_tag: int) -> None:
        self._pml().isend(np.zeros(0, np.uint8), 0, dtmod.BYTE, origin,
                          reply_tag, self.comm)

    def _lock_request(self, origin: int, mode: int, reply_tag: int) -> None:
        if mode == LOCK_SHARED:
            if self._lock_mode != LOCK_EXCLUSIVE and not self._lock_queue:
                self._lock_mode = LOCK_SHARED
                self._lock_holders.add(origin)
                self._grant(origin, reply_tag)
                return
        else:
            if self._lock_mode == 0:
                self._lock_mode = LOCK_EXCLUSIVE
                self._lock_holders.add(origin)
                self._grant(origin, reply_tag)
                return
        self._lock_queue.append((origin, mode, reply_tag))

    def _unlock_request(self, origin: int, reply_tag: int) -> None:
        self._lock_holders.discard(origin)
        if not self._lock_holders:
            self._lock_mode = 0
        self._grant(origin, reply_tag)  # unlock ack
        # grant waiters: one exclusive, or a run of shareds
        while self._lock_queue:
            o, m, rt = self._lock_queue[0]
            if m == LOCK_EXCLUSIVE:
                if self._lock_mode == 0:
                    self._lock_queue.popleft()
                    self._lock_mode = LOCK_EXCLUSIVE
                    self._lock_holders.add(o)
                    self._grant(o, rt)
                break
            if self._lock_mode == LOCK_EXCLUSIVE:
                break
            self._lock_queue.popleft()
            self._lock_mode = LOCK_SHARED
            self._lock_holders.add(o)
            self._grant(o, rt)

    # -- origin-side ops -------------------------------------------------

    @staticmethod
    def _as_wire(arr) -> Tuple[np.ndarray, int, int]:
        a = np.ascontiguousarray(arr)
        code = _DT_CODE.get(a.dtype)
        if code is None:
            raise TypeError(f"dtype {a.dtype} not supported on windows")
        return a, a.size, code

    def _check_target(self, target: int) -> None:
        if not 0 <= target < self.size:
            raise ValueError(f"bad target rank {target}")

    def put(self, arr, target: int, disp: int = 0) -> None:
        self._check_target(target)
        a, count, code = self._as_wire(arr)
        self._send_hdr(target, PUT, disp, count, code, payload=a)
        self._ops_sent[target] += 1
        band = _obs.current_band()
        pv_puts.add(1, band)
        pv_bytes_put.add(a.nbytes, band)

    def get(self, arr, target: int, disp: int = 0) -> None:
        """Fills `arr` (completes before return — stronger than MPI
        requires; rget gives the deferred form)."""
        self._wait_req(self.rget(arr, target, disp))

    def rget(self, arr, target: int, disp: int = 0):
        self._check_target(target)
        if not (isinstance(arr, np.ndarray) and arr.flags.c_contiguous):
            raise ValueError("get target must be a contiguous ndarray")
        code = _DT_CODE[arr.dtype]
        tag = self._new_reply_tag()
        buf = arr.view(np.uint8).reshape(-1)
        req = self._pml().irecv(buf, buf.size, dtmod.BYTE, target, tag,
                                self.comm)
        self._send_hdr(target, GET, disp, arr.size, code, reply_tag=tag)
        self._ops_sent[target] += 1
        self._out_reqs.append(req)
        band = _obs.current_band()
        pv_gets.add(1, band)
        pv_bytes_got.add(arr.nbytes, band)
        return req

    def accumulate(self, arr, target: int, disp: int = 0,
                   op: opmod.Op = opmod.SUM) -> None:
        self._check_target(target)
        a, count, code = self._as_wire(arr)
        self._send_hdr(target, ACC, disp, count, code, _op_code(op),
                       payload=a)
        self._ops_sent[target] += 1
        pv_accs.add(1, _obs.current_band())

    # request-form RMA (ref: ompi/mpi/c/rput.c, raccumulate.c): the AM
    # payload is snapshotted at issue, so local completion is
    # immediate — the returned request is born complete (stronger than
    # MPI requires; remote completion still needs flush/unlock)
    def rput(self, arr, target: int, disp: int = 0):
        from ompi_tpu.pml.request import CompletedRequest
        self.put(arr, target, disp)
        return CompletedRequest(self._progress)

    def raccumulate(self, arr, target: int, disp: int = 0,
                    op: opmod.Op = opmod.SUM):
        from ompi_tpu.pml.request import CompletedRequest
        self.accumulate(arr, target, disp, op)
        return CompletedRequest(self._progress)

    def rget_accumulate(self, arr, result: np.ndarray, target: int,
                        disp: int = 0, op: opmod.Op = opmod.SUM):
        """Returns the reply request (completes when `result` holds
        the pre-accumulate target data)."""
        self._check_target(target)
        a, count, code = self._as_wire(arr)
        tag = self._new_reply_tag()
        rbuf = result.view(np.uint8).reshape(-1)
        req = self._pml().irecv(rbuf, rbuf.size, dtmod.BYTE, target, tag,
                                self.comm)
        self._send_hdr(target, GET_ACC, disp, count, code, _op_code(op),
                       reply_tag=tag, payload=a)
        self._ops_sent[target] += 1
        pv_accs.add(1, _obs.current_band())
        return req

    def get_accumulate(self, arr, result: np.ndarray, target: int,
                       disp: int = 0, op: opmod.Op = opmod.SUM) -> None:
        self._wait_req(self.rget_accumulate(arr, result, target, disp, op))

    def fetch_and_op(self, value, result: np.ndarray, target: int,
                     disp: int = 0, op: opmod.Op = opmod.SUM) -> None:
        self.get_accumulate(np.atleast_1d(np.asarray(
            value, dtype=result.dtype)), result, target, disp, op)

    def compare_and_swap(self, compare, new, result: np.ndarray,
                         target: int, disp: int = 0) -> None:
        self._check_target(target)
        dt = result.dtype
        payload = np.concatenate([
            np.atleast_1d(np.asarray(compare, dtype=dt)),
            np.atleast_1d(np.asarray(new, dtype=dt))])
        code = _DT_CODE[np.dtype(dt)]
        tag = self._new_reply_tag()
        rbuf = result.view(np.uint8).reshape(-1)
        req = self._pml().irecv(rbuf, rbuf.size, dtmod.BYTE, target, tag,
                                self.comm)
        self._send_hdr(target, CAS, disp, 1, code, reply_tag=tag,
                       payload=payload)
        self._ops_sent[target] += 1
        pv_cas.add(1, _obs.current_band())
        self._wait_req(req)

    # -- synchronization -------------------------------------------------

    def _check_alive(self) -> None:
        """Raise ERR_PROC_FAILED / ERR_REVOKED instead of spinning
        when a peer of the window's comm died or the epoch was
        revoked — every blocking RMA wait loop polls this, so a
        window on a dead comm raises rather than hangs."""
        ulfm = self.state.ulfm
        if ulfm is not None and ulfm.active:
            ulfm.poll()
            ulfm.check_comm(self.comm)

    def _wait_req(self, req) -> None:
        """Reply/ack wait that stays failure-aware: a peer death
        error-completes the request (or surfaces via check_comm), and
        either way the caller gets an exception, never a hang."""
        while not req.complete:
            self._check_alive()
            if self._progress.progress() == 0:
                self._progress.idle_tick()
        if getattr(req.status, "error", 0):
            from ompi_tpu import errhandler as _eh
            raise _eh.MPIException(
                _eh.ERR_PROC_FAILED,
                "RMA peer failed while a reply was outstanding")

    def _drain_out(self) -> None:
        for r in self._out_reqs:
            r.wait()
        self._out_reqs.clear()

    def _wait_applied(self, goal: int) -> None:
        while self._applied_total < goal:
            self._check_alive()
            if self._progress.progress() == 0:
                self._progress.idle_tick()

    def fence(self) -> None:
        """Collective epoch boundary (osc/pt2pt fence: alltoall the
        per-target op counts, wait for the cumulative expectation)."""
        self._check_alive()
        counts = self._ops_sent.copy()
        expected = np.empty(self.size, dtype=np.int64)
        self.comm.Alltoall(counts, expected)
        self._expected_total += int(expected.sum())
        self._wait_applied(self._expected_total)
        self._drain_out()
        self._ops_sent[:] = 0
        self.comm.Barrier()

    def lock(self, target: int, mode: int = LOCK_EXCLUSIVE) -> None:
        self._check_target(target)
        tag = self._new_reply_tag()
        buf, req = self._recv_reply(0, target, tag)
        self._send_hdr(target, LOCK, opcode=mode, reply_tag=tag)
        t0 = time.perf_counter()
        self._wait_req(req)
        pv_lock_wait.update_max(int((time.perf_counter() - t0) * 1e6))

    def unlock(self, target: int) -> None:
        tag = self._new_reply_tag()
        buf, req = self._recv_reply(0, target, tag)
        self._send_hdr(target, UNLOCK, reply_tag=tag)
        self._wait_req(req)  # ack ⇒ every prior op at target applied
        self._drain_out()
        # _ops_sent is NOT reset: fence counting must stay consistent
        # with the target's _applied_total, which includes passive ops

    def lock_all(self) -> None:
        for t in range(self.size):
            self.lock(t, LOCK_SHARED)

    def unlock_all(self) -> None:
        for t in range(self.size):
            self.unlock(t)

    def flush(self, target: int) -> None:
        tag = self._new_reply_tag()
        buf, req = self._recv_reply(0, target, tag)
        self._send_hdr(target, FLUSH, reply_tag=tag)
        self._wait_req(req)

    def flush_all(self) -> None:
        for t in range(self.size):
            self.flush(t)

    def flush_local(self, target: int) -> None:
        self._drain_out()

    def sync(self) -> None:
        self._progress.progress()

    # -- PSCW (generalized active target) --------------------------------

    def start(self, group_ranks: List[int]) -> None:
        """Blocks until every target has post()ed — RMA ops from this
        access epoch may not touch a window before its exposure epoch
        opens (MPI-3 §11.5.2)."""
        self._start_group = list(group_ranks)
        while any(self._pscw_posted.get(t, 0) < 1
                  for t in self._start_group):
            self._check_alive()
            if self._progress.progress() == 0:
                self._progress.idle_tick()
        for t in self._start_group:
            self._pscw_posted[t] -= 1

    def complete(self) -> None:
        assert self._start_group is not None, "complete() without start()"
        for t in self._start_group:
            self._send_hdr(t, PSCW_COMPLETE)
        self._drain_out()
        self._start_group = None

    def post(self, group_ranks: List[int]) -> None:
        self._post_group = list(group_ranks)
        for o in self._post_group:
            self._send_hdr(o, PSCW_POST)

    def wait(self) -> None:
        """FIFO ordering ⇒ counting COMPLETEs is enough: each arrives
        after every op its origin issued in the epoch."""
        assert self._post_group is not None, "wait() without post()"
        need = {o: 1 for o in self._post_group}
        while any(self._pscw_complete.get(o, 0) < n
                  for o, n in need.items()):
            self._check_alive()
            if self._progress.progress() == 0:
                self._progress.idle_tick()
        for o in need:
            self._pscw_complete[o] -= 1
        self._post_group = None

    def test(self) -> bool:
        if self._post_group is None:
            return True
        self._progress.progress()
        if all(self._pscw_complete.get(o, 0) >= 1
               for o in self._post_group):
            for o in self._post_group:
                self._pscw_complete[o] -= 1
            self._post_group = None
            return True
        return False

    # -- lifecycle -------------------------------------------------------

    def free(self) -> None:
        if self._freed:
            return
        self.comm.Barrier()  # all ops everywhere done
        self._freed = True
        self._progress.unregister(self._am_progress)
        if self._hdr_req is not None:
            self._hdr_req.cancel()
            self._hdr_req = None
        self.comm.free()

    def abandon(self) -> None:
        """LOCAL teardown for fault paths: stop polling and receiving
        on this window without the collective handshake ``free``
        needs (peers may be dead).  Cancelling the wildcard header
        receive matters beyond hygiene: the dup'd comm's cid can be
        reused by a communicator built after recovery, and a live
        wildcard irecv on the dead window would steal — and misparse —
        the new communicator's traffic.  The dup'd comm itself is left
        for garbage collection."""
        if self._freed:
            return
        self._freed = True
        self._progress.unregister(self._am_progress)
        if self._hdr_req is not None:
            self._hdr_req.cancel()
            self._hdr_req = None
        self._pending = None

    def __repr__(self) -> str:
        return (f"Window({self.comm.name}, rank={self.rank}/{self.size}, "
                f"{self._mem.size}B, disp_unit={self.disp_unit})")


def create(comm, memory: np.ndarray, disp_unit: Optional[int] = None,
           name: str = "", info=None) -> Window:
    """MPI_Win_create (ref: ompi/mpi/c/win_create.c)."""
    if disp_unit is None:
        disp_unit = memory.dtype.itemsize if memory.size else 1
    return Window(comm, memory, disp_unit, name, info=info)


def allocate(comm, nbytes: int, disp_unit: int = 1, name: str = "") -> Window:
    """MPI_Win_allocate: window-owned zeroed memory."""
    return Window(comm, np.zeros(nbytes, dtype=np.uint8), disp_unit, name)


def create_dynamic(comm, info=None, name: str = "") -> Window:
    """MPI_Win_create_dynamic: no initial memory; regions come and go
    via attach/detach, addressed by absolute address."""
    win = Window(comm, np.zeros(0, dtype=np.uint8), 1, name, info=info)
    win._dynamic = True
    return win


def allocate_shared(comm, nbytes: int, disp_unit: int = 1,
                    name: str = "") -> Window:
    """MPI_Win_allocate_shared (ref: osc/sm): one file-backed segment
    mapped by every co-located rank; rank r's window memory is its
    slice, and shared_query exposes any peer's slice for direct
    load/store."""
    import mmap as mmap_mod
    import os

    rte = comm.state.rte
    # must be a shared-memory domain (same node)
    my_node = getattr(rte, "node_id", 0)
    for g in comm.group:
        st = comm._peer_state(g)
        if st is None:
            node = rte.modex_get(g, "node_id") \
                if hasattr(rte, "kv") else my_node
            if node != my_node:
                raise ValueError(
                    "MPI_Win_allocate_shared needs co-located ranks "
                    "(MPI_ERR_RMA_SHARED)")
    session = getattr(rte, "session_dir", "/tmp")
    path = os.path.join(
        session, f"winshared_{getattr(rte, 'jobid', 'job')}_"
                 f"{min(comm.group)}_{comm.cid}.buf")
    total = max(1, nbytes) * comm.size
    if comm.rank == 0:
        tmp = f"{path}.tmp"
        fd = os.open(tmp, os.O_CREAT | os.O_RDWR, 0o600)
        os.ftruncate(fd, total)
        os.close(fd)
        os.rename(tmp, path)
    comm.Barrier()
    fd = os.open(path, os.O_RDWR)
    mm = mmap_mod.mmap(fd, total)
    os.close(fd)
    seg = np.frombuffer(mm, dtype=np.uint8)
    mine = seg[comm.rank * nbytes: comm.rank * nbytes + nbytes]
    win = Window(comm, mine, disp_unit, name)
    win._shared_seg = seg
    win._shared_nbytes = nbytes
    win._shared_disp_unit = disp_unit
    return win


def shared_query(win: Window, rank: int):
    """(size, disp_unit, local view of `rank`'s segment)."""
    seg = getattr(win, "_shared_seg", None)
    if seg is None:
        raise ValueError("not a shared window (MPI_ERR_WIN)")
    n = win._shared_nbytes
    return n, win._shared_disp_unit, seg[rank * n: rank * n + n]


from ompi_tpu import errhandler as _eh_mod  # noqa: E402

_eh_mod.attach_api(Window)
