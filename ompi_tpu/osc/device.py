"""Device-memory one-sided RMA: the osc/device component.

Re-design of ompi/mca/osc/rdma for the thread-rank TPU world: window
memory lives in device HBM (one uint8 shard per rank on the comm's
mesh) and the DATA PLANE never touches the host AM path.  The
single-controller property that powers the coll reroute powers true
one-sided semantics here: the ORIGIN thread alone launches a
whole-mesh jitted program that moves its payload onto the target's
shard with ``ppermute`` + masked dynamic-slice merge — the target
thread does not participate, exactly as osc/rdma's btl put/get
bypasses the target CPU (ref: osc_rdma_comm.c put/get paths).

Lowering table (DESIGN.md §19):

    put/rput      direct DMA: compose the target shard on the origin's
                  host staging buffer (64-byte aligned so device_put
                  aliases instead of copying) and swap it in; a
                  wholesale aligned overwrite skips even the compose
                  and borrows the origin buffer until the local
                  completion point, exactly like zero-copy RDMA —
                  MPI already forbids mutating an origin buffer
                  before flush/unlock/fence.  ``--mca
                  osc_device_dma 0`` selects the mesh-collective
                  lowering instead: ppermute row origin→target +
                  masked merge, donated, chunked by the pipeline
                  tier's segment size
    get/rget      direct DMA: device→host read of the target shard +
                  memcpy of the requested span (kernel mode: masked
                  slice on target row + ppermute target→origin)
    accumulate    whole-mesh bucket kernel with bitcast u8→dtype→u8
                  and the op mapped through coll/pipeline's jnp binop
                  table (read-modify-write stays on device)
    get_accumulate / fetch_and_op   accumulate kernel variant that
                  ppermutes the pre-op bytes back to the origin
    compare_and_swap   single-element kernel (cmp, new) pair

Every kernel is cached in coll/device's CompiledLRU under keys that
embed the mesh's dev_key top-level, so ULFM's ``drop_mesh`` purge
covers RMA kernels exactly as it covers collectives.  Transfers
larger than the pipeline tier's calibrated segment are chunked into
segment-sized bucket kernels so a size sweep stays bounded.

Synchronization: ops apply synchronously inside the origin's call
(the DMA or mesh program IS remote completion), so ``fence``
degenerates to a liveness check + Barrier and ``flush`` to the
local-completion work of decoupling any zero-copy put — no AM
round-trip, because a device window never has ops outstanding at the
target.  lock/unlock/PSCW are inherited unchanged from the host AM
window — control stays on the host, payloads stay on device — and a
target parked in ``wait`` still serves grants because the AM handler
rides the progress sweep.

Typed atomics: in DMA mode every accumulate/CAS dtype takes the
host-side read-modify-write of the target's write-through mirror
under the window's table lock — one lock, every op serialized, so
atomicity holds across mixed dtypes and paths.  In kernel mode the
wire dtypes jax can bitcast run the jitted bucket kernels and the
rest (int64/float64/complex/bool/pair — x64 is off) take the same
host fallback.  put/get are byte-level and never care.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ompi_tpu import obs as _obs
from ompi_tpu import trace as _trace
from ompi_tpu.mca.params import registry
from ompi_tpu.op import op as opmod
from ompi_tpu.osc import window as _host
from ompi_tpu.osc.window import _DT_CODE, _WIRE_DTYPES, Window

_CAT_RMA = _trace.CAT_RMA
_NAME_RMA_PUT = _trace.NAME_RMA_PUT
_NAME_RMA_GET = _trace.NAME_RMA_GET
_NAME_RMA_ACC = _trace.NAME_RMA_ACC

_seg_var = registry.register(
    "osc", "device", "seg_bytes", 0, int,
    help="Chunk size (bytes) for device RMA transfers larger than one "
         "bucket kernel; 0 = reuse the pipeline tier's calibrated "
         "segment size (coll_seg_size / measured rules)")

_dma_var = registry.register(
    "osc", "device", "dma", 1, int,
    help="1 = lower contiguous put/get to direct host<->device DMA "
         "(aligned staging swap, zero-copy where the runtime allows); "
         "0 = whole-mesh ppermute bucket kernels for every transfer — "
         "the mesh-collective lowering, kept for topologies where an "
         "origin-driven host DMA is the slow path")

# staging discipline (alignment, aliasing probe, mirror pool, the
# donated-buffers warning filter) lives in the shared runtime module
# since the coll plan tier packs through the same bypass; the local
# names survive because the DMA path below predates the hoist
from ompi_tpu.runtime import staging as _staging

_STAGE_ALIGN = _staging.STAGE_ALIGN
_aligned_empty = _staging.aligned_empty
_runtime_zero_copy = _staging.runtime_zero_copy

#: window capacity / bucket alignment: max wire itemsize (complex128)
_ALIGN = 16
#: smallest bucket kernel — below this the fixed dispatch cost
#: dominates and one shape serves every tiny op
_BUCKET_MIN = 256

#: dtypes whose accumulate/CAS kernels run on device (32-bit jax
#: world: 8-byte and complex dtypes take the host fallback)
_JIT_ACC_DTYPES = frozenset(
    np.dtype(t).str for t in (np.uint8, np.int8, np.int16, np.uint16,
                              np.int32, np.uint32, np.float32))


def _pow2ceil(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


def _pow2floor(n: int) -> int:
    b = 1
    while b * 2 <= n:
        b <<= 1
    return b


def _bucket(nbytes: int, cap: int) -> int:
    """Static kernel width for an nbytes transfer into a cap-byte
    shard: pow2-quantized so the compile-cache key set stays bounded,
    clamped to the shard so the slice math can always clamp left."""
    b = _pow2ceil(max(nbytes, min(_BUCKET_MIN, cap)))
    return min(b, cap)


def _binop(opname: str):
    if opname == "MPI_REPLACE":
        return lambda s, w: s
    if opname == "MPI_NO_OP":
        return lambda s, w: w
    from ompi_tpu.coll.pipeline import _binop as _pipe_binop
    return _pipe_binop(opname)


class _ShardTable:
    """The per-window cross-rank state in world.shared: every rank's
    device shard, one lock serializing all data-plane ops (which is
    what makes accumulate atomic), per-bucket zero rows for assembling
    source globals, and the DMA path's write-through mirrors — the
    aligned host staging buffer each shard aliases (None when a shard
    is borrowed from an origin buffer or is a kernel output).
    ``alias_tok`` identifies the zero-copy put that borrowed a shard,
    so only the borrowing origin's completion point decouples it."""

    __slots__ = ("arrs", "lock", "zeros", "mirrors", "alias_tok",
                 "pool")

    def __init__(self, size: int) -> None:
        self.arrs: List[Any] = [None] * size
        self.lock = threading.RLock()
        self.zeros: Dict[int, List[Any]] = {}
        self.mirrors: List[Optional[np.ndarray]] = [None] * size
        self.alias_tok: List[Any] = [None] * size
        #: displaced mirrors parked for reuse, so the decoupling copy
        #: at a completion point never pays fresh-page faults
        self.pool = _staging.MirrorPool(max_buffers=size)


# -- kernel builders --------------------------------------------------------


def _shmap(body, mesh, in_specs, out_specs):
    from ompi_tpu.coll import device as _dc
    return _dc.shard_map_compat(body, mesh, in_specs, out_specs)


def _build_put(mesh, cap: int, b: int, o: int, t: int):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    def body(w, s, st, cnt):
        moved = lax.ppermute(s, "r", perm=[(o, t)])
        i = lax.axis_index("r")
        s0 = jnp.minimum(st[0], cap - b)
        off = st[0] - s0
        winv = lax.dynamic_slice(w, (s0,), (b,))
        idx = lax.iota(jnp.int32, b)
        src = jnp.roll(moved, off)
        sel = (idx >= off) & (idx < off + cnt[0]) & (i == t)
        merged = jnp.where(sel, src, winv)
        return lax.dynamic_update_slice(w, merged, (s0,))

    fn = _shmap(body, mesh, (P("r"), P("r"), P(None), P(None)), P("r"))
    return jax.jit(fn, donate_argnums=(0,))


def _build_get(mesh, cap: int, b: int, t: int, o: int):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    def body(w, st):
        s0 = jnp.minimum(st[0], cap - b)
        off = st[0] - s0
        winv = lax.dynamic_slice(w, (s0,), (b,))
        winv = jnp.roll(winv, -off)
        return lax.ppermute(winv, "r", perm=[(t, o)])

    fn = _shmap(body, mesh, (P("r"), P(None)), P("r"))
    return jax.jit(fn)


def _build_acc(mesh, cap: int, b: int, o: int, t: int, dtstr: str,
               opname: str, fetch: bool):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    dt = np.dtype(dtstr)
    isz = dt.itemsize
    n = b // isz
    binop = _binop(opname)

    def body(w, s, st, cnt):
        moved = lax.ppermute(s, "r", perm=[(o, t)])
        i = lax.axis_index("r")
        s0 = jnp.minimum(st[0], cap - b)
        off = st[0] - s0
        winv = lax.dynamic_slice(w, (s0,), (b,))
        wt = lax.bitcast_convert_type(winv.reshape(n, isz), dt)
        srcb = jnp.roll(moved, off)
        stt = lax.bitcast_convert_type(srcb.reshape(n, isz), dt)
        idx = lax.iota(jnp.int32, n)
        oe = off // isz
        ce = cnt[0] // isz
        sel = (idx >= oe) & (idx < oe + ce) & (i == t)
        new = jnp.where(sel, binop(stt, wt), wt)
        outb = lax.bitcast_convert_type(new, jnp.uint8).reshape(b)
        neww = lax.dynamic_update_slice(w, outb, (s0,))
        if fetch:
            fetched = lax.ppermute(jnp.roll(winv, -off), "r",
                                   perm=[(t, o)])
            return neww, fetched
        return neww

    out_specs = (P("r"), P("r")) if fetch else P("r")
    fn = _shmap(body, mesh, (P("r"), P("r"), P(None), P(None)), out_specs)
    return jax.jit(fn, donate_argnums=(0,))


def _build_cas(mesh, cap: int, o: int, t: int, dtstr: str):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    dt = np.dtype(dtstr)
    isz = dt.itemsize
    b = 2 * isz  # source row carries [compare, new]

    def body(w, s, st):
        moved = lax.ppermute(s, "r", perm=[(o, t)])
        pair = lax.bitcast_convert_type(moved.reshape(2, isz), dt)
        i = lax.axis_index("r")
        winv = lax.dynamic_slice(w, (st[0],), (isz,))
        old = lax.bitcast_convert_type(winv.reshape(1, isz), dt)
        hit = (old[0] == pair[0]) & (i == t)
        newv = jnp.where(hit, pair[1], old[0]).reshape(1)
        newb = lax.bitcast_convert_type(newv, jnp.uint8).reshape(isz)
        neww = lax.dynamic_update_slice(w, newb, (st[0],))
        fetched = lax.ppermute(winv, "r", perm=[(t, o)])
        return neww, fetched

    fn = _shmap(body, mesh, (P("r"), P("r"), P(None)), (P("r"), P("r")))
    return jax.jit(fn, donate_argnums=(0,))


def _build_lslice(cap: int, b: int):
    """Single-device local read: dynamic slice out of one shard
    without pulling the whole capacity to the host."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def body(w, st):
        s0 = jnp.minimum(st[0], cap - b)
        off = st[0] - s0
        return jnp.roll(lax.dynamic_slice(w, (s0,), (b,)), -off)

    return jax.jit(body)


class DeviceWindow(Window):
    """MPI_Win whose memory is a device shard on the comm's mesh."""

    def __init__(self, comm, memory=None, disp_unit: int = 1,
                 name: str = "", info=None) -> None:
        import jax

        mesh = comm.mesh()
        if mesh is None:
            raise ValueError(
                "osc/device window needs a comm whose ranks own "
                "distinct devices (comm.mesh() is None)")
        self._mesh = mesh
        self._devs = list(mesh.devices.reshape(-1))
        self._dev = self._devs[comm.rank]
        self._dev_key = tuple(d.id for d in self._devs)

        if memory is None:
            memory = np.zeros(0, dtype=np.uint8)
        host = np.asarray(memory)  # device arrays copy to host once
        self._shape = host.shape
        self._view_dtype = host.dtype
        flat = np.ascontiguousarray(host).reshape(-1).view(np.uint8)
        self._win_bytes = flat.nbytes
        self._cap = max(_ALIGN, -(-flat.nbytes // _ALIGN) * _ALIGN)
        pad = _aligned_empty(self._cap)
        pad[:] = 0
        pad[: flat.nbytes] = flat
        #: target -> alias token for shards this window's zero-copy
        #: puts left aliasing an origin buffer; decoupled at the
        #: local-completion points (_materialize)
        self._borrowed: Dict[int, Any] = {}

        # cross-rank shard table: windows are created collectively in
        # the same order on every rank, so a per-comm sequence number
        # names this window uniquely; the parent constructor's closing
        # Barrier publishes every rank's deposit
        seq = comm.__dict__.get("_osc_win_seq", 0)
        comm.__dict__["_osc_win_seq"] = seq + 1
        self._world = comm.state.rte.world
        self._table_key = ("osc_devwin", comm.cid, tuple(comm.group), seq)
        with self._world.shared_lock:
            tab = self._world.shared.get(self._table_key)
            if tab is None:
                tab = _ShardTable(comm.size)
                self._world.shared[self._table_key] = tab
        tab.arrs[comm.rank] = jax.device_put(pad, self._dev)
        if _runtime_zero_copy():
            tab.mirrors[comm.rank] = pad  # device_put aliased it
        self._tab = tab

        super().__init__(comm, np.zeros(0, dtype=np.uint8), disp_unit,
                         name, info=info)

    # the parent constructor assigns ``self.memory``; the device
    # window serves it as a fresh host copy of the live shard instead
    @property
    def memory(self) -> np.ndarray:
        with self._tab.lock:
            host = np.asarray(self._tab.arrs[self.rank])[: self._win_bytes]
        if self._view_dtype == np.uint8 and len(self._shape) == 1:
            return host
        return host.view(self._view_dtype).reshape(self._shape)

    @memory.setter
    def memory(self, value) -> None:
        pass  # parent __init__ writes its placeholder; shard is truth

    # -- shard plumbing ---------------------------------------------------

    def _cache(self):
        from ompi_tpu.coll import device as _dc
        return _dc.compile_cache

    def _assemble_win(self):
        from ompi_tpu.coll import device as _dc
        return _dc._assemble(self._mesh, self._tab.arrs)

    def _assemble_src(self, row: np.ndarray):
        import jax
        from ompi_tpu.coll import device as _dc
        b = row.nbytes
        zeros = self._tab.zeros.get(b)
        if zeros is None:
            import jax.numpy as jnp
            zeros = [jax.device_put(jnp.zeros(b, jnp.uint8), d)
                     for d in self._devs]
            self._tab.zeros[b] = zeros
        rows = list(zeros)
        rows[self.rank] = jax.device_put(row, self._dev)
        return _dc._assemble(self._mesh, rows)

    def _replace_shards(self, out) -> None:
        from ompi_tpu.coll import device as _dc
        parts = _dc._scatter_out(out, self._mesh, self.size)
        for i in range(self.size):
            self._tab.arrs[i] = parts[i]
            self._tab.mirrors[i] = None  # kernel outputs own themselves
            self._tab.alias_tok[i] = None

    def _seg_bytes(self) -> int:
        v = _seg_var.value
        if v > 0:
            return _pow2floor(max(_ALIGN, v))
        try:
            from ompi_tpu.coll import pipeline
            s = pipeline.segment_elems(self.comm, 1)
        except Exception:  # noqa: BLE001 — calibrate profile optional
            s = 1 << 20
        return _pow2floor(max(s, 1 << 16))

    def _span(self, arr) -> Tuple[np.ndarray, int]:
        a = np.ascontiguousarray(arr)
        if _DT_CODE.get(a.dtype) is None:
            raise TypeError(f"dtype {a.dtype} not supported on windows")
        return a, a.nbytes

    def _range_check(self, start: int, nbytes: int) -> None:
        if start < 0 or start + nbytes > self._win_bytes:
            raise ValueError(
                f"RMA range [{start}, {start + nbytes}) outside the "
                f"{self._win_bytes}-byte window (MPI_ERR_RMA_RANGE)")

    # -- data plane: put / get -------------------------------------------

    def put(self, arr, target: int, disp: int = 0) -> None:
        tr = self.state.tracer
        if tr is None:
            nbytes = self._put_impl(arr, target, disp)
        else:
            t0 = tr.start_sampled(_CAT_RMA)
            nbytes = self._put_impl(arr, target, disp)
            if t0:
                tr.end(t0, _NAME_RMA_PUT, _CAT_RMA, self.comm.cid,
                       target, nbytes)
        band = _obs.current_band()
        _host.pv_puts.add(1, band)
        _host.pv_bytes_put.add(nbytes, band)

    def get(self, arr, target: int, disp: int = 0) -> None:
        tr = self.state.tracer
        if tr is None:
            nbytes = self._get_impl(arr, target, disp)
        else:
            t0 = tr.start_sampled(_CAT_RMA)
            nbytes = self._get_impl(arr, target, disp)
            if t0:
                tr.end(t0, _NAME_RMA_GET, _CAT_RMA, self.comm.cid,
                       target, nbytes)
        band = _obs.current_band()
        _host.pv_gets.add(1, band)
        _host.pv_bytes_got.add(nbytes, band)

    def _put_impl(self, arr, target: int, disp: int) -> int:
        self._check_target(target)
        a, nbytes = self._span(arr)
        start = disp * self.disp_unit
        self._range_check(start, nbytes)
        if nbytes == 0:
            return 0
        src = a.reshape(-1).view(np.uint8)
        if _dma_var.value:
            self._put_dma(src, target, start)
            return nbytes
        seg = self._seg_bytes()
        off = 0
        with self._tab.lock:
            while off < nbytes:
                chunk = min(seg, nbytes - off)
                self._put_chunk(src[off: off + chunk], target, start + off)
                off += chunk
        return nbytes

    def _ensure_mirror(self, target: int) -> np.ndarray:
        """Put the target shard into write-through-mirror state (the
        shard aliases an owned aligned host buffer) and return the
        mirror.  Caller holds the table lock; zero-copy runtime only."""
        import jax

        tab = self._tab
        mir = tab.mirrors[target]
        if mir is None:
            mir = tab.pool.take(self._cap)
            np.copyto(mir, np.asarray(tab.arrs[target]))
            tab.arrs[target] = jax.device_put(mir, self._devs[target])
            tab.mirrors[target] = mir
            tab.alias_tok[target] = None
        return mir

    def _put_dma(self, src: np.ndarray, target: int, start: int) -> None:
        """Direct-DMA put, never a whole-mesh program.

        Zero-copy runtime: a wholesale aligned overwrite aliases the
        origin buffer outright (O(1) device_put) and defers the
        decoupling copy to the local-completion point — MPI forbids
        the origin mutating the buffer before then, the same contract
        zero-copy RDMA rides.  Anything else is one memcpy into the
        target's write-through mirror, which the device shard aliases.

        Copying runtime: compose into an aligned staging buffer and
        upload — the device_put IS the host→HBM DMA then."""
        import jax

        n = src.nbytes
        tab = self._tab
        with tab.lock:
            if not _runtime_zero_copy():
                stage = _aligned_empty(self._cap)
                if n < self._cap:
                    stage[:] = np.asarray(tab.arrs[target])
                stage[start: start + n] = src
                tab.arrs[target] = jax.device_put(
                    stage, self._devs[target])
                return
            if (n == self._cap and start == 0
                    and src.ctypes.data % _STAGE_ALIGN == 0):
                tok = object()
                tab.arrs[target] = jax.device_put(
                    src, self._devs[target])
                tab.pool.park(tab.mirrors[target])
                tab.mirrors[target] = None
                tab.alias_tok[target] = tok
                self._borrowed[target] = tok
                return
            mir = self._ensure_mirror(target)
            np.copyto(mir[start: start + n], src)
            self._borrowed.pop(target, None)

    def _put_chunk(self, src: np.ndarray, target: int, start: int) -> None:
        n = src.nbytes
        b = _bucket(n, self._cap)
        pad = np.zeros(b, dtype=np.uint8)
        pad[:n] = src
        key = ("osc_pput", self._dev_key, self._cap, b, self.rank, target)
        fn = self._cache().get(
            key, lambda: _build_put(self._mesh, self._cap, b,
                                    self.rank, target))
        w = self._assemble_win()
        s = self._assemble_src(pad)
        out = fn(w, s, np.array([start], np.int32), np.array([n], np.int32))
        self._replace_shards(out)

    def _get_impl(self, arr, target: int, disp: int) -> int:
        self._check_target(target)
        if not (isinstance(arr, np.ndarray) and arr.flags.c_contiguous
                and arr.flags.writeable):
            raise ValueError("get target must be a writable contiguous "
                             "ndarray")
        nbytes = arr.nbytes
        start = disp * self.disp_unit
        self._range_check(start, nbytes)
        if nbytes == 0:
            return 0
        dst = arr.view(np.uint8).reshape(-1)
        if _dma_var.value:
            # direct DMA: device→host read of the target shard (a
            # zero-copy view on the CPU runtime) + one memcpy of the
            # requested span
            with self._tab.lock:
                view = np.asarray(self._tab.arrs[target])
                np.copyto(dst, view[start: start + nbytes])
            return nbytes
        seg = self._seg_bytes()
        off = 0
        with self._tab.lock:
            while off < nbytes:
                chunk = min(seg, nbytes - off)
                dst[off: off + chunk] = \
                    self._get_chunk(chunk, target, start + off)
                off += chunk
        return nbytes

    def _get_chunk(self, n: int, target: int, start: int) -> np.ndarray:
        b = _bucket(n, self._cap)
        key = ("osc_pget", self._dev_key, self._cap, b, target, self.rank)
        fn = self._cache().get(
            key, lambda: _build_get(self._mesh, self._cap, b,
                                    target, self.rank))
        w = self._assemble_win()
        out = fn(w, np.array([start], np.int32))
        from ompi_tpu.coll import device as _dc
        parts = _dc._scatter_out(out, self._mesh, self.size)
        return np.asarray(parts[self.rank])[:n]

    def rput(self, arr, target: int, disp: int = 0):
        from ompi_tpu.pml.request import CompletedRequest
        self.put(arr, target, disp)
        return CompletedRequest(self._progress)

    def rget(self, arr, target: int, disp: int = 0):
        from ompi_tpu.pml.request import CompletedRequest
        self.get(arr, target, disp)
        return CompletedRequest(self._progress)

    # -- data plane: accumulate family -----------------------------------

    def accumulate(self, arr, target: int, disp: int = 0,
                   op: opmod.Op = opmod.SUM) -> None:
        self._acc_entry(arr, None, target, disp, op)

    def raccumulate(self, arr, target: int, disp: int = 0,
                    op: opmod.Op = opmod.SUM):
        from ompi_tpu.pml.request import CompletedRequest
        self.accumulate(arr, target, disp, op)
        return CompletedRequest(self._progress)

    def get_accumulate(self, arr, result: np.ndarray, target: int,
                       disp: int = 0, op: opmod.Op = opmod.SUM) -> None:
        self._acc_entry(arr, result, target, disp, op)

    def rget_accumulate(self, arr, result: np.ndarray, target: int,
                        disp: int = 0, op: opmod.Op = opmod.SUM):
        from ompi_tpu.pml.request import CompletedRequest
        self.get_accumulate(arr, result, target, disp, op)
        return CompletedRequest(self._progress)

    def fetch_and_op(self, value, result: np.ndarray, target: int,
                     disp: int = 0, op: opmod.Op = opmod.SUM) -> None:
        self.get_accumulate(np.atleast_1d(np.asarray(
            value, dtype=result.dtype)), result, target, disp, op)

    def _acc_entry(self, arr, result, target, disp, op) -> None:
        tr = self.state.tracer
        if tr is None:
            nbytes = self._acc_impl(arr, result, target, disp, op)
        else:
            t0 = tr.start_sampled(_CAT_RMA)
            nbytes = self._acc_impl(arr, result, target, disp, op)
            if t0:
                tr.end(t0, _NAME_RMA_ACC, _CAT_RMA, self.comm.cid,
                       target, nbytes)
        _host.pv_accs.add(1, _obs.current_band())

    def _acc_impl(self, arr, result, target: int, disp: int,
                  op: opmod.Op) -> int:
        self._check_target(target)
        a, nbytes = self._span(arr)
        if result is not None and result.dtype != a.dtype:
            raise TypeError("get_accumulate origin/result dtype mismatch")
        start = disp * self.disp_unit
        self._range_check(start, nbytes)
        if nbytes == 0:
            return 0
        dtstr = a.dtype.str
        isz = a.dtype.itemsize
        jitted = (not _dma_var.value
                  and dtstr in _JIT_ACC_DTYPES and op.name != "MPI_MAXLOC"
                  and op.name != "MPI_MINLOC" and start % isz == 0)
        with self._tab.lock:
            if not jitted:
                old = self._acc_host(a, target, start, op)
            else:
                old = self._acc_dev(a, target, start, op,
                                    fetch=result is not None)
        if result is not None:
            res = result.view(np.uint8).reshape(-1)
            res[:] = old[: res.nbytes]
        return nbytes

    def _acc_dev(self, a: np.ndarray, target: int, start: int,
                 op: opmod.Op, fetch: bool) -> Optional[np.ndarray]:
        src = a.reshape(-1).view(np.uint8)
        nbytes = src.nbytes
        seg = self._seg_bytes()
        out_bytes = np.empty(nbytes, np.uint8) if fetch else None
        off = 0
        while off < nbytes:
            chunk = min(seg, nbytes - off)
            got = self._acc_chunk(src[off: off + chunk], target,
                                  start + off, a.dtype, op, fetch)
            if fetch:
                out_bytes[off: off + chunk] = got
            off += chunk
        return out_bytes

    def _acc_chunk(self, src: np.ndarray, target: int, start: int,
                   dt: np.dtype, op: opmod.Op,
                   fetch: bool) -> Optional[np.ndarray]:
        n = src.nbytes
        b = _bucket(n, self._cap)
        # bucket and clamp math stay dtype-aligned: cap and b are
        # multiples of _ALIGN >= itemsize and start % itemsize == 0
        pad = np.zeros(b, dtype=np.uint8)
        pad[:n] = src
        key = ("osc_pacc", self._dev_key, self._cap, b, dt.str,
               op.name, bool(fetch), self.rank, target)
        fn = self._cache().get(
            key, lambda: _build_acc(self._mesh, self._cap, b, self.rank,
                                    target, dt.str, op.name, fetch))
        w = self._assemble_win()
        s = self._assemble_src(pad)
        out = fn(w, s, np.array([start], np.int32), np.array([n], np.int32))
        from ompi_tpu.coll import device as _dc
        if fetch:
            neww, fetched = out
            self._replace_shards(neww)
            parts = _dc._scatter_out(fetched, self._mesh, self.size)
            return np.asarray(parts[self.rank])[:n]
        self._replace_shards(out)
        return None

    def _acc_host(self, a: np.ndarray, target: int, start: int,
                  op: opmod.Op) -> np.ndarray:
        """Atomic host-side read-modify-write: the DMA mode's typed
        path for every dtype, and the kernel mode's fallback for
        dtypes the 32-bit jax world cannot bitcast (int64/float64/
        complex/bool/pair).  Holds the table lock (caller), so it
        interleaves atomically with every device kernel."""
        flat = a.reshape(-1)
        if _runtime_zero_copy():
            mir = self._ensure_mirror(target)
            region = mir[start: start + a.nbytes].view(a.dtype)
            old = region.copy()
            region[:] = op.reduce(flat, region.copy())
            return old.view(np.uint8).reshape(-1)
        import jax

        cur = _aligned_empty(self._cap)
        cur[:] = np.asarray(self._tab.arrs[target])
        region = cur[start: start + a.nbytes].view(a.dtype)
        old = region.copy()
        region[:] = op.reduce(flat, region.copy())
        self._tab.arrs[target] = jax.device_put(cur, self._devs[target])
        return old.view(np.uint8).reshape(-1)

    def compare_and_swap(self, compare, new, result: np.ndarray,
                         target: int, disp: int = 0) -> None:
        self._check_target(target)
        dt = np.dtype(result.dtype)
        if _DT_CODE.get(dt) is None:
            raise TypeError(f"dtype {dt} not supported on windows")
        start = disp * self.disp_unit
        self._range_check(start, dt.itemsize)
        cmp_v = np.atleast_1d(np.asarray(compare, dtype=dt))
        new_v = np.atleast_1d(np.asarray(new, dtype=dt))
        with self._tab.lock:
            if (not _dma_var.value and dt.str in _JIT_ACC_DTYPES
                    and start % dt.itemsize == 0):
                old = self._cas_dev(cmp_v, new_v, target, start, dt)
            else:
                old = self._cas_host(cmp_v, new_v, target, start, dt)
        res = result.view(np.uint8).reshape(-1)
        res[:] = old[: res.nbytes]
        _host.pv_cas.add(1, _obs.current_band())

    def _cas_dev(self, cmp_v, new_v, target: int, start: int,
                 dt: np.dtype) -> np.ndarray:
        pair = np.concatenate([cmp_v, new_v]).view(np.uint8)
        key = ("osc_pcas", self._dev_key, self._cap, dt.str,
               self.rank, target)
        fn = self._cache().get(
            key, lambda: _build_cas(self._mesh, self._cap, self.rank,
                                    target, dt.str))
        w = self._assemble_win()
        s = self._assemble_src(np.ascontiguousarray(pair))
        neww, fetched = fn(w, s, np.array([start], np.int32))
        self._replace_shards(neww)
        from ompi_tpu.coll import device as _dc
        parts = _dc._scatter_out(fetched, self._mesh, self.size)
        return np.asarray(parts[self.rank])[: dt.itemsize]

    def _cas_host(self, cmp_v, new_v, target: int, start: int,
                  dt: np.dtype) -> np.ndarray:
        if _runtime_zero_copy():
            mir = self._ensure_mirror(target)
            region = mir[start: start + dt.itemsize].view(dt)
            old = region.copy()
            if old[0] == cmp_v[0]:
                region[0] = new_v[0]
            return old.view(np.uint8).reshape(-1)
        import jax

        cur = _aligned_empty(self._cap)
        cur[:] = np.asarray(self._tab.arrs[target])
        region = cur[start: start + dt.itemsize].view(dt)
        old = region.copy()
        if old[0] == cmp_v[0]:
            region[0] = new_v[0]
        self._tab.arrs[target] = jax.device_put(cur, self._devs[target])
        return old.view(np.uint8).reshape(-1)

    # -- local access (oshmem heap reads ride this) ----------------------

    def read_local(self, start: int, nbytes: int) -> np.ndarray:
        """Host copy of [start, start+nbytes) of the local shard — a
        direct device→host span read in DMA mode (the oshmem
        wait_until poll path), a jitted dynamic slice (O(bucket), not
        O(capacity)) in kernel mode."""
        self._range_check(start, nbytes)
        if nbytes == 0:
            return np.empty(0, np.uint8)
        if _dma_var.value:
            with self._tab.lock:
                view = np.asarray(self._tab.arrs[self.rank])
                return view[start: start + nbytes].copy()
        b = _bucket(nbytes, self._cap)
        key = ("osc_lslice", self._dev_key, self._cap, b)
        fn = self._cache().get(key, lambda: _build_lslice(self._cap, b))
        with self._tab.lock:
            out = fn(self._tab.arrs[self.rank], np.array([start], np.int32))
            return np.asarray(out)[:nbytes].copy()

    # -- synchronization --------------------------------------------------

    def _materialize(self) -> None:
        """Decouple shards still aliasing an origin buffer from a
        zero-copy put: copy them into an owned write-through mirror
        and swap that in.  This is the DMA path's local-completion
        work, so every sync entry point (fence / flush / flush_local /
        unlock / complete) runs it first.  The alias token skips
        shards some later op already rewrote."""
        if not self._borrowed:
            return
        import jax

        tab = self._tab
        with tab.lock:
            for t, tok in self._borrowed.items():
                if tab.alias_tok[t] is not tok:
                    continue
                mir = tab.pool.take(self._cap)
                np.copyto(mir, np.asarray(tab.arrs[t]))
                tab.arrs[t] = jax.device_put(mir, self._devs[t])
                tab.mirrors[t] = mir
                tab.alias_tok[t] = None
            self._borrowed.clear()

    def fence(self) -> None:
        """Active-target epoch boundary: device ops complete inside
        the origin's call, so the fence is a liveness check plus the
        collective Barrier (which rides the coll fence/rendezvous
        primitives and raises instead of hanging on a dead comm)."""
        self._check_alive()
        self._materialize()
        self._drain_out()
        self._ops_sent[:] = 0
        self.comm.Barrier()

    def flush(self, target: int) -> None:
        # device ops complete inside the origin's call and never ride
        # the AM path, so there is nothing outstanding at the target:
        # flush is the liveness check plus decoupling any zero-copy
        # put (the host component's FLUSH round-trip waits for applied
        # AMs, of which a device window has none)
        self._check_alive()
        self._materialize()
        self._drain_out()

    def flush_all(self) -> None:
        self._check_alive()
        self._materialize()
        self._drain_out()

    def flush_local(self, target: int) -> None:
        self._materialize()
        self._drain_out()

    def unlock(self, target: int) -> None:
        self._materialize()
        super().unlock(target)

    def unlock_all(self) -> None:
        self._materialize()
        super().unlock_all()

    def complete(self) -> None:
        self._materialize()
        super().complete()

    # -- lifecycle --------------------------------------------------------

    def _drop_table(self) -> None:
        with self._world.shared_lock:
            self._world.shared.pop(self._table_key, None)

    def free(self) -> None:
        if self._freed:
            return
        super().free()
        self._drop_table()

    def abandon(self) -> None:
        if self._freed:
            return
        super().abandon()
        self._drop_table()

    def __repr__(self) -> str:
        return (f"DeviceWindow({self.comm.name}, "
                f"rank={self.rank}/{self.size}, {self._win_bytes}B@"
                f"{getattr(self._dev, 'id', '?')}, "
                f"disp_unit={self.disp_unit})")


def create(comm, memory, disp_unit: Optional[int] = None,
           name: str = "", info=None) -> DeviceWindow:
    if disp_unit is None:
        itemsize = getattr(getattr(memory, "dtype", None), "itemsize", 1)
        disp_unit = itemsize if getattr(memory, "size", 0) else 1
    return DeviceWindow(comm, memory, disp_unit, name, info=info)


def allocate(comm, nbytes: int, disp_unit: int = 1,
             name: str = "") -> DeviceWindow:
    return DeviceWindow(comm, np.zeros(nbytes, dtype=np.uint8),
                        disp_unit, name)
