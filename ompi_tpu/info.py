"""MPI_Info objects: ordered string key/value hints.

Re-design of ompi/info (ref: ompi/info/info.c — ordered list with
key length limits; MPI_INFO_ENV prepopulated at init,
ref: ompi_mpi_init.c info_env setup).
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List, Optional, Tuple

MAX_INFO_KEY = 255
MAX_INFO_VAL = 1024


class Info:
    def __init__(self) -> None:
        self._d: Dict[str, str] = {}

    # -- MPI surface ----------------------------------------------------
    def set(self, key: str, value: str) -> None:
        if not key or len(key) > MAX_INFO_KEY:
            raise ValueError(f"bad info key {key!r} (MPI_ERR_INFO_KEY)")
        if len(str(value)) > MAX_INFO_VAL:
            raise ValueError("info value too long (MPI_ERR_INFO_VALUE)")
        self._d[key] = str(value)

    def get(self, key: str) -> Tuple[bool, Optional[str]]:
        """(flag, value) like MPI_Info_get."""
        if key in self._d:
            return True, self._d[key]
        return False, None

    def delete(self, key: str) -> None:
        if key not in self._d:
            raise KeyError(f"no such info key {key} (MPI_ERR_INFO_NOKEY)")
        del self._d[key]

    def nkeys(self) -> int:
        return len(self._d)

    def nthkey(self, n: int) -> str:
        keys = list(self._d.keys())
        if not 0 <= n < len(keys):
            raise ValueError(f"info key index {n} out of range")
        return keys[n]

    def dup(self) -> "Info":
        out = Info()
        out._d = dict(self._d)
        return out

    def items(self):
        return self._d.items()

    def __repr__(self) -> str:
        return f"<Info {self._d!r}>"


INFO_NULL = None


def info_env(state=None) -> Info:
    """MPI_INFO_ENV: launch facts (ref: ompi_mpi_init.c's info_env)."""
    inf = Info()
    inf.set("command", sys.argv[0] if sys.argv else "")
    inf.set("argv", " ".join(sys.argv[1:]))
    if state is not None:
        inf.set("maxprocs", str(getattr(state.rte, "world_size",
                                        state.size)))
    inf.set("host", os.uname().nodename)
    inf.set("arch", os.uname().machine)
    inf.set("thread_level", "MPI_THREAD_MULTIPLE")
    return inf
