"""Reduction operations: per-(op, datatype) dispatch tables.

Re-design of ompi/op (ref: ompi/op/op.h:541 ompi_op_reduce dispatch;
ompi/mca/op/base/op_base_functions.c — 1544 LoC of per-type C loops;
ompi/mca/op/op.h:55-74 module-per-function selection).  Instead of C
loops, each op carries two implementations selected per buffer
residency:

  * ``np_fn(a, b) -> b`` — vectorized numpy, for host buffers on the
    p2p reduction path (ring/recursive-doubling steps);
  * ``jax_fn`` — a traceable elementwise lambda, used by coll/tpu to
    lower the whole reduction into the XLA collective (psum et al.)
    so the MXU/VPU does the math on-device.

MAXLOC/MINLOC operate on the structured pair dtypes from
datatype.engine (FLOAT_INT ...), matching MPI semantics of minimum
index on ties.
"""

from __future__ import annotations

import itertools
from typing import Callable, Dict, Optional

import numpy as np

_user_op_ids = itertools.count()


class Op:
    def __init__(self, name: str, np_fn: Optional[Callable] = None,
                 jax_name: Optional[str] = None, commute: bool = True,
                 float_ok: bool = True, int_ok: bool = True,
                 logical_ok: bool = True, complex_ok: bool = False,
                 pair_fn: Optional[Callable] = None) -> None:
        self.name = name
        self.np_fn = np_fn
        self.jax_name = jax_name  # psum/pmax/pmin lowering hint for coll/tpu
        self.commute = commute
        self.is_user = False
        self.float_ok = float_ok
        self.int_ok = int_ok
        self.logical_ok = logical_ok
        self.complex_ok = complex_ok
        self.pair_fn = pair_fn

    def __repr__(self) -> str:
        return f"Op({self.name})"

    def valid_for(self, dtype: np.dtype) -> bool:
        if self.is_user:
            return True
        if dtype.fields is not None:
            return self.pair_fn is not None
        k = dtype.kind
        if k in "fg":
            return self.float_ok
        if k in "iu":
            return self.int_ok
        if k == "b":
            return self.logical_ok
        if k == "c":
            return self.complex_ok
        return False

    def reduce(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        """b = a OP b elementwise (the MPI accumulate convention:
        ref ompi/op/op.h ompi_op_reduce(op, source, target))."""
        if a.dtype.fields is not None:
            if self.pair_fn is None:
                raise TypeError(f"{self.name} invalid on pair type")
            return self.pair_fn(a, b)
        if self.np_fn is None:
            raise TypeError(f"{self.name} has no elementwise form")
        return self.np_fn(a, b)


def _maxloc(a, b):
    # value field "v", index field "i"; ties pick the smaller index
    take_a = (a["v"] > b["v"]) | ((a["v"] == b["v"]) & (a["i"] < b["i"]))
    out = b.copy()
    out[take_a] = a[take_a]
    return out


def _minloc(a, b):
    take_a = (a["v"] < b["v"]) | ((a["v"] == b["v"]) & (a["i"] < b["i"]))
    out = b.copy()
    out[take_a] = a[take_a]
    return out


def _land(a, b):
    return ((a != 0) & (b != 0)).astype(b.dtype)


def _lor(a, b):
    return ((a != 0) | (b != 0)).astype(b.dtype)


def _lxor(a, b):
    return ((a != 0) ^ (b != 0)).astype(b.dtype)


MAX = Op("MPI_MAX", np.maximum, "max", complex_ok=False)
MIN = Op("MPI_MIN", np.minimum, "min", complex_ok=False)
SUM = Op("MPI_SUM", np.add, "add", complex_ok=True)
PROD = Op("MPI_PROD", np.multiply, "mul", complex_ok=True)
LAND = Op("MPI_LAND", _land, "and", float_ok=False)
BAND = Op("MPI_BAND", np.bitwise_and, "and", float_ok=False)
LOR = Op("MPI_LOR", _lor, "or", float_ok=False)
BOR = Op("MPI_BOR", np.bitwise_or, "or", float_ok=False)
LXOR = Op("MPI_LXOR", _lxor, "xor", float_ok=False)
BXOR = Op("MPI_BXOR", np.bitwise_xor, "xor", float_ok=False)
MAXLOC = Op("MPI_MAXLOC", None, None, pair_fn=_maxloc,
            float_ok=False, int_ok=False, logical_ok=False)
MINLOC = Op("MPI_MINLOC", None, None, pair_fn=_minloc,
            float_ok=False, int_ok=False, logical_ok=False)
# REPLACE/NO_OP are data-movement ops: legal on every datatype incl.
# pair types (MPI_Accumulate with MPI_REPLACE on MPI_DOUBLE_INT is valid)
REPLACE = Op("MPI_REPLACE", lambda a, b: a.copy(), None, commute=False,
             complex_ok=True, pair_fn=lambda a, b: a.copy())
NO_OP = Op("MPI_NO_OP", lambda a, b: b, None, complex_ok=True,
           pair_fn=lambda a, b: b)

OP_NULL = Op("MPI_OP_NULL", None, None)

PREDEFINED: Dict[str, Op] = {
    op.name: op for op in (MAX, MIN, SUM, PROD, LAND, BAND, LOR, BOR,
                           LXOR, BXOR, MAXLOC, MINLOC, REPLACE, NO_OP)
}


def create(user_fn: Callable, commute: bool) -> Op:
    """MPI_Op_create: user_fn(invec, inoutvec, datatype) -> None,
    mutating inoutvec in place (matching the C callback shape)."""
    def np_fn(a: np.ndarray, b: np.ndarray) -> np.ndarray:
        out = b.copy()
        user_fn(a, out, None)
        return out

    # monotonic name, never an id(): ids recycle after gc, and op.name
    # is the stable identity caches key on (coll/seg._nat_codes)
    op = Op(f"MPI_USER_{next(_user_op_ids)}", np_fn, None,
            commute=commute)
    op.is_user = True
    return op


# jax elementwise forms, resolved lazily so host-only paths never
# import jax.  Used by coll/tpu and coll/hbm to fuse the reduction
# into the compiled collective.
def jax_binary(op: Op):
    import jax.numpy as jnp

    table = {
        "MPI_MAX": jnp.maximum,
        "MPI_MIN": jnp.minimum,
        "MPI_SUM": jnp.add,
        "MPI_PROD": jnp.multiply,
        "MPI_LAND": lambda a, b: ((a != 0) & (b != 0)).astype(b.dtype),
        "MPI_BAND": jnp.bitwise_and,
        "MPI_LOR": lambda a, b: ((a != 0) | (b != 0)).astype(b.dtype),
        "MPI_BOR": jnp.bitwise_or,
        "MPI_LXOR": lambda a, b: ((a != 0) ^ (b != 0)).astype(b.dtype),
        "MPI_BXOR": jnp.bitwise_xor,
    }
    return table.get(op.name)
