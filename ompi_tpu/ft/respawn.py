"""Self-healing respawn: in-job rank replacement with buddy restore.

The third recovery tier (docs/DESIGN.md §11).  PR 4's ULFM layer stops
a dead rank from hanging the job and offers ``Comm.shrink`` — but a
fixed pod shape can't shrink: the mesh IS the workload.  This module
closes the loop the way ULFM's spawn-based recovery does (Bland et
al.) fused with SCR-style buddy checkpointing (cr/buddy): the dead
rank is REPLACED under its original world rank, survivors un-fail it,
and everyone resumes at full size from the newest in-memory snapshot.

The flow, per failure (``errmgr_base_policy = respawn``):

  1. **detect** — exactly PR 4: the death becomes ULFM failure records
     on every survivor; parked ops drain with ``ERR_PROC_FAILED``.
  2. **respawn** — the launch plane brings a replacement up under the
     SAME world rank at a bumped recovery epoch: mpirun's supervision
     loop relaunches the dead process with ``TPUMPI_RESPAWN=1`` +
     ``TPUMPI_FT_EPOCH=<E>`` (process jobs); ``testing.run_ranks``'s
     driver starts a fresh rank-thread (thread worlds).
  3. **rejoin** — survivors and the newcomer call :func:`rejoin` on
     their full-world communicator.  Built on the ULFM put-once store:
     the lowest-ranked survivor publishes the decision (failed set +
     a cid from the epoch's band, see
     ``communicator.EPOCH_CID_STRIDE``); survivors un-fail the
     replaced ranks, clear per-peer pml sequence state
     (``PmlOb1.ft_reset_peer``), drop mesh-keyed compile-cache entries
     (``CompiledLRU.drop_mesh``/``drop_device``), and meet the
     newcomer's init fences; the call returns a full-world
     communicator with an epoch-tagged cid.
  4. **restore** — the application calls ``buddy.restore(newcomm)``:
     the newcomer pulls its predecessor's checkpoint from a partner
     rank, every rank rolls back to the same sequence, and the run
     continues byte-identical to a fault-free run from that snapshot.

Epoch hygiene: completed epochs purge their consumed agreement
tickets (``ulfm.purge_tickets``); failure notes stay, epoch-tagged, so
late watchers filter instead of replaying recovered deaths.

Limitations (documented, enforced by the tests' structure): failures
are handled one rejoin at a time — a second rank must not die before
the previous recovery completes (mpirun's epoch counter and the
rejoin's epoch counter advance per failure event and must agree);
hybrid (HybridRTE) jobs take the process-job path best-effort.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Set

from ompi_tpu import errhandler as _eh
from ompi_tpu import obs as _obs
from ompi_tpu import trace as _trace
from ompi_tpu.ft import ulfm as _ulfm
from ompi_tpu.mca.params import registry

_timeout_var = registry.register(
    "ft", "respawn", "timeout", 60.0, float,
    help="Deadline (s) for the respawn rejoin protocol: decision "
         "agreement, survivor clearing, and the replacement's arrival "
         "at the epoch fences")

_pv_respawned = registry.register_pvar(
    "respawn", "", "ranks_respawned",
    help="Ranks this rank has seen replaced in-job (decided failed "
         "set sizes, summed over rejoins)")
_pv_rejoins = registry.register_pvar(
    "respawn", "", "rejoins_completed",
    help="Respawn rejoin protocols this rank completed")
_pv_rejoin_us = registry.register_pvar(
    "respawn", "", "rejoin_us", var_class="highwatermark",
    help="Slowest rejoin on this rank: decision + un-fail + pml/"
         "cache hygiene + epoch fences + new communicator (us)")


def joining(state) -> bool:
    """Is this rank a respawned replacement that has not yet rejoined?
    (Applications branch on this right after init: a joining rank goes
    straight to rejoin + buddy.restore instead of starting fresh.)"""
    return bool(getattr(state, "respawn_joining", False))


def epoch_cid_floor(cid_band: int, epoch: int) -> int:
    """The cid-space floor of ``(session band, recovery epoch)`` —
    the one banding formula both consumers of the epoch machinery
    share.  Rejoin mints its post-recovery communicator cids here;
    the DVM pool-resize path pre-sets ``state.respawn_epoch`` so
    sessions admitted after a resize spawn their derived comms into
    the next epoch band (docs/DESIGN.md §17), and their floor must
    agree with what a later in-session rejoin would compute."""
    from ompi_tpu.comm.communicator import (EPOCH_CID_STRIDE,
                                            MAX_RESPAWN_EPOCHS,
                                            SESSION_CID_STRIDE)
    return (cid_band * SESSION_CID_STRIDE
            + (epoch % MAX_RESPAWN_EPOCHS) * EPOCH_CID_STRIDE)


def _dbg(state, msg: str) -> None:
    if os.environ.get("FT_DEBUG"):
        import sys
        print(f"[respawn r{state.rank}] {msg}", file=sys.stderr,
              flush=True)


def _wait_store(store, key, comm, deadline, what: str):
    """Poll the put-once store for ``key`` while ticking progress."""
    while True:
        v = store.try_get(key)
        if v is not None:
            return v
        if time.monotonic() > deadline:
            raise _eh.MPIException(
                _eh.ERR_OTHER,
                f"respawn rejoin timed out waiting for {what} "
                f"(tune ft_respawn_timeout)")
        _ulfm._tick(comm)


def _epoch_rewire(state, epoch: int) -> None:
    """Survivor-side epoch reset for PROCESS jobs — the ft.recover
    sequence with the respawn epoch: epoch-scoped jobid/modex
    namespaces, transport + pml reset, re-modex, and the two fences
    that pair with the replacement's init fences (its launch env
    carries TPUMPI_FT_EPOCH=epoch, so it fences under the same
    epoch-scoped keys with a reset fence counter)."""
    rte = state.rte
    state.ft_epoch = epoch
    base_jobid = getattr(rte, "jobid_base", None) or rte.jobid
    rte.jobid_base = base_jobid
    rte.jobid = f"{base_jobid}:e{epoch}"
    rte._fence_count = 0
    rte.modex_epoch = epoch

    keep = []
    for m in state.btls:
        ft = getattr(m, "ft_reset", None)
        if ft is not None:
            if ft(epoch):
                keep.append(m)
        else:
            keep.append(m)
    state.btls = keep

    state.pml.ft_reset()
    eng = getattr(state, "_tpu_rndv", None)
    if eng is not None:
        eng.ft_reset()

    if state.device is not None:
        rte.modex_put("device_id", int(state.device.id))
    rte.modex_put("node_id", getattr(rte, "node_id", 0))
    rte.modex_put("cores", os.cpu_count() or 1)
    if getattr(state, "_seg_modex_done", False):
        rte.modex_put("seg_session", rte.session_dir)
    _dbg(state, f"modex re-published; entering epoch {epoch} fence 1")
    rte.fence()

    from ompi_tpu.btl import base as btl_base
    endpoints = btl_base.wire_endpoints(state, state.btls)
    state.pml.add_procs(endpoints)
    _dbg(state, "endpoints rewired; entering epoch fence 2")
    rte.fence()


def rejoin(comm, name: str = ""):
    """Collective (survivors + replacement, over the full world):
    agree on the replaced ranks, un-fail them, rewire, and return a
    full-world communicator with a fresh epoch-band cid.  Survivors
    call this after catching ``ERR_PROC_FAILED``; a replacement rank
    (``respawn.joining(state)``) calls it right after init."""
    from ompi_tpu.comm.communicator import (
        EPOCH_CID_STRIDE, MAX_RESPAWN_EPOCHS, Communicator, Group)

    state = comm.state
    u = _ulfm._require(comm)
    if len(comm.group) != state.size:
        raise ValueError(
            "respawn.rejoin must run on a full-world-size communicator")
    state.progress.interrupt = None  # disarm: rejoin must not re-raise
    # drop any in-flight filesystem checkpoint epoch torn: it was begun
    # with the dead ranks and can never commit (the manifest gather
    # would wait on them forever); the previous committed epoch is
    # intact by two-phase construction, so the restore ladder
    # (ckpt.restore — buddy, then filesystem replay) still has its
    # newest durable state
    from ompi_tpu.cr import ckpt as _ckpt
    _ckpt.ft_abort(state)
    store = _ulfm._store(state)
    am_joining = joining(state)
    epoch = state.respawn_epoch + 1
    if epoch >= MAX_RESPAWN_EPOCHS:
        # the epoch dimension of the banded cid space is exhausted: a
        # further band would spill into the NEXT session's cid range
        # (see SESSION_CID_STRIDE) and break pool-wide cid uniqueness
        raise _eh.MPIException(
            _eh.ERR_OTHER,
            f"respawn epoch limit reached ({MAX_RESPAWN_EPOCHS}); "
            "restart the job instead of recovering in place")
    base = ("respawn", epoch)
    deadline = time.monotonic() + max(1.0, _timeout_var.value)
    t0 = time.perf_counter()
    u.poll()
    _dbg(state, f"rejoin epoch {epoch} "
                f"({'joining' if am_joining else 'survivor'})")

    if am_joining:
        # the decision predates this process's ability to run user
        # code (thread drivers start the replacement after it lands;
        # a respawned process's init fences pair with survivor fences
        # issued after it) — but poll defensively
        d = _wait_store(store, base + ("d",), comm, deadline,
                        f"epoch {epoch} decision")
    else:
        # shrink-shaped two-phase agreement on the failed set: each
        # survivor contributes its view put-once; the lowest-ranked
        # LIVE member (the replacement's rank is still in `failed`
        # here, so it can never lead) publishes the union + the cid
        store.put_once(base + ("c", comm.rank),
                       sorted(u.failed.intersection(comm.group)))
        while True:
            d = store.try_get(base + ("d",))
            if d is not None:
                break
            u.poll()
            live = [r for r in range(comm.size)
                    if comm.group[r] not in u.failed]
            if live and live[0] == comm.rank:
                union: Set[int] = set(
                    u.failed.intersection(comm.group))
                complete = True
                for r in range(comm.size):
                    v = store.try_get(base + ("c", r))
                    if v is not None:
                        union.update(int(x) for x in v)
                    elif comm.group[r] not in u.failed:
                        complete = False
                        break
                if complete and union:
                    store.put_once(base + ("d",), {
                        "failed": sorted(union),
                        # session band first: a recovery inside a
                        # DVM-resident session must stay inside that
                        # session's cid range (band 0 for plain jobs)
                        "cid": epoch_cid_floor(state.cid_band, epoch)
                        + store.next_cid() % EPOCH_CID_STRIDE})
                    continue
            if time.monotonic() > deadline:
                raise _eh.MPIException(
                    _eh.ERR_OTHER,
                    f"respawn rejoin decision timed out on "
                    f"{comm.name or comm.cid}")
            _ulfm._tick(comm)

    decided: Set[int] = set(int(x) for x in d["failed"])
    survivors: List[int] = [g for g in comm.group if g not in decided]
    world = getattr(state.rte, "world", None)
    kv = getattr(state.rte, "kv", None)

    # the dead incarnations' device ids, captured from the thread
    # world BEFORE the replacements overwrite their slots (process
    # jobs never share compiled executables across rank processes,
    # so there is nothing to drop there)
    dead_devs: List[int] = []
    if world is not None and hasattr(world, "states"):
        for g in sorted(decided):
            st = (world.states[g]
                  if 0 <= g < len(world.states) else None)
            dev = getattr(st, "device", None)
            if dev is not None:
                dead_devs.append(int(dev.id))

    if not am_joining:
        # un-fail: the decided ranks are being replaced in place.
        # World bookkeeping under the fence cv — a concurrent
        # ulfm_fence recomputes its quorum on every wake and must see
        # add/discard atomically
        for g in sorted(decided):
            u.unfail(g)
        if world is not None and hasattr(world, "ulfm_failed"):
            cv = getattr(world, "_uf_cv", None)
            if cv is not None:
                with cv:
                    for g in decided:
                        world.ulfm_failed.discard(g)
                    cv.notify_all()
            else:
                for g in decided:
                    world.ulfm_failed.discard(g)
        # per-peer pml sequence reset BEFORE the replacement can send
        # anything: its seq-0 traffic must match, not park behind the
        # predecessor's counters (process jobs do a full ft_reset in
        # the rewire below; this narrower reset is the thread path's)
        state.pml.ft_reset_peer(decided, state.comms)
        # put-once "cleared" barrier: the replacement may only start
        # (thread driver) / pass its init fences (process job) once
        # EVERY survivor has un-failed it — otherwise a straggler's
        # stale quorum strands a fence generation
        store.put_once(base + ("cleared", comm.rank), True)
        for r in range(comm.size):
            if comm.group[r] in decided or r == comm.rank:
                continue
            _wait_store(store, base + ("cleared", r), comm, deadline,
                        f"rank {r} to clear epoch {epoch}")
        _dbg(state, "all survivors cleared")

        if kv is not None:
            # process job: full epoch rewire, fences pairing with the
            # replacement's TPUMPI_FT_EPOCH init fences
            _epoch_rewire(state, epoch)
        elif world is not None:
            # thread world: the inproc btl resolves peers through
            # world.states dynamically — no transport rewire.  Two
            # bare fences pair with the replacement's two init fences
            # (ulfm_fence is an anonymous generation barrier at full
            # quorum again now that ulfm_failed is empty)
            state.rte.fence()
            state.rte.fence()
        _dbg(state, "epoch fences passed")

    # hygiene on both sides: caches keyed on the old incarnation's
    # group/mesh must not survive into the epoch (the replacement's
    # fresh state has none — the calls are no-ops there)
    for c in list(state.comms.values()):
        if c is None or c is comm:
            continue
        if decided.intersection(c.group):
            _ulfm._invalidate(c)
    _ulfm._invalidate(comm)
    if dead_devs:
        try:
            from ompi_tpu.coll import device as _dev
            for did in dead_devs:
                _dev.compile_cache.drop_device(did)
        except Exception:  # noqa: BLE001 — cache hygiene, never fatal
            pass
    # epoch rollover: consumed agreement/shrink tickets are garbage
    # now (leader-only — one purge per epoch suffices)
    if survivors and state.rank == survivors[0]:
        _ulfm.purge_tickets(state)

    state.respawn_epoch = epoch
    state.respawn_joining = False

    new = Communicator(state, int(d["cid"]), Group(list(comm.group)),
                       name=name or f"world-e{epoch}")
    new.errhandler = comm.errhandler
    dur_us = int((time.perf_counter() - t0) * 1e6)
    _pv_respawned.add(len(decided))
    _pv_rejoins.add(1)
    _pv_rejoin_us.update_max(dur_us)
    _trace.instant_state(state, "respawn_rejoin", "ft",
                         epoch=epoch, cid=new.cid,
                         replaced=len(decided), us=dur_us)
    _obs.record_event(_obs.EV_RESPAWN, epoch, len(decided), dur_us,
                      rank=state.rank)
    _dbg(state, f"rejoined: cid {new.cid}, replaced {sorted(decided)}")
    return new


# -- thread-world driver support (testing.run_ranks(respawn=True)) ----------


def thread_decision(world, epoch: int, timeout: float = 60.0) -> Dict:
    """Driver-side wait (the inproc analog of mpirun's supervision
    loop): block until epoch's rejoin decision is published AND every
    survivor has written its "cleared" mark — only then may the
    replacement thread start, or its init fences could pair against a
    survivor still counting the dead rank in its quorum."""
    deadline = time.monotonic() + timeout
    while True:
        with world.shared_lock:
            d = world.shared.get(("respawn", epoch, "d"))
            if d is not None:
                decided = set(int(x) for x in d["failed"])
                ok = all(
                    ("respawn", epoch, "cleared", r) in world.shared
                    for r in range(world.size) if r not in decided)
                if ok:
                    return dict(d)
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"respawn driver: epoch {epoch} decision/clearing "
                f"did not complete within {timeout}s")
        time.sleep(0.001)
