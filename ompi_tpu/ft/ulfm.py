"""ULFM-style rank-failure mitigation: detect -> ERR_PROC_FAILED ->
revoke / shrink / agree.

Re-design of the ULFM prototype's run-through-stabilization surface
(ref: the MPI-4 FT proposal's MPIX_Comm_revoke/shrink/agree +
failure_ack, ompi/communicator/ft and the errmgr framework):

* **detect** — a permanently dead rank (ft_inject ``rank_kill``, a
  killed tpud child, tcp reconnect exhaustion, or OOB heartbeat
  silence) becomes a per-rank failure *record* carried job-wide:
  thread-rank worlds deliver it directly to every survivor's
  ``UlfmState``; process-rank jobs append ``ulfm:note:<n>`` records to
  the KV store, consumed by a per-rank watcher thread (the ft.py
  epoch-watcher pattern).  Each ingested failure bumps a monotonic
  local failure epoch.
* **report** — pending and future p2p/collective operations naming a
  failed peer complete with ``ERR_PROC_FAILED`` through
  ``errhandler.dispatch`` instead of hanging: ``pml/ob1`` grows a
  ``ulfm_sweep`` that drains parked requests, and the coll shim /
  device rendezvous abort-check consult ``check_comm`` on entry.
* **mitigate** — ``Comm.revoke()`` poisons a communicator job-wide
  (in-flight ops drain with ``ERR_REVOKED``); ``Comm.agree(flag)``
  runs a fault-tolerant agreement whose decision is published
  put-once, so every survivor returns the SAME flag no matter when
  the killer strikes; ``Comm.shrink()`` returns a survivor
  communicator, rebuilding the device mesh and dropping the
  CompiledLRU entries keyed on the old mesh shape.
* **observe** — detect/revoke/shrink/agree emit trace instants and
  ``ulfm_*`` pvars.

Agreement/shrink run over a *store*, not over p2p: the control plane
must stay usable on a communicator whose data plane is already
revoked or holed.  Thread-rank worlds use the world-shared dict;
process ranks use KV put-once (incr-claim) records.

Documented simplifications vs the reference: an ANY_SOURCE receive
with unacknowledged failures completes with
``ERR_PROC_FAILED_PENDING`` (the reference leaves it pending until
``MPIX_Comm_failure_ack``); rendezvous deposits of a dead generation
are simply abandoned (the shrunk comm gets a fresh rendezvous keyed
on its new cid).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, List, Optional, Set, Tuple

from ompi_tpu import errhandler as _eh
from ompi_tpu import obs as _obs
from ompi_tpu import trace as _trace
from ompi_tpu.mca.params import registry

_enable_var = registry.register(
    "mpi", "ft", "ulfm", True, bool,
    help="Attach the ULFM failure-mitigation layer at MPI_Init "
         "(detect dead ranks, raise MPI_ERR_PROC_FAILED, enable "
         "Comm.revoke/agree/shrink).  Off: permanent failures hang "
         "or abort, the pre-ULFM behavior")
_agree_timeout_var = registry.register(
    "mpi", "ft", "ulfm_agree_timeout", 60.0, float,
    help="Deadline (s) for the agree/shrink decision loop; expiry "
         "raises MPI_ERR_OTHER (survivors unreachable, not dead)")

_pv_failures = registry.register_pvar(
    "ulfm", "", "failures_detected",
    help="Rank failures ingested by this rank's ULFM state")
_pv_revokes = registry.register_pvar(
    "ulfm", "", "revokes",
    help="Communicator revocations ingested by this rank")
_pv_agreements = registry.register_pvar(
    "ulfm", "", "agreements",
    help="Fault-tolerant agreements completed by this rank")
_pv_shrink_us = registry.register_pvar(
    "ulfm", "", "shrink_rebuild_us", var_class="highwatermark",
    help="Slowest Comm.shrink on this rank: survivor agreement + "
         "communicator/mesh rebuild + compile-cache invalidation (us)")


class RankKilled(SystemExit):
    """Injected permanent rank death (ft_inject ``rank_kill``).

    A SystemExit subclass on purpose: it must behave exactly like the
    process dying — ``Communicator._guard`` and ``errhandler.dispatch``
    both re-raise SystemExit untouched, so no error handler can absorb
    the kill."""


# -- per-rank state ---------------------------------------------------------


class UlfmState:
    """One per rank: the failure/revocation view plus the plumbing
    that turns delivered records into drained requests.

    ``active`` flips True on the first delivered record and never
    flips back — the hot-path cost while healthy is one attribute
    fetch and one falsy check (the trace-layer zero-cost contract)."""

    def __init__(self, state) -> None:
        self.state = state
        self.lock = threading.Lock()
        self.failed: Set[int] = set()          # global ranks
        self.acked: Set[int] = set()           # failure_ack'd ranks
        # revoked communicators as (cid, group-tuple): disjoint comms
        # of different processes may share a cid, the group keeps a
        # revoke from poisoning an unrelated communicator
        self.revoked: Set[Tuple[int, Tuple[int, ...]]] = set()
        self.epoch = 0                         # monotonic failure epoch
        self.active = False
        self._dirty = False
        self._seen: Set[tuple] = set()
        self._pending: List[tuple] = []
        # test seam: called at named agreement phases so kill-at-every-
        # phase tests are deterministic instead of timer-raced
        self._agree_test_hook = None

    # -- record delivery (any thread) -----------------------------------
    def deliver(self, rec: tuple) -> None:
        with self.lock:
            if rec in self._seen:
                return
            self._seen.add(rec)
            self._pending.append(rec)
            self._dirty = True
            self.active = True
        self.state.progress.wakeup()

    # -- ingestion (the rank's own thread, via poll) --------------------
    def poll(self) -> int:
        if not self._dirty:
            return 0
        with self.lock:
            pending, self._pending = self._pending, []
            self._dirty = False
        n = 0
        for rec in pending:
            n += self._ingest(rec)
        return n

    def _ingest(self, rec: tuple) -> int:
        if rec[0] == "fail":
            grank = int(rec[1])
            if grank == self.state.rank:
                # a respawned replacement replays the KV note stream
                # and meets its predecessor's death note: its own rank
                # is alive by construction
                return 0
            if (len(rec) > 2 and int(rec[2]) <=
                    getattr(self.state, "respawn_epoch", 0)):
                # epoch-tagged note from a failure the respawn
                # protocol already recovered: ingesting it would
                # re-mark a revived rank dead forever
                return 0
            if grank in self.failed:
                return 0
            self.failed.add(grank)
            self.epoch += 1
            _pv_failures.add(1)
            rte = self.state.rte
            if getattr(rte, "kv", None) is not None:
                # EnvRTE/HybridRTE fences shrink their KV quorum by
                # this set (dead ranks never arrive at a fence)
                rte.ulfm_failed = set(self.failed)
            _trace.instant_state(self.state, "ulfm_detect", "ft",
                                 failed=grank, epoch=self.epoch)
            _obs.record_event(_obs.EV_ULFM_DETECT, grank, self.epoch,
                              rank=self.state.rank)
        elif rec[0] == "revoke":
            key = (int(rec[1]), tuple(rec[2]))
            if key in self.revoked:
                return 0
            self.revoked.add(key)
            _pv_revokes.add(1)
            _trace.instant_state(self.state, "ulfm_revoke", "ft",
                                 cid=key[0])
            _obs.record_event(_obs.EV_ULFM_REVOKE, key[0],
                              rank=self.state.rank)
        else:
            return 0
        self._sweep_pml()
        return 1

    def unfail(self, grank: int) -> None:
        """Respawn rejoin (ft/respawn): ``grank`` has been replaced in
        place — stop treating it as dead.  The delivery dedup for its
        old failure records is cleared too, so a LATER kill of the same
        world rank is detected again (``active`` stays True: the
        entry-check cost is already paid and a re-kill must drain
        instantly)."""
        with self.lock:
            self.failed.discard(grank)
            self.acked.discard(grank)
            self._seen = {
                r for r in self._seen
                if not (r[0] == "fail" and int(r[1]) == grank)}
            self._pending = [
                r for r in self._pending
                if not (r[0] == "fail" and int(r[1]) == grank)]
        rte = self.state.rte
        if getattr(rte, "kv", None) is not None:
            rte.ulfm_failed = set(self.failed)

    def _sweep_pml(self) -> None:
        # reaches PmlOb1 through any monitoring/vprotocol wrapper
        # (both delegate unknown attributes to the wrapped pml)
        sweep = getattr(self.state.pml, "ulfm_sweep", None)
        if sweep is not None:
            sweep(self.failed, self.revoked)

    def _progress_cb(self) -> int:
        return self.poll()

    # -- entry checks (raise, callers route through dispatch) -----------
    def check_comm(self, comm) -> None:
        """Collective-entry check: a revoked comm raises ERR_REVOKED,
        a comm with a failed member raises ERR_PROC_FAILED."""
        if (comm.cid, tuple(comm.group)) in self.revoked:
            raise _eh.MPIException(
                _eh.ERR_REVOKED,
                f"MPI_ERR_REVOKED: communicator {comm.name or comm.cid} "
                f"was revoked")
        dead = self.failed.intersection(comm.group)
        if dead:
            raise _eh.MPIException(
                _eh.ERR_PROC_FAILED,
                f"MPI_ERR_PROC_FAILED: rank(s) "
                f"{sorted(dead)} of {comm.name or comm.cid} failed")

    def check_peer(self, comm, peer: int) -> None:
        """P2P-entry check for an op naming comm-rank ``peer``."""
        if (comm.cid, tuple(comm.group)) in self.revoked:
            raise _eh.MPIException(
                _eh.ERR_REVOKED,
                f"MPI_ERR_REVOKED: communicator {comm.name or comm.cid} "
                f"was revoked")
        if peer >= 0:
            if comm.group[peer] in self.failed:
                raise _eh.MPIException(
                    _eh.ERR_PROC_FAILED,
                    f"MPI_ERR_PROC_FAILED: peer rank {peer} failed")
        else:  # ANY_SOURCE with unacknowledged failures
            pending = (self.failed.intersection(comm.group)
                       - self.acked)
            if pending:
                raise _eh.MPIException(
                    _eh.ERR_PROC_FAILED_PENDING,
                    f"MPI_ERR_PROC_FAILED_PENDING: unacknowledged "
                    f"failed rank(s) {sorted(pending)}")


def attach(state) -> Optional[UlfmState]:
    """Install a UlfmState on ``state`` (before pml selection, so the
    pml can cache the reference) and hook the progress engine."""
    if not _enable_var.value:
        state.ulfm = None
        return None
    u = UlfmState(state)
    state.ulfm = u
    state.progress.register(u._progress_cb)
    return u


# -- failure/revoke publication ---------------------------------------------


def publish_world_failure(world, grank: int) -> None:
    """Thread-rank delivery: mark the rank failed on the world, break
    the fence barrier (survivors fall through to the ULFM fence), and
    deliver the record to every live rank's UlfmState."""
    publish_world_failures(world, (grank,))


def publish_world_failures(world, granks) -> None:
    """Atomic failure-DOMAIN delivery: mark EVERY rank in ``granks``
    failed before any waiter wakes, so a whole-host death surfaces as
    one consistent failure set — survivors of a host kill observe all
    N resident ranks dead at once, never N racing single-rank
    detections with fences recounting quorum between them."""
    fresh = []
    for grank in granks:
        if grank not in world.ulfm_failed:
            fresh.append(int(grank))
        world.ulfm_failed.add(grank)
    if fresh:
        try:
            world.barrier.abort()
        except Exception:  # noqa: BLE001 — barrier may be mid-reset
            pass
    cv = getattr(world, "_uf_cv", None)
    if cv is not None:
        with cv:           # release anyone parked in a ULFM fence
            cv.notify_all()
    for st in list(world.states):  # indexed by rank; remote = None
        u = getattr(st, "ulfm", None)
        if u is not None:
            for grank in granks:
                u.deliver(("fail", int(grank)))


def publish_failure(state, grank: int) -> None:
    """Propagate a suspected-permanent rank failure job-wide: direct
    delivery in thread-rank worlds, a ``ulfm:note:<n>`` KV record for
    process-rank jobs (each rank's watcher thread consumes it)."""
    world = getattr(state.rte, "world", None)
    if world is not None and hasattr(world, "ulfm_failed"):
        publish_world_failure(world, grank)
    kv = getattr(state.rte, "kv", None)
    if kv is not None:
        try:
            n = kv.incr("ulfm:nseq")
            kv.put(f"ulfm:note:{n}", ["fail", int(grank)])
        except (ConnectionError, OSError, RuntimeError):
            pass  # control plane gone: local delivery still drains us
    u = getattr(state, "ulfm", None)
    if u is not None:
        u.deliver(("fail", int(grank)))


def publish_revoke(comm) -> None:
    """MPIX_Comm_revoke: poison ``comm`` job-wide.  Not collective —
    any member may revoke; the notice reaches every rank through the
    same channels failure records ride."""
    state = comm.state
    rec = ("revoke", int(comm.cid), tuple(comm.group))
    world = getattr(state.rte, "world", None)
    if world is not None and hasattr(world, "states"):
        for st in list(world.states):
            u = getattr(st, "ulfm", None)
            if u is not None:
                u.deliver(rec)
    kv = getattr(state.rte, "kv", None)
    if kv is not None:
        try:
            n = kv.incr("ulfm:nseq")
            kv.put(f"ulfm:note:{n}",
                   ["revoke", int(comm.cid), list(comm.group)])
        except (ConnectionError, OSError, RuntimeError):
            pass
    u = getattr(state, "ulfm", None)
    if u is not None:
        u.deliver(rec)
        u.poll()  # the revoker's own parked ops drain immediately


# -- KV watcher (process ranks; the ft.start_watcher pattern) ---------------


def start_watcher(state) -> None:
    """Consume ``ulfm:note:<n>`` records from the KV store on a daemon
    thread with its own KVClient (the shared client is single-threaded
    by contract)."""
    addr = os.environ.get("TPUMPI_KV_ADDR")
    if not addr or getattr(state, "ulfm", None) is None:
        return

    def run() -> None:
        from ompi_tpu.runtime.kvstore import KVClient
        try:
            kv = KVClient(addr)
        except (OSError, RuntimeError):
            return
        n = 0
        while True:
            try:
                rec = kv.get(f"ulfm:note:{n}", timeout=3600.0)
            except (RuntimeError, OSError, TimeoutError):
                if getattr(state, "finalized", False):
                    return
                continue
            n += 1
            u = getattr(state, "ulfm", None)
            if u is None or getattr(state, "finalized", False):
                return
            if rec and rec[0] == "fail":
                # respawn-mode notes carry the recovery epoch the
                # failure opens; _ingest drops stale epochs so note
                # replay after a rejoin cannot re-kill a revived rank
                if len(rec) > 2:
                    u.deliver(("fail", int(rec[1]), int(rec[2])))
                else:
                    u.deliver(("fail", int(rec[1])))
            elif rec and rec[0] == "revoke":
                u.deliver(("revoke", int(rec[1]), tuple(rec[2])))

    threading.Thread(target=run, daemon=True,
                     name=f"ulfm-watcher-{state.rank}").start()


# -- injected kills ---------------------------------------------------------


def arm_rank_kill(state, after_s: float) -> None:
    """ft_inject ``rank_kill``: after ``after_s`` the victim's next
    progress sweep raises RankKilled — out of whatever wait it is
    parked in (the WaitSync spin runs progress, so armed interrupts
    escape blocking calls)."""

    def fire() -> None:
        if getattr(state, "finalized", False):
            return
        _trace.instant_state(state, "ft_inject", "ft",
                             cls="rank_kill", rank=state.rank)
        _obs.record_event(_obs.EV_FT_INJECT, _obs.intern("rank_kill"),
                          _obs.intern("rank"), rank=state.rank)
        # this incarnation can never finalize: let process-wide
        # last-rank accounting (coll.device) stop waiting for it
        state.ulfm_dead = True
        state.progress.interrupt = RankKilled(
            f"ft_inject rank_kill: rank {state.rank}")
        state.progress.wakeup()

    t = threading.Timer(max(0.0, after_s), fire)
    t.daemon = True
    t.start()


def kill_now(state):
    """Deterministic in-line kill for tests/benchmarks: the calling
    rank dies HERE (no timer race)."""
    state.ulfm_dead = True
    raise RankKilled(f"rank {state.rank} killed (ulfm.kill_now)")


# -- the agreement/shrink store ---------------------------------------------


class _InprocStore:
    """Thread-rank backend: the world-shared dict under its lock."""

    def __init__(self, state) -> None:
        self.world = state.rte.world

    def put_once(self, key: tuple, value: Any) -> bool:
        with self.world.shared_lock:
            if key in self.world.shared:
                return False
            self.world.shared[key] = value
            return True

    def try_get(self, key: tuple) -> Any:
        with self.world.shared_lock:
            return self.world.shared.get(key)

    def next_cid(self) -> int:
        # shrink cids live far above next_cid_local's counting range
        with self.world.shared_lock:
            n = self.world.shared.get(("ulfm", "cid"), 4096)
            self.world.shared[("ulfm", "cid")] = n + 1
            return n


class _KvStore:
    """Process-rank backend: KV put-once via incr-claim (the first
    caller's pre-increment is 0 — it owns the write)."""

    def __init__(self, state) -> None:
        self.kv = state.rte.kv

    @staticmethod
    def _k(key: tuple) -> str:
        return "ulfm:" + ":".join(str(p) for p in key)

    def put_once(self, key: tuple, value: Any) -> bool:
        return self.kv.put_once(self._k(key), value)

    def try_get(self, key: tuple) -> Any:
        try:
            return self.kv.get(self._k(key), timeout=0.05)
        except (TimeoutError, RuntimeError):
            return None

    def next_cid(self) -> int:
        return 4096 + self.kv.incr("ulfm:cid")


def _store(state):
    if getattr(state.rte, "kv", None) is not None:
        return _KvStore(state)
    return _InprocStore(state)


def _require(comm) -> UlfmState:
    u = getattr(comm.state, "ulfm", None)
    if u is None:
        raise RuntimeError(
            "ULFM is disabled (--mca mpi_ft_ulfm 0): "
            "revoke/agree/shrink unavailable")
    return u


def _tick(comm) -> None:
    """One decision-loop beat: run progress (armed interrupts — e.g. a
    rank_kill landing mid-agreement — fire here) and yield."""
    comm.state.progress.progress()
    time.sleep(0.0005)


# -- MPIX_Comm_agree --------------------------------------------------------


def agree(comm, flag) -> bool:
    """Fault-tolerant agreement: returns the AND of the contributed
    flags, identical on every survivor regardless of when members die.

    Two-phase over the store: (1) every member publishes its
    contribution put-once; (2) the lowest-ranked *live* member gathers
    the contributions of everyone not known-failed and publishes the
    decision put-once.  A leader dying mid-gather just promotes the
    next survivor; because the decision is put-once, a late write from
    a zombie leader cannot split the outcome."""
    u = _require(comm)
    store = _store(comm.state)
    seq = comm.__dict__.get("_ulfm_agree_seq", 0)
    comm.__dict__["_ulfm_agree_seq"] = seq + 1
    base = ("agree", comm.cid, tuple(comm.group), seq)
    hook = u._agree_test_hook
    u.poll()
    if hook is not None:
        hook("pre_contrib")
    store.put_once(base + ("c", comm.rank), bool(flag))
    if hook is not None:
        hook("post_contrib")
    deadline = time.monotonic() + max(1.0, _agree_timeout_var.value)
    while True:
        d = store.try_get(base + ("d",))
        if d is not None:
            if hook is not None:
                hook("post_decision")
            _pv_agreements.add(1)
            _trace.instant_state(comm.state, "ulfm_agree", "ft",
                                 cid=comm.cid, seq=seq,
                                 flag=bool(d["flag"]))
            _obs.record_event(_obs.EV_ULFM_AGREE, comm.cid, seq,
                              int(bool(d["flag"])),
                              rank=comm.state.rank)
            return bool(d["flag"])
        u.poll()
        live = [r for r in range(comm.size)
                if comm.group[r] not in u.failed]
        if live and live[0] == comm.rank:
            vals: List[bool] = []
            complete = True
            for r in range(comm.size):
                v = store.try_get(base + ("c", r))
                if v is not None:
                    vals.append(bool(v))
                elif comm.group[r] not in u.failed:
                    complete = False
                    break
            if complete:
                if hook is not None:
                    hook("pre_decision")
                store.put_once(base + ("d",), {"flag": all(vals)})
                continue
        if time.monotonic() > deadline:
            raise _eh.MPIException(
                _eh.ERR_OTHER,
                f"ulfm agree timed out on {comm.name or comm.cid}")
        _tick(comm)


# -- MPIX_Comm_shrink -------------------------------------------------------

# per-comm cached plans/verdicts that key on the OLD group/mesh (the
# ft.recover invalidation list + the device/fusion fast-path caches)
_COMM_CACHE_KEYS = (
    "_seg_eligible", "_coll_seg", "_seg_ar_plan", "_hbm_one_device",
    "_hbm_plans", "_device_rv", "_device_abort_check",
    "_oversub_verdict", "_mesh_none", "_mesh", "_fusion_engine",
    "_dev_seq",
    # large-message tier (coll/pipeline + topo): routing thresholds,
    # hierarchy plans and the cart device mesh all key on the old
    # group/mesh — segment state must not leak across shrink/respawn
    # epochs
    "_pipeline_pick", "_hier_eligible", "_hier_plan",
    "_cart_device_mesh",
    # compiled collective plans (DESIGN.md §22): Plan objects hold the
    # old mesh, its sharding and a jitted executable bound to the old
    # device set — stale-mesh executables must never survive an epoch
    "_coll_plans",
    # osc framework: the per-window component verdict keys on the old
    # mesh (device eligibility), so a shrunk comm must re-decide
    "_osc_pick",
)

# the subset safe to purge while a comm stays LIVE: pure routing
# thresholds whose recompute is rank-local (coll/autotune re-resolves
# them online when the calibrate profile moves).  _hier_plan and the
# rendezvous caches are NOT here — their rebuild is collective
# (subcomm construction) and may only happen at epoch boundaries.
# _coll_plans qualifies: a Plan rebuild is rank-local (the jitted
# executable comes out of the process-wide CompiledLRU) and keys on
# calibrated segment size, which is exactly what an autotune fold moves
SELECTION_CACHE_KEYS = ("_pipeline_pick", "_osc_pick", "_coll_plans")


def purge_comm_caches(comm, keys=_COMM_CACHE_KEYS) -> None:
    """Drop per-comm cached plans/verdicts.  The full key list is the
    shrink/respawn epoch boundary; callers on a live comm must pass
    SELECTION_CACHE_KEYS (see above)."""
    for k in keys:
        comm.__dict__.pop(k, None)


def _invalidate(comm) -> None:
    """Drop everything keyed on the dying comm's group/mesh: cached
    per-comm plans, the device rendezvous, and the CompiledLRU entries
    compiled against the old mesh shape (a shrunk world re-keys on the
    survivor device list — stale executables would never be hit again
    but would squat in the bounded cache)."""
    mesh = comm.__dict__.get("_mesh")
    if mesh is not None:
        try:
            from ompi_tpu.coll import device
            dev_key = tuple(d.id for d in mesh.devices.reshape(-1))
            device.compile_cache.drop_mesh(dev_key)
        except Exception:  # noqa: BLE001 — cache hygiene, never fatal
            pass
    purge_comm_caches(comm)
    world = getattr(comm.state.rte, "world", None)
    if world is not None and hasattr(world, "shared"):
        group = tuple(comm.group)
        with world.shared_lock:
            world.shared.pop(("coll_rv", comm.cid, group), None)
            # device-osc shard tables of windows on the dying comm:
            # the shards belong to the old mesh/group and must not be
            # resurrected by a cid reuse after recovery
            dead = [k for k in world.shared
                    if isinstance(k, tuple) and k and
                    k[0] == "osc_devwin" and k[1] == comm.cid and
                    k[2] == group]
            for k in dead:
                world.shared.pop(k, None)


# -- store hygiene ----------------------------------------------------------

# first elements of world.shared tuple keys owned by the ULFM/respawn
# control plane (the KV spellings all live under the "ulfm:" prefix)
_STORE_KEY_HEADS = ("agree", "shrink", "respawn", "ulfm")


def purge_tickets(state) -> None:
    """Epoch-rollover hygiene: drop consumed agreement/shrink tickets
    (contributions, decisions, and their put-once claim counters).
    Failure notes are deliberately kept — a late-starting watcher
    replays the note stream from n=0 and relies on the epoch filter,
    not on deletion, to skip recovered failures."""
    world = getattr(state.rte, "world", None)
    if world is not None and hasattr(world, "shared"):
        with world.shared_lock:
            for k in [k for k in world.shared
                      if isinstance(k, tuple) and k
                      and k[0] in ("agree", "shrink")]:
                del world.shared[k]
    kv = getattr(state.rte, "kv", None)
    if kv is not None:
        try:
            kv.purge("ulfm:agree:")
            kv.purge("ulfm:shrink:")
        except (ConnectionError, OSError, RuntimeError):
            pass


def purge_store(state) -> None:
    """Finalize hygiene (stale-note satellite): remove every ULFM
    record this job wrote — failure notes, the note sequence counter,
    agreement/shrink/respawn tickets and their claim counters — so a
    looped world (pytest re-entry, warm launcher pools) starts with a
    clean failure plane instead of replaying last run's deaths."""
    world = getattr(state.rte, "world", None)
    if world is not None and hasattr(world, "shared"):
        with world.shared_lock:
            for k in [k for k in world.shared
                      if isinstance(k, tuple) and k
                      and k[0] in _STORE_KEY_HEADS]:
                del world.shared[k]
    kv = getattr(state.rte, "kv", None)
    if kv is not None:
        try:
            kv.purge("ulfm:")
        except (ConnectionError, OSError, RuntimeError):
            pass


def shrink(comm, name: str = ""):
    """MPIX_Comm_shrink: agree on the failed set, build the survivor
    communicator (fresh cid from the store so every member lands on
    the same one), and invalidate what the old mesh shape cached."""
    u = _require(comm)
    store = _store(comm.state)
    t0 = time.perf_counter()
    u.poll()
    seq = comm.__dict__.get("_ulfm_shrink_seq", 0)
    comm.__dict__["_ulfm_shrink_seq"] = seq + 1
    base = ("shrink", comm.cid, tuple(comm.group), seq)
    store.put_once(base + ("c", comm.rank),
                   sorted(u.failed.intersection(comm.group)))
    deadline = time.monotonic() + max(1.0, _agree_timeout_var.value)
    while True:
        d = store.try_get(base + ("d",))
        if d is not None:
            break
        u.poll()
        live = [r for r in range(comm.size)
                if comm.group[r] not in u.failed]
        if live and live[0] == comm.rank:
            union: Set[int] = set(u.failed.intersection(comm.group))
            complete = True
            for r in range(comm.size):
                v = store.try_get(base + ("c", r))
                if v is not None:
                    union.update(int(x) for x in v)
                elif comm.group[r] not in u.failed:
                    complete = False
                    break
            if complete:
                store.put_once(base + ("d",), {
                    "failed": sorted(union), "cid": store.next_cid()})
                continue
        if time.monotonic() > deadline:
            raise _eh.MPIException(
                _eh.ERR_OTHER,
                f"ulfm shrink timed out on {comm.name or comm.cid}")
        _tick(comm)
    decided = set(int(x) for x in d["failed"])
    survivors = [g for g in comm.group if g not in decided]
    # adopt the decided view: a member that learned of a failure only
    # through the decision must treat that rank as failed from now on
    for g in decided:
        u.deliver(("fail", int(g)))
    u.poll()
    from ompi_tpu.comm.communicator import Communicator, Group
    new = Communicator(comm.state, int(d["cid"]), Group(survivors),
                       name=name or f"{comm.name or 'comm'}-shrink")
    new.errhandler = comm.errhandler
    _invalidate(comm)
    dur_us = int((time.perf_counter() - t0) * 1e6)
    _pv_shrink_us.update_max(dur_us)
    _trace.instant_state(comm.state, "ulfm_shrink", "ft",
                         cid=comm.cid, new_cid=new.cid,
                         survivors=len(survivors), us=dur_us)
    _obs.record_event(_obs.EV_ULFM_SHRINK, comm.cid, new.cid,
                      len(survivors), dur_us, rank=comm.state.rank)
    return new
