"""User-level failure mitigation (ULFM / MPI-4 FT proposal analog).

The forward-recovery complement to ``runtime/ft.py``'s whole-job
rollback: permanent rank death is detected, surfaced to the
application as ``MPI_ERR_PROC_FAILED``, and mitigated in place with
``Comm.revoke()`` / ``Comm.agree()`` / ``Comm.shrink()`` so the job
continues on the survivors (ref: ompi/communicator/ft and the
MPIX_Comm_* surface of the ULFM prototype).

``ft/respawn.py`` adds the third tier: instead of shrinking around a
dead rank, mpirun (or the thread-world driver) launches a replacement
that re-registers under the same world rank, restores its state from
a buddy checkpoint (``cr/buddy.py``) and rejoins at full size.
"""

from ompi_tpu.ft.ulfm import (  # noqa: F401
    RankKilled,
    UlfmState,
    agree,
    arm_rank_kill,
    attach,
    kill_now,
    publish_failure,
    publish_revoke,
    publish_world_failure,
    purge_store,
    purge_tickets,
    shrink,
    start_watcher,
)
from ompi_tpu.ft import respawn  # noqa: F401
