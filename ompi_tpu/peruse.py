"""PERUSE-analog: request-lifecycle event introspection.

Re-design of the reference's PERUSE layer (ref: ompi/peruse/peruse.h
— event handles like PERUSE_COMM_REQ_ACTIVATE /
PERUSE_COMM_REQ_MATCH_UNEX / PERUSE_COMM_REQ_COMPLETE registered per
communicator, fired from the pml).  Differences: events are plain
strings, subscriptions are process-wide callables, and the pml pays
a single module-flag check when nobody subscribed (the hot path must
not regress — same discipline as the reference compiling PERUSE out
by default).

Events fired by pml/ob1:

    req_activate   — a send/recv request entered the pml
                     (kind='send'|'recv', cid, peer, tag, bytes)
    req_match      — an incoming message matched a posted receive
    req_match_unex — an incoming message was queued unexpected
    req_complete   — a request completed (kind, bytes)

Events fired by the shared collective hooks (ompi_tpu/trace — the
span tracer and PERUSE observe the SAME instrumentation points):

    coll_begin     — a blocking collective entered its merged-vtable
                     shim (cid, coll, seq)
    coll_end       — that collective returned (cid, coll, seq)
    nbc_activate   — a nonblocking-collective schedule was activated
                     (cid, coll, seq)
    nbc_complete   — that schedule finished its last round
                     (cid, coll, seq)

Usage:

    from ompi_tpu import peruse
    peruse.subscribe("req_complete", lambda ev, **kw: stats.add(kw))
    ...
    peruse.unsubscribe_all()
"""

from __future__ import annotations

from typing import Callable, Dict, List

EVENTS = ("req_activate", "req_match", "req_match_unex",
          "req_complete",
          # collective / nonblocking-collective lifecycle (fired by
          # the shared hooks in ompi_tpu/trace)
          "coll_begin", "coll_end", "nbc_activate", "nbc_complete")

# the pml checks this single flag before building event payloads
enabled = False

_subs: Dict[str, List[Callable]] = {e: [] for e in EVENTS}


def subscribe(event: str, cb: Callable) -> None:
    """Register ``cb(event, **info)`` for ``event`` (must be in
    EVENTS — the PERUSE_Event_comm_register analog)."""
    global enabled
    if event not in _subs:
        raise ValueError(f"unknown peruse event {event!r}; "
                         f"one of {EVENTS}")
    _subs[event].append(cb)
    enabled = True


def unsubscribe(event: str, cb: Callable) -> None:
    global enabled
    try:
        _subs[event].remove(cb)
    except (KeyError, ValueError):
        pass
    enabled = any(v for v in _subs.values())


def unsubscribe_all() -> None:
    global enabled
    for v in _subs.values():
        v.clear()
    enabled = False


def fire(event: str, **info) -> None:
    """Invoked by the pml only when ``enabled`` (subscriber errors
    propagate: an observability hook that raises is a test bug worth
    failing loudly, never a silently-dropped event)."""
    for cb in _subs.get(event, ()):
        cb(event, **info)
