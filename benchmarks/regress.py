"""regress: the perf-regression sentry over the BENCH_r* history.

``bench.py --regress`` is pure file analysis — it runs NO probes.  It
loads the per-round driver records (``BENCH_r*.json``: the parsed
headline metric plus the captured stdout tail) and the full-sweep
``BENCH_DETAIL.json``, compares the newest round against the history
with **noise-aware tolerances**, appends a trajectory row so probe
metrics become comparable round over round, and exits nonzero when a
metric regressed beyond what the history's own noise can explain.

Noise model: for each metric the baseline is the MEDIAN of the prior
samples and the tolerance is::

    tol = max(base_tol, NOISE_K * MAD / median)

where MAD is the median absolute deviation of the prior samples — a
flat history (74.4, 74.5, 74.3) keeps the tight base tolerance and a
20% drop trips the sentry; a history whose own scatter dwarfs any
plausible regression (74 -> 10 -> 12 across reworked sweeps) widens
the band automatically, because claiming a regression noisier than
the noise floor would be a lie.  Lower-is-better metrics (overhead
percentages) use the same model with the comparison flipped and an
absolute floor (percentages near zero make relative bands useless).

Rounds whose metric is missing or nonpositive (a failed sweep) are
excluded from baselines — a crashed round must not poison the noise
estimate OR hide as a fake regression.

``--dry`` evaluates everything but appends nothing: the tier-1 smoke
validates history parsing without mutating BENCH_DETAIL.json.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

#: scale factor on MAD when widening a tolerance band
NOISE_K = 3.0

#: cap on retained trajectory rows (oldest dropped first)
TRAJECTORY_CAP = 100

#: metric -> (direction, base tolerance).  Direction "higher" metrics
#: regress by dropping (relative tolerance); "lower" metrics regress
#: by rising (absolute tolerance, percentage points).
TOLERANCES: Dict[str, Tuple[str, float]] = {
    "headline_busbw_gbs": ("higher", 0.10),
    "pipeline_fused_busbw_gbs": ("higher", 0.25),
    "pipeline_segring_busbw_gbs": ("higher", 0.25),
    # compiled-plan sentries (ISSUE 17): the best segmented busbw
    # anywhere on the sweep, and segmented-vs-fused at 256 KiB — the
    # size where plan orchestration savings dominate, so a plan-path
    # regression (per-op rebuilds, a lost zero-copy pack) shows up
    # here before it shows in the 8 MiB headline
    "seg_best_busbw_gbs": ("higher", 0.25),
    "seg_vs_fused_ratio_256k": ("higher", 0.25),
    "trace_overhead_pct": ("lower", 2.0),
    "obs_overhead_pct": ("lower", 2.0),
    "dispatch_const_us": ("lower", 50.0),
    # one-sided busbw at the 1 MiB acceptance tier (ISSUE 14): same
    # noise band as the pipeline curves — thread-rank timing on a
    # shared host core is jittery, real drops are way past 25%
    "rma_device_put_busbw_gbs": ("higher", 0.25),
    "rma_device_get_busbw_gbs": ("higher", 0.25),
    "rma_pt2pt_put_busbw_gbs": ("higher", 0.25),
    # control-plane recovery MTTRs (ISSUE 15): "lower" metrics use an
    # ABSOLUTE band in the metric's own unit (ms here).  Warm KV
    # failover is detect+rotate+reconnect on localhost (~2 ms typical)
    # but the client's backoff ladder makes the tail jumpy — a real
    # regression (e.g. a lost sleepless-retry path) lands in seconds.
    # The DVM restart MTTR is dominated by the respawned server's
    # interpreter + import cold start (~600 ms), so its band is wide.
    "kv_failover_mttr_ms": ("lower", 150.0),
    "dvm_restart_mttr_ms": ("lower", 1500.0),
    # whole-host recovery (ISSUE 16): daemon SIGKILL -> silence
    # detection -> domain respawn.  Dominated by the probe's 3-beat
    # grace horizon (~600 ms at the probe's 0.2 s beat), so the band
    # absorbs a missed beat or two; a real regression (a detector
    # stuck on the default horizon, a respawn replaying whole
    # journals) lands in multiple seconds.
    "host_kill_mttr_ms": ("lower", 1500.0),
    # reqtrace sentries (ISSUE 18): queue-wait p99 of the probe's
    # 4-session Poisson workload (µs — admission scheduling drift
    # shows up here before goodput moves) and the hang doctor's
    # threshold-to-capture latency (ms — contractually within
    # 2 x obs_watchdog_ms; the band absorbs watchdog-tick phase)
    "queue_wait_p99_us": ("lower", 100000.0),
    "doctor_mttd_ms": ("lower", 200.0),
    # gray-failure plane sentries (ISSUE 19): slow-start -> quarantine
    # applied (budget 4x the probe's 300 ms health tick; the band
    # absorbs a tick or two of phase), mitigated-vs-unmitigated
    # goodput (relative — a broken drain/re-placement halves it), and
    # false quarantines on the healthy arm, which must stay EXACTLY
    # zero (the 0.5 absolute band means any nonzero count regresses)
    "grayfail_mttm_ms": ("lower", 2000.0),
    "grayfail_goodput_ratio": ("higher", 0.25),
    "false_quarantines": ("lower", 0.5),
    # sdc-integrity plane sentries (ISSUE 20): the detection rate on
    # the flip-every-op arm must stay EXACTLY 1.0 (the 1% relative
    # band means a single missed flip out of the probe's 40 regresses),
    # false positives on the clean armed arm must stay EXACTLY zero
    # (0.5 absolute band — same contract as false_quarantines), and
    # conviction-to-quarantine latency is bounded by a couple of
    # effective health sweeps (the band absorbs sweep phase; a real
    # regression — a lost decisive-signal path making sdc wait out the
    # beat-score hysteresis — lands in multiples of the budget)
    "sdc_detection_rate": ("higher", 0.01),
    "sdc_false_positives": ("lower", 0.5),
    "sdc_mttq_ms": ("lower", 1000.0),
    # the armed integrity plane's steady-state overhead rides the
    # trace_overhead budget model: an absolute percentage-point band
    "integrity_overhead_pct": ("lower", 2.0),
}


def _json_lines(text: str):
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                yield json.loads(line)
            except ValueError:
                continue


#: sanity bound on the device sweep's measured d2h read constant.  An
#: idle box reads 4 bytes in tens of microseconds; ~100 ms means the
#: quiet gate failed (polling peers / tunnel threads contaminated the
#: probe — the r4 failure mode) and the constant-subtraction then
#: FABRICATES busbw.  Rounds in that state are not comparable.
READ_CONST_SANE_US = 5000.0


def headline_valid(doc: dict) -> bool:
    """True when a round's headline came from the chained-dependency
    methodology with a sane read constant.  Rounds predating the
    ``read_const_us`` field timed unforced dispatch (the
    block_until_ready floor), and rounds with a contaminated constant
    over-credit every op — neither number is a usable baseline."""
    parsed = doc.get("parsed") or {}
    rc = parsed.get("read_const_us")
    return isinstance(rc, (int, float)) and 0 <= rc < READ_CONST_SANE_US


def round_headline(doc: dict) -> Optional[float]:
    """GB/s of the headline metric for one BENCH_r record: the
    driver-parsed value, else the last parseable JSON line of the
    captured stdout tail (the r2 failure mode — a tail outgrowing the
    capture — leaves parsed null with the line still in the text)."""
    parsed = doc.get("parsed") or {}
    v = parsed.get("value")
    if isinstance(v, (int, float)) and v > 0:
        return float(v)
    for obj in _json_lines(doc.get("tail", "") or ""):
        if obj.get("unit") == "GB/s" and \
                isinstance(obj.get("value"), (int, float)) and \
                obj["value"] > 0:
            return float(obj["value"])
    return None


def load_rounds(bench_dir: str) -> List[Tuple[int, dict]]:
    """(round number, record) sorted ascending from BENCH_r*.json."""
    out = []
    for path in glob.glob(os.path.join(bench_dir, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            continue
        out.append((int(m.group(1)), doc))
    out.sort()
    return out


def _detail_metrics(detail: dict) -> Dict[str, float]:
    """Flatten the probe blocks of BENCH_DETAIL.json into the sentry's
    comparable scalar metrics (missing probes simply absent)."""
    out: Dict[str, float] = {}
    to = detail.get("trace_overhead") or {}
    if isinstance(to.get("overhead_pct"), (int, float)):
        out["trace_overhead_pct"] = float(to["overhead_pct"])
    if isinstance(to.get("integrity_overhead_pct"), (int, float)):
        out["integrity_overhead_pct"] = \
            float(to["integrity_overhead_pct"])
    ob = detail.get("probe_obs") or {}
    if isinstance(ob.get("overhead_pct"), (int, float)):
        out["obs_overhead_pct"] = float(ob["overhead_pct"])
    pd = detail.get("probe_dispatch") or {}
    const = (pd.get("fused") or {}).get("dispatch_const_us") \
        if isinstance(pd.get("fused"), dict) else None
    if const is None:
        const = pd.get("dispatch_const_us")
    if isinstance(const, (int, float)):
        out["dispatch_const_us"] = float(const)
    pp = detail.get("probe_pipeline") or {}
    bus = pp.get("busbw_gbs") or {}
    for alg in ("fused", "segring"):
        curve = bus.get(alg) or {}
        sizes = [k for k, v in curve.items()
                 if isinstance(v, (int, float)) and v > 0]
        if sizes:
            top = max(sizes, key=int)
            out[f"pipeline_{alg}_busbw_gbs"] = float(curve[top])
    # best segmented busbw across BOTH plan algs and ALL sizes
    seg_vals = [float(v)
                for alg in ("segring", "segrd")
                for v in (bus.get(alg) or {}).values()
                if isinstance(v, (int, float)) and v > 0]
    if seg_vals:
        out["seg_best_busbw_gbs"] = max(seg_vals)
    k256 = str(256 << 10)
    fused256 = (bus.get("fused") or {}).get(k256)
    seg256 = [v for v in ((bus.get("segring") or {}).get(k256),
                          (bus.get("segrd") or {}).get(k256))
              if isinstance(v, (int, float)) and v > 0]
    if isinstance(fused256, (int, float)) and fused256 > 0 and seg256:
        out["seg_vs_fused_ratio_256k"] = round(max(seg256) / fused256, 3)
    rma = (detail.get("probe_rma") or {}).get("components") or {}
    mib = str(1 << 20)
    for comp in ("device", "pt2pt"):
        for kind in ("put", "get"):
            if comp == "pt2pt" and kind == "get":
                continue  # pt2pt get ~= put; three metrics suffice
            v = ((rma.get(comp) or {}).get(f"{kind}_busbw_gbs")
                 or {}).get(mib)
            if isinstance(v, (int, float)) and v > 0:
                out[f"rma_{comp}_{kind}_busbw_gbs"] = float(v)
    cp = detail.get("probe_ctrlplane") or {}
    for key in ("kv_failover_mttr_ms", "dvm_restart_mttr_ms"):
        v = cp.get(key)
        if isinstance(v, (int, float)) and v > 0:
            out[key] = float(v)
    fl = (detail.get("probe_fleet") or {}).get("hosts") or {}
    v = fl.get("host_kill_mttr_ms") if isinstance(fl, dict) else None
    if isinstance(v, (int, float)) and v > 0:
        out["host_kill_mttr_ms"] = float(v)
    rp = detail.get("probe_reqtrace") or {}
    for key in ("queue_wait_p99_us", "doctor_mttd_ms"):
        v = rp.get(key)
        if isinstance(v, (int, float)) and v > 0:
            out[key] = float(v)
    gf = detail.get("probe_grayfail") or {}
    v = gf.get("mttm_ms")
    if isinstance(v, (int, float)) and v > 0:
        out["grayfail_mttm_ms"] = float(v)
    v = gf.get("goodput_ratio")
    if isinstance(v, (int, float)) and v > 0:
        out["grayfail_goodput_ratio"] = float(v)
    v = gf.get("false_quarantines")
    # v >= 0 on purpose: the required value IS zero — the v > 0
    # pattern used above would drop the healthy samples and leave the
    # sentry blind to the first false quarantine
    if isinstance(v, (int, float)) and v >= 0:
        out["false_quarantines"] = float(v)
    sd = detail.get("probe_sdc") or {}
    for key in ("sdc_detection_rate", "sdc_mttq_ms"):
        v = sd.get(key)
        if isinstance(v, (int, float)) and v > 0:
            out[key] = float(v)
    v = sd.get("sdc_false_positives")
    # v >= 0 for the same reason as false_quarantines: zero IS the
    # required value, and dropping it would blind the sentry
    if isinstance(v, (int, float)) and v >= 0:
        out["sdc_false_positives"] = float(v)
    return out


def current_metrics(rounds: List[Tuple[int, dict]],
                    detail: dict) -> Dict[str, float]:
    out = _detail_metrics(detail)
    if rounds:
        v = round_headline(rounds[-1][1])
        if v is not None:
            out["headline_busbw_gbs"] = v
    return out


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    m = n // 2
    return s[m] if n % 2 else 0.5 * (s[m - 1] + s[m])


def check_metric(name: str, current: float,
                 history: List[float]) -> Optional[dict]:
    """One finding dict when ``current`` regressed vs ``history``
    beyond the noise-aware band, else None.  Needs >= 2 valid prior
    samples — a single point has no noise estimate."""
    direction, base = TOLERANCES.get(name, ("higher", 0.10))
    hist = [v for v in history if isinstance(v, (int, float)) and
            (v > 0 or direction == "lower")]
    if len(hist) < 2:
        return None
    med = _median(hist)
    mad = _median([abs(v - med) for v in hist])
    if direction == "higher":
        if med <= 0:
            return None
        tol = max(base, NOISE_K * mad / med)
        floor = med * (1.0 - tol)
        if current < floor:
            return {"metric": name, "current": round(current, 3),
                    "baseline_median": round(med, 3),
                    "floor": round(floor, 3),
                    "tolerance": round(tol, 3),
                    "n_history": len(hist)}
        return None
    # lower-is-better: absolute band in the metric's own units
    band = max(base, NOISE_K * mad)
    ceil = med + band
    if current > ceil:
        return {"metric": name, "current": round(current, 3),
                "baseline_median": round(med, 3),
                "ceiling": round(ceil, 3), "tolerance": round(band, 3),
                "n_history": len(hist)}
    return None


def evaluate(rounds: List[Tuple[int, dict]],
             detail: dict) -> Dict[str, Any]:
    """The sentry verdict document: current metrics, per-metric
    findings, and the trajectory row a non-dry run appends."""
    cur = current_metrics(rounds, detail)
    findings: List[dict] = []

    # headline: newest round vs the prior rounds' own records —
    # measurement-valid rounds only on BOTH sides (headline_valid):
    # an invalid current round cannot be judged, and invalid history
    # rows would anchor the baseline to fabricated numbers
    if "headline_busbw_gbs" in cur and len(rounds) >= 3 and \
            headline_valid(rounds[-1][1]):
        hist = []
        for _n, doc in rounds[:-1]:
            if not headline_valid(doc):
                continue
            v = round_headline(doc)
            if v is not None:
                hist.append(v)
        f = check_metric("headline_busbw_gbs",
                         cur["headline_busbw_gbs"], hist)
        if f:
            findings.append(f)

    # probe metrics: current BENCH_DETAIL vs the recorded trajectory
    traj = detail.get("regress_trajectory") or []
    for name, val in cur.items():
        if name == "headline_busbw_gbs":
            continue
        hist = [row["metrics"][name] for row in traj
                if isinstance(row, dict) and
                name in (row.get("metrics") or {})]
        f = check_metric(name, val, hist)
        if f:
            findings.append(f)

    row = {"round": rounds[-1][0] if rounds else None, "metrics": cur}
    return {"metrics": cur, "findings": findings, "trajectory_row": row,
            "rounds_seen": len(rounds),
            "trajectory_len": len(traj)}


def append_trajectory(detail_path: str, row: dict) -> None:
    """Read-modify-write the trajectory list in BENCH_DETAIL.json,
    capped so the file never grows without bound."""
    try:
        with open(detail_path) as fh:
            detail = json.load(fh)
        if not isinstance(detail, dict):
            detail = {}
    except (OSError, ValueError):
        detail = {}
    traj = detail.get("regress_trajectory")
    if not isinstance(traj, list):
        traj = []
    traj.append(row)
    detail["regress_trajectory"] = traj[-TRAJECTORY_CAP:]
    with open(detail_path, "w") as fh:
        json.dump(detail, fh, indent=1)


def run_regress(bench_dir: str, detail_path: str,
                dry: bool = False) -> int:
    """The ``bench.py --regress`` entry: 0 = no regression, 1 =
    regression detected, 2 = no usable history (CI treats that as a
    configuration error, not a pass)."""
    rounds = load_rounds(bench_dir)
    try:
        with open(detail_path) as fh:
            detail = json.load(fh)
        if not isinstance(detail, dict):
            detail = {}
    except (OSError, ValueError):
        detail = {}
    if not rounds and not detail:
        print(json.dumps({"regress": "no history",
                          "bench_dir": bench_dir}))
        return 2
    res = evaluate(rounds, detail)
    if not dry:
        append_trajectory(detail_path, res["trajectory_row"])
    line = {
        "metric": f"perf-regression sentry over {res['rounds_seen']} "
                  f"round(s) + {res['trajectory_len']} trajectory "
                  f"row(s)",
        "value": len(res["findings"]),
        "unit": "regressions",
        "dry": dry,
        "metrics": res["metrics"],
    }
    if res["findings"]:
        line["findings"] = res["findings"]
    print(json.dumps(line))
    if res["findings"]:
        import sys
        for f in res["findings"]:
            sys.stderr.write(f"REGRESSION: {json.dumps(f)}\n")
        return 1
    return 0
