"""--probe-sdc: close the silent-data-corruption plane end to end.

Three arms, each against an acceptance gate (DESIGN.md §25):

  detect   a 4-rank device mesh with ``device_sdc`` armed on rank 1
           (flip EVERY op) and the integrity plane checking EVERY op
           (integrity_sample=1): every injected flip must be caught at
           the rendezvous, bisection must convict rank 1 and nobody
           else, the poisoned op must be retried from pristine sources
           (every step's result byte-exact against the analytic
           answer), and the job must complete — detection rate 1.0,
           zero failed jobs.
  clean    the same fully-armed world with NO injector: zero
           mismatches, zero convictions over a longer op stream — the
           false-positive gate.  A detector that cries wolf gets
           turned off in production, so this arm is as load-bearing
           as the detection arm.
  pool     a live 2-host DVM pool running the self-verifying SDC
           workload with a ONE-SHOT flip on rank 1: the conviction
           must flow through the §24 health plane's decisive ``sdc``
           signal into an applied quarantine of the corrupting host,
           with MTTQ (first conviction -> quarantine applied) inside
           a budget derived from the probe's own heartbeat/tick
           cadence — and the job still exits 0 with every rank's
           result exact (never a failed job).

The detection-rate denominator is by construction, not by counter:
``device_sdc:1`` with period 1 fires on every collective the victim
rank deposits, so injected == steps exactly and the rate has no
self-grading term.  MTTQ timestamps come from one process — the
conviction hook fires in the pool's executing rank thread and the
quarantine is observed via the server's applied-state ledger — so the
clock base is a single perf_counter_ns domain.

``bench.py --probe-sdc`` persists under ``probe_sdc`` in
BENCH_DETAIL.json and FAILS (exit 1) when any gate breaks.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import time
from typing import Dict

NRANKS = 4
VICTIM = 1                # ft_inject_victim_rank: the corrupting chip
DETECT_STEPS = 40         # injected arm: one flip per step
CLEAN_STEPS = 200         # false-positive arm: longer, fully checked
HOSTS = 2
POOL_STEPS = 6            # pool workload length (flip is one-shot)
FLIP_AT = 3               # pool arm: corrupt exactly op FLIP_AT
HB_S = 0.15               # dvm_heartbeat_s: hb-loop (= sweep) period
TICK_MS = 100             # health_tick_ms: below the hb period, so
                          # the tick fires on every sweep wake
#: conviction -> quarantine-applied budget: a handful of effective
#: sweep periods (hb wake + tick + collect), with CI-box slack
MTTQ_BUDGET_MS = 8 * (HB_S * 1000.0 + TICK_MS)

PROG = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "_sdc_prog.py")

#: every knob the probe sets, saved/restored around the whole run
_KNOBS = {
    "integrity_enable": "1",
    "integrity_sample": "1",       # check every device collective
    "integrity_sample_auto": "0",  # pin the period (no adaptation)
    "ft_inject_victim_rank": str(VICTIM),
    "ft_inject_plan": "",          # each arm sets its own plan
    "ft_inject_sdc_period": "1",
    "health_enable": "1",
    "health_tick_ms": str(TICK_MS),
    "dvm_heartbeat_s": str(HB_S),
}


def _pv(name: str) -> int:
    from ompi_tpu.mca.params import registry
    return registry._pvars[name].read()


def _mesh_arm(steps: int, inject: bool) -> Dict:
    """One fully-checked 4-rank device world; with ``inject`` the
    victim rank flips every op it deposits.  Returns pvar deltas, the
    conviction roster and the per-rank count of byte-exact steps."""
    from ompi_tpu.mca.params import registry
    from ompi_tpu.obs import integrity as ig
    from ompi_tpu.testing import run_ranks

    registry.set("ft_inject_plan", "device_sdc:1" if inject else "")
    registry.set("ft_inject_sdc_period", "1")
    ig.refresh()
    ig.reset()
    base = {k: _pv(f"integrity_{k}") for k in
            ("checks", "mismatches", "convictions", "retry_ops")}

    def fn(comm):
        import jax.numpy as jnp
        import numpy as np

        from ompi_tpu.op.op import SUM
        x = jnp.full((64,), float(comm.rank + 1), jnp.float32)
        want = np.full(64, NRANKS * (NRANKS + 1) / 2.0, np.float32)
        exact = 0
        for _ in range(steps):
            got = np.asarray(comm.allreduce_arr(x, SUM))
            exact += int(np.array_equal(got, want))
        return exact

    exact = run_ranks(NRANKS, fn, devices=True, timeout=600)
    conv = ig.convicted_snapshot()
    out = {k: _pv(f"integrity_{k}") - base[k] for k in base}
    out["steps"] = steps
    out["exact_steps_min"] = min(exact)
    out["byte_exact"] = bool(min(exact) == steps)
    out["convicted_ranks"] = sorted({r["rank"] for r in conv})
    return out


def _pool_arm(tmpdir: str) -> Dict:
    """Live 2-host pool, one-shot flip: conviction -> decisive sdc
    signal -> quarantine applied, timed as MTTQ."""
    import jax

    from ompi_tpu.mca.params import registry
    from ompi_tpu.obs import integrity as ig
    from ompi_tpu.obs.health import QUARANTINED
    from ompi_tpu.tools.dvm import DVMServer, DvmClient

    registry.set("ft_inject_plan", f"device_sdc:{FLIP_AT}")
    registry.set("ft_inject_sdc_period", "0")  # one-shot
    ig.refresh()
    ig.reset()

    conv_ns = [0]

    def _stamp(rec, _c=conv_ns):
        if _c[0] == 0:
            _c[0] = time.perf_counter_ns()

    ig.install_convict_hook(_stamp)
    uri = os.path.join(tmpdir, f"sdc-{time.time_ns()}.uri")
    srv = DVMServer(NRANKS, devices=jax.devices(), uri_file=uri,
                    hosts=HOSTS)
    srv.start()
    c = DvmClient(uri)
    failed = 0
    try:
        sid = c.attach(NRANKS)["sid"]
        r = c.run(sid, PROG, ["probe", str(POOL_STEPS)], timeout=240)
        ok_ranks = len(re.findall(r"SDC probe \d+ ok", r["stdout"]))
        if r["code"] != 0 or ok_ranks != NRANKS:
            failed = 1
        conv = ig.convicted_snapshot()
        if not conv or conv_ns[0] == 0:
            return {"hosts": HOSTS, "error": "no conviction recorded",
                    "failed_jobs": 1, "mttq_ms": -1.0}
        host = int(conv[0]["host"])
        applied_ns = 0
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if srv._health_applied[host] >= QUARANTINED:
                applied_ns = time.perf_counter_ns()
                break
            time.sleep(0.005)
        mttq_ms = ((applied_ns - conv_ns[0]) / 1e6
                   if applied_ns else -1.0)
        other = 1 - host
        out = {
            "hosts": HOSTS,
            "pool_steps": POOL_STEPS,
            "ok_ranks": ok_ranks,
            "failed_jobs": failed,
            "convicted_rank": int(conv[0]["rank"]),
            "convicted_host": host,
            "quarantine_applied": bool(applied_ns),
            "mttq_ms": round(mttq_ms, 1),
            # the healthy host must be untouched, and the metrics RPC
            # must carry the conviction rows to operators
            "other_host_clean": bool(
                srv._health_applied[other] == 0
                and srv.health.sdc[other] == 0),
            "metrics_rows": len(c.metrics().get("sdc") or []),
        }
        c.detach(sid)
        return out
    finally:
        c.sock.close()
        ig.remove_convict_hook(_stamp)
        hp = srv.health
        if hp is not None:
            for h in range(HOSTS):
                hp.reset_host(h)
            hp.collect()
        srv.stop()


def run_probe() -> Dict:
    # the mesh arms need a multi-device CPU backend; force it before
    # anything imports jax (the probe_rma idiom)
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            " --xla_force_host_platform_device_count=8")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    # import the registering modules before touching their knobs
    import ompi_tpu.ft_inject  # noqa: F401
    import ompi_tpu.obs.health  # noqa: F401
    import ompi_tpu.tools.dvm  # noqa: F401
    from ompi_tpu.mca.params import registry
    from ompi_tpu.obs import integrity as ig

    saved = {k: registry.get(k) for k in _KNOBS}
    for k, v in _KNOBS.items():
        registry.set(k, v)
    try:
        detect = _mesh_arm(DETECT_STEPS, inject=True)
        clean = _mesh_arm(CLEAN_STEPS, inject=False)
        with tempfile.TemporaryDirectory() as td:
            pool = _pool_arm(td)
    finally:
        for k, v in saved.items():
            registry.set(k, v)
        ig.refresh()
        ig.reset()

    # injected == steps by construction: after_ops=1, period=1 flips
    # every collective the victim deposits
    rate = detect["mismatches"] / float(detect["steps"])
    false_pos = clean["mismatches"]
    mttq_ms = pool.get("mttq_ms", -1.0)
    failed = int(detect["byte_exact"] is False) + \
        int(clean["byte_exact"] is False) + \
        int(pool.get("failed_jobs", 1))
    gates = {
        "detection_rate_1": bool(rate >= 1.0),
        "conviction_pinned": bool(
            detect["convicted_ranks"] == [VICTIM]
            and pool.get("convicted_rank") == VICTIM),
        "retry_byte_exact": bool(
            detect["byte_exact"] and detect["retry_ops"] >= detect["steps"]),
        "false_positives_0": bool(
            false_pos == 0 and clean["convictions"] == 0),
        "mttq_within_budget": bool(0 < mttq_ms <= MTTQ_BUDGET_MS),
        "pool_isolation": bool(pool.get("quarantine_applied")
                               and pool.get("other_host_clean")),
        "zero_failed_jobs": bool(failed == 0),
    }
    return {
        "nranks": NRANKS,
        "victim": VICTIM,
        "detect": detect,
        "clean": clean,
        "pool": pool,
        "sdc_detection_rate": round(rate, 4),
        "sdc_false_positives": int(false_pos),
        "sdc_mttq_ms": mttq_ms,
        "mttq_budget_ms": round(MTTQ_BUDGET_MS, 1),
        "failed_jobs": failed,
        "gates": gates,
        "within_budget": bool(all(gates.values())),
    }


def persist(probe: Dict, detail_path: str) -> Dict:
    """Merge under 'probe_sdc' in BENCH_DETAIL.json, preserving every
    other section (the probe_dispatch/full-sweep pattern)."""
    notes: Dict = {}
    try:
        with open(detail_path) as fh:
            detail = json.load(fh)
        if not isinstance(detail, dict):
            detail = {}
    except (OSError, ValueError):
        detail = {}
    detail["probe_sdc"] = probe
    try:
        tmp = f"{detail_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(detail, fh, indent=1)
        os.replace(tmp, detail_path)
    except OSError as e:
        notes["detail_error"] = str(e)[:120]
    return notes
