"""--probe-serve microbench: the multiplexed DVM service plane.

Two questions, answered against a live in-process pool (the same
embedded-server harness test_dvm.py uses):

1. **How much faster is a warm attach than a cold launch?**  Cold
   baseline: a full ``mpirun -np N`` subprocess — interpreter start,
   jax import, wireup, one device collective, teardown — timed
   end-to-end, best-of-REPS (the latency a user pays today per job).
   Warm side: ``DvmClient.attach(N)`` against the resident pool —
   session bring-up over the already-warm runtime — median over many
   attach/detach cycles.  The service-plane claim is attach latency
   at least COLD_FACTOR below the cold launch; bench.py FAILS loudly
   if it is not.

2. **What does the pool sustain under contention?**  SUBMITTERS
   concurrent clients each attach a session, pump JOBS_PER_SUBMITTER
   back-to-back runs of the standard warm-pool workload through it,
   and detach.  Reported: aggregate jobs/sec, per-job p50/p99, and
   the pool's own pvar counters (attaches, peak sessions, compiled
   cache hits) proving the sessions actually shared one warmed
   executable cache.

Results land in BENCH_DETAIL.json under ``probe_serve``.
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import threading
import time
from typing import Dict, List

NP = 4                   # ranks per session, both sides of the pair
CAPACITY = 8             # pool rank capacity
COLD_REPS = 3
ATTACH_REPS = 12
SUBMITTERS = 4           # concurrent clients (>= the acceptance bar)
SUBMITTER_NP = 2         # 4 x 2 = 8 ranks resident at once
JOBS_PER_SUBMITTER = 6
COLD_FACTOR = 10.0       # warm attach must beat cold launch by this

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROG = os.path.join(REPO, "tests", "_dvm_prog.py")


def _pct(sorted_vals: List[float], p: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[i]


def _measure_cold() -> List[float]:
    """Full mpirun subprocess launches: interpreter + jax import +
    wireup + one collective + teardown, wall-clock each."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_force_host_platform_device_count=8")
    times = []
    for _ in range(COLD_REPS):
        t0 = time.perf_counter()
        r = subprocess.run(
            [sys.executable, "-m", "ompi_tpu.tools.mpirun",
             "-np", str(NP), PROG],
            capture_output=True, timeout=300, env=env, cwd=REPO)
        dt = time.perf_counter() - t0
        if r.returncode != 0:
            raise RuntimeError(
                f"cold mpirun failed rc={r.returncode}: "
                f"{r.stderr.decode(errors='replace')[-300:]}")
        times.append(dt)
    return times


def run_probe() -> Dict:
    import jax

    from ompi_tpu.tools.dvm import DvmClient, DVMServer

    cold_times = _measure_cold()
    cold_s = min(cold_times)

    import tempfile
    tmpdir = tempfile.mkdtemp(prefix="probe_serve_")
    uri = os.path.join(tmpdir, "dvm.uri")
    srv = DVMServer(CAPACITY, devices=jax.devices(), uri_file=uri)
    srv.start()
    try:
        # -- warm attach latency ------------------------------------
        attach_s: List[float] = []
        cli = DvmClient(uri)
        for i in range(ATTACH_REPS + 1):
            t0 = time.perf_counter()
            sid = cli.attach(NP)["sid"]
            dt = time.perf_counter() - t0
            cli.detach(sid)
            if i > 0:          # rep 0 warms the pool's runtime paths
                attach_s.append(dt)
        cli.close()
        attach_s.sort()
        attach_med = statistics.median(attach_s)

        # -- sustained jobs/sec under concurrent submitters ---------
        job_s: List[float] = []
        jlock = threading.Lock()
        errs: List[str] = []

        def submitter(idx: int) -> None:
            try:
                c = DvmClient(uri)
                sid = c.attach(SUBMITTER_NP, timeout=120)["sid"]
                for _ in range(JOBS_PER_SUBMITTER):
                    t0 = time.perf_counter()
                    r = c.run(sid, PROG, timeout=120)
                    dt = time.perf_counter() - t0
                    if r["code"] != 0:
                        raise RuntimeError(
                            f"job rc={r['code']}: {r['stderr'][-200:]}")
                    with jlock:
                        job_s.append(dt)
                c.detach(sid)
                c.close()
            except Exception as e:  # noqa: BLE001
                with jlock:
                    errs.append(f"submitter {idx}: {e}")

        threads = [threading.Thread(target=submitter, args=(i,))
                   for i in range(SUBMITTERS)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        if errs:
            raise RuntimeError("; ".join(errs[:3]))
        job_s.sort()

        from ompi_tpu.coll.device import compile_cache
        from ompi_tpu.mca.params import registry
        pv = {name: registry._pvars[f"dvm_{name}"].read()
              for name in ("attaches", "sessions_peak", "jobs")
              if f"dvm_{name}" in registry._pvars}
        cache_hits = int(registry._pvars[
            "coll_device_cache_hits"].read())
        builds = compile_cache.builds
    finally:
        srv.stop()
        import shutil
        shutil.rmtree(tmpdir, ignore_errors=True)

    speedup = cold_s / attach_med if attach_med > 0 else 0.0
    return {
        "np": NP,
        "capacity": CAPACITY,
        "cold_reps": COLD_REPS,
        "cold_launch_s": round(cold_s, 4),
        "cold_launch_s_all": [round(t, 4) for t in cold_times],
        "attach_reps": ATTACH_REPS,
        "attach_med_ms": round(attach_med * 1e3, 3),
        "attach_p99_ms": round(_pct(attach_s, 99.0) * 1e3, 3),
        "attach_speedup_vs_cold": round(speedup, 1),
        "submitters": SUBMITTERS,
        "submitter_np": SUBMITTER_NP,
        "jobs": len(job_s),
        "jobs_per_s": round(len(job_s) / wall, 2) if wall else 0.0,
        "job_p50_ms": round(_pct(job_s, 50.0) * 1e3, 3),
        "job_p99_ms": round(_pct(job_s, 99.0) * 1e3, 3),
        "pool_pvars": pv,
        "compiled_cache_hits": cache_hits,
        "compiled_cache_builds": builds,
        "cold_factor": COLD_FACTOR,
        "within_budget": bool(attach_med * COLD_FACTOR <= cold_s),
    }


def persist(probe: Dict, detail_path: str) -> Dict:
    """Merge under 'probe_serve' in BENCH_DETAIL.json, preserving
    every other section (the probe_dispatch/trace_overhead pattern)."""
    notes: Dict = {}
    try:
        with open(detail_path) as fh:
            detail = json.load(fh)
        if not isinstance(detail, dict):
            detail = {}
    except (OSError, ValueError):
        detail = {}
    detail["probe_serve"] = probe
    try:
        tmp = f"{detail_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(detail, fh, indent=1)
        os.replace(tmp, detail_path)
    except OSError as e:
        notes["detail_error"] = str(e)[:120]
    return notes
