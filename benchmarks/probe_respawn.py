"""--probe-respawn microbench: self-healing respawn MTTR + cost.

Two questions, answered on a 4-rank thread-rank world (the same
harness and conventions as probe_recovery):

1. **How long from kill to healed?**  Rank 1 dies deterministically
   after a buddy checkpoint has committed; the survivors and the
   driver-respawned replacement run the full recovery pipeline.  Each
   survivor times it from the instant of death: detect
   (ERR_PROC_FAILED out of the parked collective), respawn+rejoin
   (replacement up, decision agreed, un-fail, epoch fences, new
   full-world communicator), restore (buddy copy pulled from a
   partner, every rank rolled back), and the first FULL-SIZE
   collective completing with the right answer — the MTTR the paper's
   availability story turns on.  Reported numbers are rank 0's,
   best-of-REPS.

2. **What does buddy replication cost when OFF?**  With
   ``cr_buddy_degree=0`` (the default) ``buddy.checkpoint`` must be a
   single int check.  Measured like trace_overhead: interleaved reps
   of the same app loop with the call absent vs present-but-off,
   best-of per side, LOUD failure in bench.py when the off-call side
   exceeds the budget.

Results land in BENCH_DETAIL.json under ``probe_respawn``.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict

NRANKS = 4
VICTIM = 1
OPS = 400          # allreduces per overhead rep
WARMUP = 20
REPS = 5
BUDGET_PCT = 5.0   # acceptance bound for the degree-0 checkpoint call


def _measure_mttr() -> Dict:
    """One kill → detect → respawn/rejoin → restore → first full-size
    collective timeline."""
    import numpy as np

    from ompi_tpu.cr import buddy
    from ompi_tpu.errhandler import MPIException
    from ompi_tpu.ft import respawn, ulfm
    from ompi_tpu.mca.params import registry
    from ompi_tpu.op.op import SUM
    from ompi_tpu.testing import run_ranks

    registry.set("cr_buddy_degree", "1")
    # the victim stamps t0 the instant before it dies; survivors
    # subtract it from their own perf_counter reads (thread ranks
    # share one clock, so no correction is needed)
    t0 = [0.0]

    def fn(comm):
        sbuf = np.ones(16, dtype=np.float64)
        rbuf = np.zeros(16, dtype=np.float64)
        if respawn.joining(comm.state):
            # the replacement's half of the pipeline: rejoin, pull the
            # buddy copy, then meet the survivors' first collective
            comm = respawn.rejoin(comm)
            buddy.restore(comm)
            comm.Allreduce(sbuf, rbuf, SUM)
            return None
        buddy.checkpoint(comm, {"step": 0})
        if comm.rank == VICTIM:
            time.sleep(0.05)  # let survivors park in the Allreduce
            t0[0] = time.perf_counter()
            ulfm.kill_now(comm.state)
        try:
            while True:
                comm.Allreduce(sbuf, rbuf, SUM)
        except MPIException as e:
            t_detect = time.perf_counter()
            assert e.code in (75, 76, 77), e.code
        comm = respawn.rejoin(comm)
        t_rejoin = time.perf_counter()
        buddy.restore(comm)
        t_restore = time.perf_counter()
        comm.Allreduce(sbuf, rbuf, SUM)
        t_first = time.perf_counter()
        assert comm.size == NRANKS            # healed to FULL size
        assert rbuf[0] == float(comm.size)
        return {
            "detect_ms": (t_detect - t0[0]) * 1e3,
            "respawn_ms": (t_rejoin - t_detect) * 1e3,
            "restore_ms": (t_restore - t_rejoin) * 1e3,
            "first_coll_ms": (t_first - t_restore) * 1e3,
            "total_ms": (t_first - t0[0]) * 1e3,
        }

    out = run_ranks(NRANKS, fn, respawn=True, timeout=120)
    return out[0]  # rank 0's view; the victim slot holds the
    #                replacement's None


def _measure_overhead(with_call: bool) -> float:
    """us/op of the healthy app loop without the buddy.checkpoint
    call vs with it present at degree 0 (the zero-cost-when-off
    contract)."""
    import numpy as np

    from ompi_tpu.cr import buddy
    from ompi_tpu.op.op import SUM
    from ompi_tpu.testing import run_ranks

    def fn(comm):
        sbuf = np.ones(8, dtype=np.float32)
        rbuf = np.zeros(8, dtype=np.float32)
        payload = {"step": 0}
        for _ in range(WARMUP):
            comm.Allreduce(sbuf, rbuf, SUM)
        comm.Barrier()
        t0 = time.perf_counter()
        if with_call:
            for _ in range(OPS):
                assert buddy.checkpoint(comm, payload) == -1
                comm.Allreduce(sbuf, rbuf, SUM)
        else:
            for _ in range(OPS):
                comm.Allreduce(sbuf, rbuf, SUM)
        return (time.perf_counter() - t0) / OPS * 1e6

    return run_ranks(NRANKS, fn, timeout=300)[0]


def run_probe() -> Dict:
    from ompi_tpu.mca.params import registry

    prior_ulfm = registry.get("mpi_ft_ulfm", "1")
    prior_deg = registry.get("cr_buddy_degree", "0")
    recs = []
    off_times, on_times = [], []
    try:
        registry.set("mpi_ft_ulfm", "1")
        for _ in range(REPS):
            recs.append(_measure_mttr())
        registry.set("cr_buddy_degree", "0")
        for _ in range(REPS):
            off_times.append(_measure_overhead(False))
            on_times.append(_measure_overhead(True))
    finally:
        registry.set("mpi_ft_ulfm", prior_ulfm)
        registry.set("cr_buddy_degree", prior_deg)
    best = min(recs, key=lambda r: r["total_ms"])
    off_us = min(off_times)
    on_us = min(on_times)
    overhead = (on_us - off_us) / off_us * 100.0
    return {
        "nranks": NRANKS,
        "victim": VICTIM,
        "reps": REPS,
        "detect_ms": round(best["detect_ms"], 3),
        "respawn_ms": round(best["respawn_ms"], 3),
        "restore_ms": round(best["restore_ms"], 3),
        "first_coll_ms": round(best["first_coll_ms"], 3),
        "total_ms": round(best["total_ms"], 3),
        "total_ms_all": [round(r["total_ms"], 3) for r in recs],
        "ops_per_rep": OPS,
        "payload_bytes": 32,
        "off_us_per_op": round(off_us, 2),
        "on_us_per_op": round(on_us, 2),
        "off_us_all": [round(x, 2) for x in off_times],
        "on_us_all": [round(x, 2) for x in on_times],
        "overhead_pct": round(overhead, 2),
        "budget_pct": BUDGET_PCT,
        "within_budget": bool(overhead <= BUDGET_PCT),
    }


def persist(probe: Dict, detail_path: str) -> Dict:
    """Merge under 'probe_respawn' in BENCH_DETAIL.json, preserving
    every other section (the probe_dispatch/trace_overhead pattern)."""
    notes: Dict = {}
    try:
        with open(detail_path) as fh:
            detail = json.load(fh)
        if not isinstance(detail, dict):
            detail = {}
    except (OSError, ValueError):
        detail = {}
    detail["probe_respawn"] = probe
    try:
        tmp = f"{detail_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(detail, fh, indent=1)
        os.replace(tmp, detail_path)
    except OSError as e:
        notes["detail_error"] = str(e)[:120]
    return notes
