"""--probe-pipeline microbench: the large-message busbw curve per
device algorithm — fused single-dispatch, segmented ring, per-segment
recursive doubling, and the hierarchical tier — over an OSU-style size
ladder (64 KiB ... 256 MiB; the in-container default caps the ladder
so a CI run finishes, real hardware raises --pipeline-max-bytes).

One thread-rank device world runs every configuration: the pipeline
knobs are process-global and every rank writes the identical values
before its next collective (then drops its per-comm routing caches),
so the world never splits across algorithms.  Each rep is timed
individually and the MEDIAN is reported, as in probe_dispatch.

allreduce busbw follows the OSU convention 2*(P-1)/P * nbytes / t —
the bytes a rank actually moves on the wire, so ring and recursive
doubling curves are directly comparable.

Results are persisted under ``probe_pipeline`` in BENCH_DETAIL.json
(read-modify-write) and the measured fused-vs-segmented and
segmented-vs-hierarchical crossovers refresh the coll/calibrate
per-host profile, so ``--mca coll_tuned_use_measured_rules 1``
consumes *measured* data — the same contract as --probe-dispatch.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List

# full OSU-style ladder; run_probe caps it (1-core CI boxes cannot
# hold 8 ranks x 256 MiB, and the curve's knee sits far below that)
SIZES = tuple((64 << 10) * 4 ** k for k in range(7))  # 64K .. 256M
DEFAULT_MAX_BYTES = 16 << 20
_CAP = 4 << 20  # mirror calibrate._CROSSOVER_CAP

ALGS = ("fused", "segring", "segrd", "hier")

# knob overrides per configuration; every rank applies them before
# its next collective (identical values — the registry is shared)
_CONFIGS: Dict[str, Dict[str, object]] = {
    "fused": {"coll_pipeline_enable": False, "coll_hier_enable": False},
    "segring": {"coll_pipeline_enable": True, "coll_hier_enable": False,
                "coll_pipeline_min_bytes": 1, "coll_plan_enable": True,
                "coll_pipeline_rd_max_bytes": 0},
    "segrd": {"coll_pipeline_enable": True, "coll_hier_enable": False,
              "coll_pipeline_min_bytes": 1, "coll_plan_enable": True,
              "coll_pipeline_rd_max_bytes": 1 << 62},
    "hier": {"coll_pipeline_enable": True, "coll_hier_enable": True,
             "coll_pipeline_min_bytes": 1, "coll_hier_min_bytes": 1,
             "coll_plan_enable": True,
             "coll_pipeline_rd_max_bytes": 0},
}

# per-comm routing caches that must be dropped when knobs change
# (resolved Plan objects key on geometry the knobs move)
_ROUTE_KEYS = ("_pipeline_pick", "_hier_eligible", "_hier_plan",
               "_coll_plans")


def _median_us(samples: List[float]) -> float:
    samples = sorted(samples)
    mid = len(samples) // 2
    med = samples[mid] if len(samples) % 2 else \
        (samples[mid - 1] + samples[mid]) / 2
    return med * 1e6


def _time_loop(comm, call, reps: int) -> float:
    call()  # warm: compile + first-dispatch (and hier comm splits)
    call()
    comm.Barrier()
    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        call()
        samples.append(time.perf_counter() - t0)
    comm.Barrier()
    return _median_us(samples)


def _apply(comm, alg: str, nranks: int) -> None:
    from ompi_tpu.mca.params import registry
    over = dict(_CONFIGS[alg])
    if alg == "hier":
        over["coll_hier_slice_size"] = max(2, nranks // 2)
    for k, v in over.items():
        registry.set(k, v)
    for k in _ROUTE_KEYS:
        comm.__dict__.pop(k, None)


def _busbw_gbs(nbytes: int, us: float, nranks: int) -> float:
    wire = 2.0 * (nranks - 1) / nranks * nbytes
    return round(wire / (us * 1e-6) / 1e9, 3) if us > 0 else 0.0


def run_probe(nranks: int = 8, reps: int = 7,
              max_bytes: int = DEFAULT_MAX_BYTES) -> Dict:
    from ompi_tpu.testing import run_ranks

    sizes = [nb for nb in SIZES if nb <= max_bytes] or [SIZES[0]]

    def fn(comm):
        import jax
        import jax.numpy as jnp
        from ompi_tpu.coll import pipeline
        from ompi_tpu.coll import plan as coll_plan
        from ompi_tpu.op.op import SUM

        curve: Dict[str, Dict[str, float]] = {a: {} for a in ALGS}
        # plan-cache traffic per alg x size: builds measured across the
        # whole block (all ranks add to the process-wide pvar), so a
        # steady-state regression — plans rebuilt per op — shows up as
        # builds >> nranks for a single size
        plan_cache: Dict[str, Dict[str, Dict[str, int]]] = \
            {a: {} for a in ALGS}
        seg_before = pipeline.pv_segments.read()
        for alg in ALGS:
            for nb in sizes:
                _apply(comm, alg, comm.size)
                x = jax.device_put(
                    jnp.arange(nb // 4, dtype=jnp.float32) + comm.rank,
                    comm.device)
                b0 = coll_plan.pv_builds.read()
                h0 = coll_plan.pv_hits.read()
                # big payloads settle for fewer reps: the median of 3
                # at 16 MiB still rejects a single preemption
                r = max(3, reps - 2 * sizes.index(nb))
                curve[alg][str(nb)] = round(_time_loop(
                    comm, lambda: comm.allreduce_arr(x, SUM), r), 1)
                plan_cache[alg][str(nb)] = {
                    "builds": coll_plan.pv_builds.read() - b0,
                    "hits": coll_plan.pv_hits.read() - h0}
                del x

        # per-phase breakdown (ISSUE 13): a short pass per alg x size
        # with the phase profiler armed, so BENCH_DETAIL tracks WHERE
        # a segmented op's time goes (rendezvous / pack / dispatch /
        # execute / unpack) round over round — the dispatch-tax number
        # with a trajectory, not a guess.  The timing sweep above ran
        # untraced; knobs are restored before returning.
        from ompi_tpu import trace
        from ompi_tpu.mca.params import registry
        saved = {k: registry.get(k) for k in
                 ("trace_phase_enable", "trace_sample_auto")}
        registry.set("trace_phase_enable", True)
        registry.set("trace_sample_auto", 0)
        tr = trace.force_attach(comm.state)
        raw: Dict[str, Dict[str, Dict[str, List[float]]]] = {}
        for alg in ALGS:
            raw[alg] = {}
            for nb in sizes:
                _apply(comm, alg, comm.size)
                x = jax.device_put(
                    jnp.arange(nb // 4, dtype=jnp.float32) + comm.rank,
                    comm.device)
                comm.allreduce_arr(x, SUM)  # warm (compile spans out)
                comm.Barrier()
                mark = time.time() - 1e-3
                for _ in range(2):
                    comm.allreduce_arr(x, SUM)
                comm.Barrier()
                acc: Dict[str, List[float]] = {}
                for ev in tr.snapshot():
                    if ev.get("ph") != "X" or ev["ts"] < mark:
                        continue
                    label = trace.PHASE_LABELS.get(ev["name"])
                    if label is None or ev["cat"] != "phase":
                        continue
                    acc.setdefault(label, []).append(ev["dur"] * 1e6)
                raw[alg][str(nb)] = acc
                del x
        comm.state.tracer = None
        comm.state.progress.tracer = None
        for k, v in saved.items():
            registry.set(k, v)
        _apply(comm, "fused", comm.size)  # leave the world at defaults
        return {"lat_us": curve, "phase_raw": raw,
                "plan_cache": plan_cache,
                "segments": pipeline.pv_segments.read() - seg_before}

    res = run_ranks(nranks, fn, devices=True, timeout=1800)
    lat = res[0]["lat_us"]
    # phase medians merged over EVERY rank's recorded spans: dispatch/
    # execute land on whichever rank arrived last at each rendezvous,
    # so a single rank's view would usually miss them entirely
    phase_us: Dict[str, Dict[str, Dict[str, float]]] = {}
    for alg in ALGS:
        phase_us[alg] = {}
        for s in (res[0].get("phase_raw") or {}).get(alg, {}):
            merged: Dict[str, List[float]] = {}
            for r in res:
                for label, durs in ((r.get("phase_raw") or {})
                                    .get(alg, {}).get(s) or {}).items():
                    merged.setdefault(label, []).extend(durs)
            phase_us[alg][s] = {
                label: round(_median_us([d * 1e-6 for d in durs]), 1)
                for label, durs in sorted(merged.items())}
    probe: Dict = {
        "nranks": nranks,
        "sizes": sizes,
        "lat_us": lat,
        "busbw_gbs": {a: {s: _busbw_gbs(int(s), us, nranks)
                          for s, us in lat[a].items()}
                      for a in ALGS},
        "phase_us": phase_us,
        "plan_cache": res[0].get("plan_cache") or {},
        "segments_rank0": res[0]["segments"],
    }
    # measured crossovers: smallest probed size where the tier wins
    best_seg = {s: min(lat["segring"][s], lat["segrd"][s])
                for s in lat["fused"]}
    probe["seg_crossover_bytes"] = next(
        (int(s) for s in sorted(lat["fused"], key=int)
         if best_seg[s] <= lat["fused"][s]), _CAP)
    probe["hier_min_bytes"] = next(
        (int(s) for s in sorted(lat["hier"], key=int)
         if lat["hier"][s] <= best_seg[s]), _CAP)
    return probe


def persist(probe: Dict, detail_path: str) -> Dict:
    """Merge under 'probe_pipeline' in BENCH_DETAIL.json and refresh
    the calibrate profile's segmented/hierarchical crossovers."""
    notes = {}
    try:
        with open(detail_path) as fh:
            detail = json.load(fh)
        if not isinstance(detail, dict):
            detail = {}
    except (OSError, ValueError):
        detail = {}
    detail["probe_pipeline"] = probe
    try:
        tmp = f"{detail_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(detail, fh, indent=1)
        os.replace(tmp, detail_path)
    except OSError as e:
        notes["detail_error"] = str(e)[:120]

    try:
        from ompi_tpu.coll import calibrate
        prof = calibrate.get_profile(create=True) or {}
        prof = dict(prof)
        prof["source"] = "probe_pipeline_sweep"
        prof["seg_crossover_bytes"] = {
            kind: probe["seg_crossover_bytes"]
            for kind in ("allreduce", "bcast", "alltoall")}
        prof["hier_min_bytes"] = probe["hier_min_bytes"]
        notes["profile_path"] = calibrate.save_profile(prof)
    except Exception as e:  # noqa: BLE001
        notes["profile_error"] = str(e)[:120]
    return notes
