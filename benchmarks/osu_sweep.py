"""OSU-style microbenchmark sweep over the launched job: the
software-baseline side of BASELINE.md (coll/tuned over a byte
transport; ref: the external OSU suite SURVEY §4 delegates to).

Run under mpirun (process-ranks; force TCP for the tuned-over-TCP
configuration the north star names):

    python -m ompi_tpu.tools.mpirun -np 8 --mca btl self,tcp \
        benchmarks/osu_sweep.py --max-ar 268435456

Rank 0 prints ONE JSON line mapping collective -> {bytes: usec}:
allreduce (MPI_SUM float32), bcast (float32), alltoall (float32),
reduce_scatter_block MPI_MAX on MPI_DOUBLE through a derived vector
datatype (BASELINE config 5).

Latency convention: barrier, time a fixed loop per rank, allreduce-MAX
the per-rank averages (the OSU avg-of-max convention).
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

import ompi_tpu
from ompi_tpu.datatype import engine as dt
from ompi_tpu.op import op as mpi_op


def sizes_upto(max_bytes: int, start: int = 4):
    s = start
    while s <= max_bytes:
        yield s
        s *= 2


_DEADLINE = [0.0]


def _should_continue(comm, last_dt_s: float = 0.0) -> bool:
    """Collectively-agreed budget check (rank 0 decides): ranks must
    never diverge on whether the next size's collectives run.

    ``last_dt_s`` is the previous size's per-op time: the NEXT size is
    ~2x that, and its unbudgeted warmup probe alone could eat the rest
    of the budget (the r2 starvation failure: a 110 s probe at 128 MiB
    consumed the entire window before any timed point ran) — so the
    projected probe cost gates entry, not just the wall clock."""
    d = _DEADLINE[0]
    ok = d <= 0 or (time.perf_counter() + 4.0 * last_dt_s) < d
    flag = np.array([1 if ok else 0], dtype=np.int32)
    comm.Bcast(flag, root=0)
    return bool(flag[0])


def _timeit(comm, fn, dt_probe: float) -> float:
    """Per-rank mean over an iteration count adapted to the probe
    time (~0.25 s budget per size, rank-0-agreed), max-reduced
    across ranks."""
    it = np.array([max(2, min(100, int(0.25 / max(dt_probe, 1e-6))))],
                  dtype=np.int32)
    comm.Bcast(it, root=0)
    iters = int(it[0])
    comm.Barrier()
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    mine = np.array([(time.perf_counter() - t0) / iters])
    worst = np.empty_like(mine)
    comm.Allreduce(mine, worst, mpi_op.MAX)
    return float(worst[0])


def bench_allreduce(comm, max_bytes: int, start: int = 4) -> dict:
    out = {}
    last = 0.0
    for nbytes in sizes_upto(max_bytes, start=start):
        if not _should_continue(comm, last):
            out["truncated"] = True
            return out
        n = max(1, nbytes // 4)
        x = np.full(n, comm.rank + 1.0, dtype=np.float32)
        r = np.empty_like(x)
        comm.Allreduce(x, r, mpi_op.SUM)  # warmup (segment/page-fault setup)
        t0 = time.perf_counter()
        comm.Allreduce(x, r, mpi_op.SUM)  # probe
        probe = time.perf_counter() - t0
        dt_s = _timeit(comm, lambda: comm.Allreduce(x, r, mpi_op.SUM),
                       probe)
        assert abs(r[0] - sum(range(1, comm.size + 1))) < 1e-3
        out[str(n * 4)] = round(dt_s * 1e6, 2)
        last = dt_s
    return out


def bench_bcast(comm, max_bytes: int) -> dict:
    out = {}
    last = 0.0
    for nbytes in sizes_upto(max_bytes):
        if not _should_continue(comm, last):
            out["truncated"] = True
            return out
        n = max(1, nbytes // 4)
        x = np.full(n, 7.0 if comm.rank == 0 else 0.0, dtype=np.float32)
        comm.Bcast(x, root=0)  # warmup
        t0 = time.perf_counter()
        comm.Bcast(x, root=0)
        probe = time.perf_counter() - t0
        dt_s = _timeit(comm, lambda: comm.Bcast(x, root=0), probe)
        assert x[0] == 7.0
        out[str(n * 4)] = round(dt_s * 1e6, 2)
        last = dt_s
    return out


def bench_alltoall(comm, max_bytes: int) -> dict:
    """max_bytes is the per-peer message size (OSU convention)."""
    out = {}
    last = 0.0
    for nbytes in sizes_upto(max_bytes):
        if not _should_continue(comm, last):
            out["truncated"] = True
            return out
        n = max(1, nbytes // 4) * comm.size
        x = np.full(n, comm.rank + 1.0, dtype=np.float32)
        r = np.empty_like(x)
        comm.Alltoall(x, r)  # warmup
        t0 = time.perf_counter()
        comm.Alltoall(x, r)
        probe = time.perf_counter() - t0
        dt_s = _timeit(comm, lambda: comm.Alltoall(x, r), probe)
        assert r[0] == 1.0 and r[-1] == float(comm.size)
        out[str(max(1, nbytes // 4) * 4)] = round(dt_s * 1e6, 2)
        last = dt_s
    return out


def bench_rsb_vector(comm, max_bytes: int) -> dict:
    """Reduce_scatter_block, MPI_MAX on MPI_DOUBLE, send data viewed
    through a derived vector type (BASELINE config 5): block of
    `per` doubles per rank, sent as vector(count=per/2, blocklen=2,
    stride=2) — contiguous coverage but exercising the derived-type
    pack path."""
    out = {}
    last = 0.0
    for nbytes in sizes_upto(max_bytes, start=64):
        if not _should_continue(comm, last):
            out["truncated"] = True
            return out
        per = max(2, nbytes // 8 // 2 * 2)  # doubles per rank, even
        total = per * comm.size
        x = np.full(total, float(comm.rank + 1), dtype=np.float64)
        r = np.empty(per, dtype=np.float64)
        vec = dt.vector(per // 2, 2, 2, dt.DOUBLE)

        def op_():
            comm.Reduce_scatter_block((x, comm.size, vec), (r, 1, vec),
                                      mpi_op.MAX)

        op_()  # warmup
        t0 = time.perf_counter()
        op_()
        probe = time.perf_counter() - t0
        dt_s = _timeit(comm, op_, probe)
        assert r[0] == float(comm.size)
        out[str(per * 8)] = round(dt_s * 1e6, 2)
        last = dt_s
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-ar", type=int, default=256 * 1024 * 1024)
    ap.add_argument("--max-bcast", type=int, default=64 * 1024 * 1024)
    ap.add_argument("--max-a2a", type=int, default=4 * 1024 * 1024)
    ap.add_argument("--max-rsb", type=int, default=16 * 1024 * 1024)
    ap.add_argument("--start", type=int, default=4,
                    help="Smallest allreduce size (the tuned-tcp "
                         "north-star config skips the sub-4KiB tail)")
    ap.add_argument("--budget", type=float, default=0.0,
                    help="Soft wall-clock budget in seconds; later "
                         "sizes are dropped (and marked truncated) "
                         "once exceeded")
    opts = ap.parse_args()
    if opts.budget:
        _DEADLINE[0] = time.perf_counter() + opts.budget

    comm = ompi_tpu.init()
    results = {}
    if opts.max_ar:
        results["allreduce"] = bench_allreduce(comm, opts.max_ar,
                                               opts.start)
    if opts.max_bcast:
        results["bcast"] = bench_bcast(comm, opts.max_bcast)
    if opts.max_a2a:
        results["alltoall"] = bench_alltoall(comm, opts.max_a2a)
    if opts.max_rsb:
        results["reduce_scatter_block_vector"] = bench_rsb_vector(
            comm, opts.max_rsb)
    if comm.rank == 0:
        print(json.dumps(results), flush=True)
    ompi_tpu.finalize()


if __name__ == "__main__":
    main()
