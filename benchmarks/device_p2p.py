"""Device p2p bench: the btl/tpu D2D path vs the host-staged path,
timed truthfully (wall clock around completed round trips; results
are materialized each iteration via a host read of one element, so
no dispatch-floor artifacts — the same discipline as device_sweep).

    python benchmarks/device_p2p.py [--nranks 2] [--max-bytes N]

Prints one JSON line: {nbytes: {"device_us": .., "staged_us": ..}}.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def run(nranks: int, max_bytes: int) -> dict:
    from ompi_tpu.testing import run_ranks

    def fn(comm):
        import jax
        import jax.numpy as jnp

        out = {}
        nxt = (comm.rank + 1) % comm.size
        prv = (comm.rank - 1) % comm.size
        nbytes = 4
        while nbytes <= max_bytes:
            n = max(1, nbytes // 4)
            x = jnp.full((n,), float(comm.rank), jnp.float32)
            x.block_until_ready()

            def rtt(exchange) -> float:
                for _ in range(3):
                    exchange()
                iters = max(5, min(200, int(2e6 / max(nbytes, 1))))
                t0 = time.perf_counter()
                for _ in range(iters):
                    got = exchange()
                    # force completion: one host read per iteration
                    float(np.asarray(got[:1])[0])
                return (time.perf_counter() - t0) / iters

            dev = rtt(lambda: comm.sendrecv_arr(x, nxt, prv, tag=1))
            host_buf = np.empty(n, np.float32)

            def staged():
                # classic host path: d2h, byte send/recv, h2d.
                # Isend+Recv: head-to-head blocking sends would
                # deadlock once the size crosses the eager limit
                from ompi_tpu.datatype import engine as dt
                req = comm.state.pml.isend(
                    np.asarray(x), n, dt.FLOAT, nxt, 2, comm)
                comm.Recv(host_buf, prv, tag=2)
                req.wait()
                return jax.device_put(host_buf, comm.state.device)

            stg = rtt(staged)
            if comm.rank == 0:
                out[str(nbytes)] = {
                    "device_us": round(dev * 1e6, 1),
                    "staged_us": round(stg * 1e6, 1),
                }
            comm.Barrier()
            nbytes *= 8
        return out

    res = run_ranks(nranks, fn, devices=True, timeout=600)
    return res[0]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nranks", type=int, default=2)
    ap.add_argument("--max-bytes", type=int, default=4 * 1024 * 1024)
    opts = ap.parse_args()
    print(json.dumps(run(opts.nranks, opts.max_bytes)))


if __name__ == "__main__":
    main()
