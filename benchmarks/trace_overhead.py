"""--trace-overhead microbench: the cost of span tracing ON vs OFF.

The trace contract (ompi_tpu/trace, docs/DESIGN.md §9) is near-zero
cost when ``trace_enable`` is off — a single attribute-is-None check
on each instrumented hot path — and bounded, never-blocking cost when
on.  This probe quantifies both sides on the small-message path where
per-op overhead is largest relative to the work: a 4-rank thread-rank
world looping small host Allreduces (coll shim + pml p2p + progress
ticks all traced).

Methodology: tracing off and on are measured in INTERLEAVED reps
(off, on, off, on, ...) so slow drift on a noisy box hits both sides
equally, and each side reports its best (minimum) per-op time — the
contamination-free floor is what the overhead delta means, not the
scheduler-noise mean.  Inside the traced world, rank 0 snapshots the
latency-histogram pvars and span counts, which land in
BENCH_DETAIL.json under ``trace_overhead``.

The 5%% budget is enforced LOUDLY: ``bench.py --trace-overhead``
exits nonzero when the measured ON-overhead exceeds it.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict

NRANKS = 4
OPS = 400          # allreduces per measured rep
WARMUP = 20
REPS = 5           # interleaved off/on pairs
BUDGET_PCT = 5.0   # acceptance bound for the ON path


def _measure_world(traced: bool) -> Dict:
    """One thread-rank world; returns rank 0's timing (every rank
    loops — the collective synchronizes each op) plus, when traced,
    the histogram/span snapshot taken INSIDE the world (pvar getters
    resolve through the current rank's state)."""
    import numpy as np

    from ompi_tpu.mca.params import registry
    from ompi_tpu.op.op import SUM
    from ompi_tpu.testing import run_ranks

    registry.set("trace_enable", "1" if traced else "0")
    if traced:
        # big enough that the measured loop never wraps: a drop-heavy
        # ring would under-report the recording cost
        registry.set("trace_buffer_events", str(max(8192, OPS * 8)))

    def fn(comm):
        sbuf = np.ones(8, dtype=np.float32)
        rbuf = np.zeros(8, dtype=np.float32)
        for _ in range(WARMUP):
            comm.Allreduce(sbuf, rbuf, SUM)
        comm.Barrier()
        t0 = time.perf_counter()
        for _ in range(OPS):
            comm.Allreduce(sbuf, rbuf, SUM)
        dt = time.perf_counter() - t0
        out: Dict = {"us_per_op": dt / OPS * 1e6}
        if comm.rank != 0:
            return out
        if traced:
            from ompi_tpu import mpit, trace
            tr = comm.state.tracer
            out["spans"] = {cat: tr.span_count(cat)
                            for cat in ("coll", "p2p")}
            out["recorded"] = tr.recorded
            out["dropped"] = tr.dropped
            # snapshot through MPI_T itself (not the Tracer object):
            # the pvar surface is what bench consumers get
            mpit.init_thread()
            try:
                sess = mpit.pvar_session_create()
                out["hists"] = {}
                for name in trace.HIST_NAMES:
                    ph = mpit.pvar_handle_alloc(
                        sess, f"trace_hist_{name}")
                    out["hists"][name] = mpit.pvar_read(ph)
                mpit.pvar_session_free(sess)
            finally:
                mpit.finalize()
        else:
            # the off-side contract, asserted where it is measured
            assert comm.state.tracer is None
        return out

    return run_ranks(NRANKS, fn, timeout=300)[0]


def run_probe() -> Dict:
    from ompi_tpu.mca.params import registry

    off_times, on_times = [], []
    snap: Dict = {}
    try:
        for _ in range(REPS):
            off_times.append(_measure_world(False)["us_per_op"])
            on = _measure_world(True)
            on_times.append(on["us_per_op"])
            snap = on  # keep the freshest traced snapshot
    finally:
        registry.set("trace_enable", "0")
    off_us = min(off_times)
    on_us = min(on_times)
    overhead = (on_us - off_us) / off_us * 100.0
    return {
        "nranks": NRANKS,
        "ops_per_rep": OPS,
        "reps": REPS,
        "payload_bytes": 32,
        "off_us_per_op": round(off_us, 2),
        "on_us_per_op": round(on_us, 2),
        "off_us_all": [round(x, 2) for x in off_times],
        "on_us_all": [round(x, 2) for x in on_times],
        "overhead_pct": round(overhead, 2),
        "budget_pct": BUDGET_PCT,
        "within_budget": bool(overhead <= BUDGET_PCT),
        "traced_spans": snap.get("spans", {}),
        "traced_recorded": snap.get("recorded", 0),
        "traced_dropped": snap.get("dropped", 0),
        "hist_pvars": snap.get("hists", {}),
    }


def persist(probe: Dict, detail_path: str) -> Dict:
    """Merge under 'trace_overhead' in BENCH_DETAIL.json, preserving
    every other section (the probe_dispatch/full-sweep pattern)."""
    notes: Dict = {}
    try:
        with open(detail_path) as fh:
            detail = json.load(fh)
        if not isinstance(detail, dict):
            detail = {}
    except (OSError, ValueError):
        detail = {}
    detail["trace_overhead"] = probe
    try:
        tmp = f"{detail_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(detail, fh, indent=1)
        os.replace(tmp, detail_path)
    except OSError as e:
        notes["detail_error"] = str(e)[:120]
    return notes
