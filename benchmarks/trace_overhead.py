"""--trace-overhead microbench: the cost of span tracing ON vs OFF.

The trace contract (ompi_tpu/trace, docs/DESIGN.md §9) is near-zero
cost when ``trace_enable`` is off — a single attribute-is-None check
on each instrumented hot path — and bounded, never-blocking cost when
on.  This probe quantifies both sides on the small-message path where
per-op overhead is largest relative to the work: a 4-rank thread-rank
world looping small host Allreduces (coll shim + device dispatch +
progress ticks all traced).

Methodology: ONE world, arms MICRO-INTERLEAVED inside it.  Separate
worlds land in different scheduler/placement modes on a small box —
the mode spread (±15%% observed on a 1-core host) buries a 5%%
effect — and even second-long contiguous blocks land wholly inside
±20-30%% scheduler regimes (measured here), so block-vs-block
comparison cannot resolve 5%% either.  Instead every rotation visit
times a ~10 ms chunk (``CHUNK_OPS`` allreduces) of ONE arm, cycling
all four arms in palindromic order (odd visits reverse) many times
per reported block: adjacent chunks share the regime, so every arm
samples every regime nearly equally and the regime noise divides
out of the per-block aggregates.  The acceptance bound is judged on
the MEDIAN of PER-BLOCK PAIRED overheads — each arm's aggregate
against the untraced aggregate of the SAME block — so a one-off
spike inflates a single block's ratio that the median then discards.
A best-of comparison would reward one lucky quiet block; the paired
median is what a user actually pays (best-of is still reported for
context).  Before the measured
blocks the adaptive sampler is ramped to steady state over
``RAMP_OPS`` traced ops (disclosed in the JSON) — the budget is the
long-run cost of always-on tracing, with the transient's length
reported honestly rather than averaged invisibly into it.

The JSON also records the host core count and whether the GIL is
active, because thread-rank worlds on a GIL build serialize every
rank through one interpreter lock — the harshest (most honest)
setting for per-op bookkeeping overhead.  Rank 0 snapshots the
latency-histogram, sampling-rate, and per-category dropped pvars,
which land in BENCH_DETAIL.json under ``trace_overhead``.

The 5%% budget is enforced LOUDLY: ``bench.py --trace-overhead``
exits nonzero when the MEDIAN overhead exceeds it.

The phase profiler (DESIGN.md §18) rides the same budget: the block
rotation is four-way (off / on / on+phase spans / on+request tags),
so the JSON also reports ``phase_overhead_pct`` — the cost of per-op
rendezvous / pack / dispatch / execute sub-spans measured against
the SAME untraced blocks, judged against the SAME 5%% bound — and
``reqtrace_overhead_pct``: the cost of per-job request tagging
(DESIGN.md §23) at the serving plane's own cadence — one
``req_mark`` bracket per run, both marks ON the clock — with the
probe's "runs" only ``CHUNK_OPS`` ops long (real serving runs are
two to four orders of magnitude longer, so the per-run cost is
overstated here, never hidden), against the same untraced blocks
and the same bound.

The sdc-integrity plane (DESIGN.md §25) rides the same budget with
its own world: integrity gates DEVICE collectives only (the host
Allreduce above never reaches the rendezvous gate), so the
``integrity`` arm runs a second 4-rank device mesh with the same
palindromic micro-chunk interleave — disarmed vs armed at the
``integrity_sample`` steady state (the adaptive sampler is ramped to
its period cap before anything is timed, transient disclosed via
``integrity_ramp_ops``).  ``integrity_overhead_pct`` is the paired
per-block median against the disarmed blocks of the SAME device
world, judged against the SAME 5%% bound.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time
from typing import Dict

NRANKS = 4
WARMUP = 50        # untimed JIT/cache warm ops before anything else
RAMP_OPS = 8000    # traced ops to carry the adaptive sampler to its
                   # steady state (period doubles every
                   # trace_sample_auto seen, to trace_sample_max)
CHUNK_OPS = 100    # allreduces per timed micro-chunk (~10 ms: well
                   # inside one scheduler regime, so the four arms'
                   # adjacent chunks share it)
SUB_ROUNDS = 15    # micro-chunk visits of EVERY arm per block
BLOCK_OPS = CHUNK_OPS * SUB_ROUNDS  # per arm per reported block
BLOCKS = 7         # reported off/on/phase/reqtrace block rounds
BUDGET_PCT = 5.0   # acceptance bound for the ON path (median)

# integrity-arm world (device mesh — slower per op than the host
# Allreduce, so fewer ops bound the wall clock; the chunking keeps
# the same adjacent-regime pairing property)
I_CHUNK_OPS = 25
I_SUB_ROUNDS = 8
I_BLOCKS = 5
I_BLOCK_OPS = I_CHUNK_OPS * I_SUB_ROUNDS
I_RAMP_OPS = 600   # armed ops carrying the integrity sampler's period
                   # from 1 to the integrity_sample cap (auto=2 during
                   # the probe, so the ramp is ~2x the period sum)


def _probe_world() -> Dict:
    """One thread-rank world alternating untraced/traced blocks;
    returns rank 0's per-block timings (every rank loops — the
    collective synchronizes each op) plus the histogram/span/sampling
    snapshot taken INSIDE the world (pvar getters resolve through the
    current rank's state)."""
    import numpy as np

    from ompi_tpu.op.op import SUM
    from ompi_tpu.testing import run_ranks

    def fn(comm):
        sbuf = np.ones(8, dtype=np.float32)
        rbuf = np.zeros(8, dtype=np.float32)
        tr = comm.state.tracer
        assert tr is not None  # world starts traced (trace_enable=1)
        for _ in range(WARMUP):
            comm.Allreduce(sbuf, rbuf, SUM)
        for _ in range(RAMP_OPS):
            comm.Allreduce(sbuf, rbuf, SUM)
        phase0 = tr.phase
        # ramp the PHASE category's adaptive sampler too: its period
        # starts at 1 (every op pays a device fence for the execute
        # span) and doubles to trace_sample_max — the budget is the
        # steady state, with the transient disclosed via RAMP_OPS
        tr.phase = True
        for _ in range(RAMP_OPS):
            comm.Allreduce(sbuf, rbuf, SUM)
        tr.phase = phase0
        # request-tag arm (DESIGN.md §23): a fixed nonzero 63-bit id
        # per rank — req_mark's cost is value-independent.  The arm
        # brackets each timed chunk exactly the way the serving plane
        # brackets each run (tag at entry, 0 at exit, both inside the
        # run wall); a chunk is a far SHORTER "run" than serving ever
        # issues, so the bracket cost is overstated, never hidden
        req_tid = 0x7e57_0000 + comm.rank + 1
        # acc[block][mode] = accumulated seconds over that block's
        # SUB_ROUNDS micro-chunks of that arm
        acc = [[0.0] * 4 for _ in range(BLOCKS)]
        for b in range(BLOCKS):
            for s in range(SUB_ROUNDS):
                # 0 = off, 1 = on, 2 = on + phase spans,
                # 3 = on + per-op request tag.  Palindromic visit
                # order (odd visits reverse) so no arm always trails
                # the others inside a regime
                rev = (b * SUB_ROUNDS + s) % 2 == 1
                for pos in range(4):
                    mode = 3 - pos if rev else pos
                    comm.Barrier()
                    # every rank flips ITS OWN state: the shim and
                    # the device dispatch read state.tracer per call,
                    # so None here is exactly the trace-off contract
                    # (one is-None check).  Mode 2 additionally arms
                    # the per-op phase profiler via the same
                    # attribute the trace_phase_enable knob sets at
                    # attach — the hot-path gate is ``tr.phase``,
                    # read per op.
                    comm.state.tracer = tr if mode else None
                    tr.phase = mode == 2
                    comm.Barrier()
                    t0 = time.perf_counter()
                    if mode == 3:
                        tr.req_mark(req_tid)
                        for _ in range(CHUNK_OPS):
                            comm.Allreduce(sbuf, rbuf, SUM)
                        tr.req_mark(0)
                    else:
                        for _ in range(CHUNK_OPS):
                            comm.Allreduce(sbuf, rbuf, SUM)
                    acc[b][mode] += time.perf_counter() - t0
        off_blocks = [acc[b][0] / BLOCK_OPS * 1e6 for b in range(BLOCKS)]
        on_blocks = [acc[b][1] / BLOCK_OPS * 1e6 for b in range(BLOCKS)]
        phase_blocks = [acc[b][2] / BLOCK_OPS * 1e6
                        for b in range(BLOCKS)]
        req_blocks = [acc[b][3] / BLOCK_OPS * 1e6 for b in range(BLOCKS)]
        comm.state.tracer = tr
        tr.phase = phase0
        comm.Barrier()
        out: Dict = {"off_us_blocks": off_blocks,
                     "on_us_blocks": on_blocks,
                     "phase_us_blocks": phase_blocks,
                     "req_us_blocks": req_blocks}
        if comm.rank != 0:
            return out
        from ompi_tpu import mpit, trace
        out["spans"] = {cat: tr.span_count(cat)
                        for cat in ("coll", "coll_dispatch", "p2p",
                                    "phase")}
        out["recorded"] = tr.recorded
        out["dropped"] = tr.dropped
        # snapshot through MPI_T itself (not the Tracer object): the
        # pvar surface is what bench consumers get
        mpit.init_thread()
        try:
            sess = mpit.pvar_session_create()
            out["hists"] = {}
            for name in trace.HIST_NAMES:
                ph = mpit.pvar_handle_alloc(
                    sess, f"trace_hist_{name}")
                out["hists"][name] = mpit.pvar_read(ph)
            out["sampling"] = mpit.pvar_read(
                mpit.pvar_handle_alloc(sess, "trace_sampling_rate"))
            out["dropped_by_cat"] = {
                cat: mpit.pvar_read(mpit.pvar_handle_alloc(
                    sess, f"trace_dropped_{cat}"))
                for cat in trace.SPAN_CATS}
            mpit.pvar_session_free(sess)
        finally:
            mpit.finalize()
        return out

    return run_ranks(NRANKS, fn, timeout=600)[0]


def _integrity_world() -> Dict:
    """Device-mesh companion world for the integrity arm: the §25
    plane gates device collectives at the rendezvous, so its cost is
    measured where it is actually paid.  Two arms (disarmed / armed at
    the sampler's steady-state period), same palindromic micro-chunk
    interleave and per-block pairing as the host world.  The arm
    toggle is ``integrity.set_armed`` — the exact module flag the
    coll hot path reads per op, so the disarmed chunks price the
    always-on ``_ig.on`` check honestly rather than a world that
    never imported the plane."""
    from ompi_tpu.obs import integrity as ig
    from ompi_tpu.op.op import SUM
    from ompi_tpu.testing import run_ranks

    def fn(comm):
        import jax.numpy as jnp
        x = jnp.full((8,), float(comm.rank + 1), jnp.float32)
        for _ in range(WARMUP):
            comm.allreduce_arr(x, SUM)
        # ramp the adaptive integrity sampler to its steady-state
        # period cap before anything is timed (same disclosure model
        # as the trace sampler's RAMP_OPS)
        ig.set_armed(True)
        for _ in range(I_RAMP_OPS):
            comm.allreduce_arr(x, SUM)
        acc = [[0.0] * 2 for _ in range(I_BLOCKS)]
        for b in range(I_BLOCKS):
            for s in range(I_SUB_ROUNDS):
                rev = (b * I_SUB_ROUNDS + s) % 2 == 1
                for pos in range(2):
                    mode = 1 - pos if rev else pos
                    comm.Barrier()
                    # every rank sets the same value between barriers
                    # (the flag is module-global across rank threads,
                    # so the writes are idempotent, never racing)
                    ig.set_armed(mode == 1)
                    comm.Barrier()
                    t0 = time.perf_counter()
                    for _ in range(I_CHUNK_OPS):
                        comm.allreduce_arr(x, SUM)
                    acc[b][mode] += time.perf_counter() - t0
        ig.set_armed(True)
        comm.Barrier()
        return {"ig_off_us_blocks": [acc[b][0] / I_BLOCK_OPS * 1e6
                                     for b in range(I_BLOCKS)],
                "ig_on_us_blocks": [acc[b][1] / I_BLOCK_OPS * 1e6
                                    for b in range(I_BLOCKS)]}

    return run_ranks(NRANKS, fn, devices=True, timeout=600)[0]


def run_probe() -> Dict:
    from ompi_tpu.mca.params import registry

    # the integrity arm's device mesh needs a multi-device CPU
    # backend; force it before anything imports jax (probe_rma idiom)
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            f" --xla_force_host_platform_device_count={NRANKS}")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    registry.set("trace_enable", "1")
    # big enough that KEPT spans never wrap (the sampler caps kept
    # volume at ~2k per category): a drop-heavy ring would
    # under-report the recording cost
    registry.set("trace_buffer_events", "16384")
    # the probe measures tracing alone: the autotune loop (its own lp
    # callback + periodic folds) must not ride along on either side
    registry.set("coll_autotune_enable", "0")
    try:
        snap = _probe_world()
    finally:
        registry.set("trace_enable", "0")

    # integrity arm: its own device world, armed via the knobs the
    # refresh() at mpi_init reads; auto=2 ramps the sampler to the
    # 1-in-64 steady state inside I_RAMP_OPS
    from ompi_tpu.obs import integrity as ig
    ig_saved = {k: registry.get(k) for k in
                ("integrity_enable", "integrity_sample",
                 "integrity_sample_auto")}
    registry.set("integrity_enable", "1")
    registry.set("integrity_sample", "64")
    registry.set("integrity_sample_auto", "2")
    ig_checks0 = registry._pvars["integrity_checks"].read()
    try:
        isnap = _integrity_world()
    finally:
        for k, v in ig_saved.items():
            registry.set(k, v)
        ig.refresh()
    ig_checks = registry._pvars["integrity_checks"].read() - ig_checks0

    off_times = snap["off_us_blocks"]
    on_times = snap["on_us_blocks"]
    phase_times = snap["phase_us_blocks"]
    req_times = snap["req_us_blocks"]
    off_us = min(off_times)
    on_us = min(on_times)
    off_med = statistics.median(off_times)
    on_med = statistics.median(on_times)
    phase_med = statistics.median(phase_times)
    req_med = statistics.median(req_times)
    overhead_best = (on_us - off_us) / off_us * 100.0

    # acceptance statistic: pair each arm with the untraced aggregate
    # of the SAME block (index b of every list is block b, and the
    # four aggregates of a block are built from micro-chunks
    # interleaved through the same regimes), then take the median of
    # the per-block ratios — a spike contributes one outlier ratio
    # the median discards.
    def _paired_med(arm):
        return statistics.median(
            (a - o) / o * 100.0 for a, o in zip(arm, off_times))

    overhead_med = _paired_med(on_times)
    phase_overhead_med = _paired_med(phase_times)
    req_overhead_med = _paired_med(req_times)
    # integrity pairs within ITS OWN device world's blocks — the host
    # world's untraced blocks price a different op entirely
    ig_off = isnap["ig_off_us_blocks"]
    ig_on = isnap["ig_on_us_blocks"]
    ig_overhead_med = statistics.median(
        (a - o) / o * 100.0 for a, o in zip(ig_on, ig_off))
    gil = getattr(sys, "_is_gil_enabled", lambda: True)()
    return {
        "nranks": NRANKS,
        "ops_per_block": BLOCK_OPS,
        "blocks_per_side": BLOCKS,
        "ramp_ops": RAMP_OPS,
        "payload_bytes": 32,
        "host_cores": os.cpu_count(),
        "gil_enabled": bool(gil),
        "gil_note": ("thread ranks share one GIL: per-op bookkeeping "
                     "is fully serialized (worst case for overhead)"
                     if gil else
                     "free-threaded build: ranks overlap, overhead "
                     "partially hides"),
        "off_us_per_op": round(off_us, 2),
        "on_us_per_op": round(on_us, 2),
        "off_us_median": round(off_med, 2),
        "on_us_median": round(on_med, 2),
        "off_us_all": [round(x, 2) for x in off_times],
        "on_us_all": [round(x, 2) for x in on_times],
        "overhead_pct_best": round(overhead_best, 2),
        # the acceptance number: median of per-round paired ratios
        # (overhead_pct keeps its historical name so BENCH_DETAIL
        # consumers stay working — the figure is the drift-robust
        # paired median, the honest long-run cost)
        "overhead_pct": round(overhead_med, 2),
        # phase profiler (DESIGN.md §18): trace ON + per-op phase
        # sub-spans, vs the same untraced blocks, same budget
        "phase_us_median": round(phase_med, 2),
        "phase_us_all": [round(x, 2) for x in phase_times],
        "phase_overhead_pct": round(phase_overhead_med, 2),
        "phase_within_budget": bool(phase_overhead_med <= BUDGET_PCT),
        # request tagging (DESIGN.md §23): trace ON + the serving
        # plane's per-run req_mark bracket around each (short) timed
        # chunk, vs the same untraced blocks, same budget
        "reqtrace_us_median": round(req_med, 2),
        "reqtrace_us_all": [round(x, 2) for x in req_times],
        "reqtrace_overhead_pct": round(req_overhead_med, 2),
        "reqtrace_within_budget": bool(req_overhead_med <= BUDGET_PCT),
        # sdc-integrity plane (DESIGN.md §25): disarmed vs armed at
        # the 1-in-integrity_sample steady state on a device mesh,
        # paired per block inside that world, same budget
        "integrity_nranks": NRANKS,
        "integrity_ops_per_block": I_BLOCK_OPS,
        "integrity_blocks": I_BLOCKS,
        "integrity_ramp_ops": I_RAMP_OPS,
        "integrity_sample_cap": 64,
        "integrity_checks_sampled": ig_checks,
        "integrity_off_us_median": round(statistics.median(ig_off), 2),
        "integrity_us_median": round(statistics.median(ig_on), 2),
        "integrity_off_us_all": [round(x, 2) for x in ig_off],
        "integrity_us_all": [round(x, 2) for x in ig_on],
        "integrity_overhead_pct": round(ig_overhead_med, 2),
        "integrity_within_budget": bool(ig_overhead_med <= BUDGET_PCT),
        "budget_pct": BUDGET_PCT,
        "within_budget": bool(overhead_med <= BUDGET_PCT),
        "traced_spans": snap.get("spans", {}),
        "traced_recorded": snap.get("recorded", 0),
        "traced_dropped": snap.get("dropped", 0),
        "sampling_pvars": snap.get("sampling", {}),
        "dropped_by_cat_pvars": snap.get("dropped_by_cat", {}),
        "hist_pvars": snap.get("hists", {}),
    }


def persist(probe: Dict, detail_path: str) -> Dict:
    """Merge under 'trace_overhead' in BENCH_DETAIL.json, preserving
    every other section (the probe_dispatch/full-sweep pattern)."""
    notes: Dict = {}
    try:
        with open(detail_path) as fh:
            detail = json.load(fh)
        if not isinstance(detail, dict):
            detail = {}
    except (OSError, ValueError):
        detail = {}
    detail["trace_overhead"] = probe
    try:
        tmp = f"{detail_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(detail, fh, indent=1)
        os.replace(tmp, detail_path)
    except OSError as e:
        notes["detail_error"] = str(e)[:120]
    return notes
