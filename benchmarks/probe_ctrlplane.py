"""--probe-ctrlplane microbench: control-plane fault tolerance.

Kills both control-plane processes mid-traffic and proves zero failed
jobs (docs/DESIGN.md §20) — the chaos closure for the replicated KV
store and the journal-rehydrating DVM:

1. **KV primary kill mid-fence.**  A ``KVServer`` with one hot
   standby (``kv_replicas=1``) serves 4 worker threads running a
   Poisson op mix (put/get/incr) punctuated by n=4 fences.  The
   primary is crashed while three workers are PARKED inside a fence —
   the hardest replicated-state case: the promoted standby must
   complete that fence from replicated arrivals plus cid-deduped
   re-sends, never re-create it.  Reported: kill -> first-completed-op
   MTTR per worker (max = the headline), retries/reconnects/failovers
   pvars, and the op failure count, gated at zero.

2. **DVM kill mid-run.**  A real subprocess pool under the
   ``Supervisor`` with ``ft_inject dvm_kill`` armed serves 4
   concurrent sessions; the armed op count lands the death while runs
   are in flight.  The supervisor respawns the server, which
   rehydrates its session table from the write-ahead journal; each
   client reconnects, reattaches by token and replays its in-flight
   jobid — the journal dedup makes the replay exactly-once.
   Reported: kill -> first-completed-job MTTR (includes the cold
   respawn: interpreter + jax import) and the job failure count,
   gated at zero.

Also measured: raw KV op throughput with ``kv_replicas=0`` (the
default single-server fast path) vs ``kv_replicas=1``, so the
replication tax is a number, not a hope.

Results land in BENCH_DETAIL.json under ``probe_ctrlplane``.
"""

from __future__ import annotations

import json
import os
import random
import sys
import threading
import time
from typing import Dict, List, Optional

WORKERS = 4              # concurrent KV workers / DVM sessions
KV_ROUNDS = 5            # fence rounds per KV worker
KV_OPS_PER_ROUND = 25
KV_KILL_ROUND = 2        # primary dies inside this round's fence
TPUT_OPS = 600           # ops for the replicas=0 vs 1 throughput pair
DVM_JOBS = 3             # jobs per DVM session across the kill
DVM_KILL_AFTER_OPS = 12  # armed dvm_kill op count (lands mid-traffic)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _kv_phase() -> Dict:
    from ompi_tpu.runtime.kvstore import KVClient, KVServer, _kv_pvars

    srv = KVServer(WORKERS, replicas=1)
    clients = [KVClient(srv.uri) for _ in range(WORKERS)]
    done: List[List[float]] = [[] for _ in range(WORKERS)]
    fails: List[str] = []
    flock = threading.Lock()
    armed = threading.Event()   # worker 0 reached the kill round
    pv0 = {p.full_name: p.read() for p in _kv_pvars()}

    def worker(i: int) -> None:
        c = clients[i]
        rng = random.Random(7 + i)
        try:
            for rnd in range(KV_ROUNDS):
                for k in range(KV_OPS_PER_ROUND):
                    r = rng.random()
                    if r < 0.5:
                        c.put(f"w{i}/k{rnd}.{k}", "v")
                    elif r < 0.8:
                        c.put(f"w{i}/g{rnd}.{k}", k)
                        c.get(f"w{i}/g{rnd}.{k}", timeout=30)
                    else:
                        c.incr(f"w{i}/ctr")
                    done[i].append(time.perf_counter())
                    time.sleep(rng.expovariate(500))  # ~2ms Poisson
                if rnd == KV_KILL_ROUND:
                    if i == 0:
                        armed.set()
                    if i == WORKERS - 1:
                        # the last arriver hangs back so the other
                        # three are PARKED in the fence when the
                        # primary dies
                        time.sleep(0.3)
                c.fence(f"R{rnd}", n=WORKERS)
                done[i].append(time.perf_counter())
        except Exception as e:  # noqa: BLE001
            with flock:
                fails.append(f"kv worker {i}: {e!r}")

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(WORKERS)]
    for t in threads:
        t.start()
    armed.wait(timeout=60)
    time.sleep(0.1)           # three workers parked in the fence now
    t_kill = time.perf_counter()
    srv.crash()               # hard primary death, standby promotes
    for t in threads:
        t.join(timeout=120)
    hung = any(t.is_alive() for t in threads)
    mttrs = []
    for i in range(WORKERS):
        after = [t for t in done[i] if t > t_kill]
        if after:
            mttrs.append((after[0] - t_kill) * 1e3)
    pv = {p.full_name: p.read() - pv0[p.full_name]
          for p in _kv_pvars()}
    for c in clients:
        c.close()
    srv.close()
    ops = sum(len(d) for d in done)
    # NOTE this is NOT the failover latency: the three parked workers
    # cannot complete the fence until the deliberate 0.3s straggler
    # arrives, so this measures the whole chaos choreography.  The
    # warm failover number comes from _kv_warm_failover().
    return {
        "workers": WORKERS,
        "ops": ops,
        "failed_ops": len(fails),
        "failures": fails[:3],
        "hung_workers": int(hung),
        "fence_complete_ms": round(max(mttrs), 3) if mttrs else -1.0,
        "pvars": pv,
    }


def _kv_warm_failover() -> float:
    """Kill → first-completed-op with nothing in the way: one client
    streaming back-to-back puts, primary crashed mid-stream.  This is
    the number the ~10ms warm target speaks to — pure detect + rotate
    + reconnect + re-send, no fence choreography."""
    from ompi_tpu.runtime.kvstore import KVClient, KVServer

    srv = KVServer(1, replicas=1)
    c = KVClient(srv.uri)
    done: List[float] = []
    stop = threading.Event()

    def stream() -> None:
        k = 0
        while not stop.is_set():
            c.put(f"wf/{k & 63}", k)
            done.append(time.perf_counter())
            k += 1

    t = threading.Thread(target=stream, daemon=True)
    t.start()
    time.sleep(0.15)          # mid-stream
    t_kill = time.perf_counter()
    srv.crash()
    time.sleep(1.0)           # let the client fail over and resume
    stop.set()
    t.join(timeout=30)
    c.close()
    srv.close()
    after = [x for x in done if x > t_kill]
    return (after[0] - t_kill) * 1e3 if after else -1.0


def _kv_throughput(replicas: int) -> float:
    from ompi_tpu.runtime.kvstore import KVClient, KVServer

    srv = KVServer(1, replicas=replicas)
    c = KVClient(srv.uri)
    for k in range(32):      # warm the socket + server threads
        c.put(f"warm/{k}", k)
    t0 = time.perf_counter()
    for k in range(TPUT_OPS):
        c.put(f"t/{k & 63}", k)
    dt = time.perf_counter() - t0
    c.close()
    srv.close()
    return TPUT_OPS / dt if dt > 0 else 0.0


def _dvm_phase() -> Dict:
    import tempfile
    import textwrap

    from ompi_tpu.tools.dvm import DvmClient, Supervisor

    tmpdir = tempfile.mkdtemp(prefix="probe_ctrlplane_")
    uri = os.path.join(tmpdir, "dvm.uri")
    prog = os.path.join(tmpdir, "job.py")
    with open(prog, "w") as f:
        f.write(textwrap.dedent("""
            import time
            import numpy as np
            import ompi_tpu
            from ompi_tpu.op import op as mpi_op
            comm = ompi_tpu.init()
            time.sleep(0.2)
            x = np.full(8, comm.rank + 1.0, dtype=np.float32)
            r = np.empty_like(x)
            comm.Allreduce(x, r, mpi_op.SUM)
            assert abs(float(r[0])
                       - sum(range(1, comm.size + 1))) < 1e-3
            ompi_tpu.finalize()
        """))
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    # arm the deterministic mid-traffic death: the server hard-exits
    # serving its Nth op (attaches + runs from 4 sessions land N
    # squarely inside concurrent runs)
    env["TPUMPI_MCA_ft_inject_plan"] = \
        f"dvm_kill:{DVM_KILL_AFTER_OPS}"
    # respawns come up with the plan CLEARED — kill once, then heal
    # (otherwise every incarnation re-arms and dies at the same op)
    heal_env = dict(env)
    del heal_env["TPUMPI_MCA_ft_inject_plan"]
    sup = Supervisor(
        [sys.executable, "-m", "ompi_tpu.tools.dvm",
         "--np", str(WORKERS), "--uri-file", uri,
         "--devices", "none"], env=env,
        respawn_env=heal_env).start()
    try:
        for _ in range(600):
            if os.path.exists(uri):
                break
            time.sleep(0.1)
        else:
            raise RuntimeError("DVM pool never wrote its uri file")
        pid0 = sup.proc.pid
        done: List[List[float]] = [[] for _ in range(WORKERS)]
        fails: List[str] = []
        flock = threading.Lock()

        def session(i: int) -> None:
            try:
                c = DvmClient(uri, connect_timeout=30.0)
                sid = c.attach(1, timeout=120)["sid"]
                for _ in range(DVM_JOBS):
                    r = c.run(sid, prog, timeout=180)
                    if r["code"] != 0:
                        raise RuntimeError(
                            f"job rc={r['code']}: "
                            f"{r['stderr'][-200:]}")
                    done[i].append(time.perf_counter())
                c.detach(sid)
                c.close()
            except Exception as e:  # noqa: BLE001
                with flock:
                    fails.append(f"dvm session {i}: {e!r}")

        threads = [threading.Thread(target=session, args=(i,),
                                    daemon=True)
                   for i in range(WORKERS)]
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        # the armed injector kills the server; note when the pid dies
        t_kill: Optional[float] = None
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if sup.proc is not None and sup.proc.pid != pid0:
                t_kill = time.perf_counter()  # respawned already
                break
            try:
                os.kill(pid0, 0)
            except OSError:
                t_kill = time.perf_counter()
                break
            if all(not t.is_alive() for t in threads):
                break
            time.sleep(0.01)
        for t in threads:
            t.join(timeout=300)
        hung = any(t.is_alive() for t in threads)
        mttrs = []
        if t_kill is not None:
            for i in range(WORKERS):
                after = [t for t in done[i] if t > t_kill]
                if after:
                    mttrs.append((after[0] - t_kill) * 1e3)
        jobs = sum(len(d) for d in done)
        restarts = sup.restarts
    finally:
        sup.stop(kill=True)
        import shutil
        shutil.rmtree(tmpdir, ignore_errors=True)
    return {
        "sessions": WORKERS,
        "jobs_per_session": DVM_JOBS,
        "jobs_done": jobs,
        "failed_jobs": len(fails),
        "failures": fails[:3],
        "hung_sessions": int(hung),
        "killed": bool(t_kill is not None),
        "supervisor_restarts": restarts,
        "dvm_restart_mttr_ms": round(max(mttrs), 1) if mttrs else -1.0,
        "wall_s": round(time.perf_counter() - t_start, 2),
    }


def run_probe() -> Dict:
    kv = _kv_phase()
    warm_ms = _kv_warm_failover()
    r0 = _kv_throughput(0)
    r1 = _kv_throughput(1)
    dvm = _dvm_phase()
    overhead = (100.0 * (r0 - r1) / r0) if r0 > 0 else 0.0
    ok = (kv["failed_ops"] == 0 and kv["hung_workers"] == 0
          and kv["fence_complete_ms"] >= 0 and warm_ms >= 0
          and dvm["failed_jobs"] == 0 and dvm["hung_sessions"] == 0
          and dvm["killed"]
          and dvm["jobs_done"] == WORKERS * DVM_JOBS)
    return {
        "kv": kv,
        "dvm": dvm,
        "kv_failover_mttr_ms": round(warm_ms, 3),
        "kv_fence_complete_ms": kv["fence_complete_ms"],
        "dvm_restart_mttr_ms": dvm["dvm_restart_mttr_ms"],
        "failed_jobs": kv["failed_ops"] + dvm["failed_jobs"],
        "kv_ops_per_s_r0": round(r0, 1),
        "kv_ops_per_s_r1": round(r1, 1),
        "kv_repl_overhead_pct": round(overhead, 2),
        "within_budget": bool(ok),
    }


def persist(probe: Dict, detail_path: str) -> Dict:
    """Merge under 'probe_ctrlplane' in BENCH_DETAIL.json, preserving
    every other section (the probe_serve pattern)."""
    notes: Dict = {}
    try:
        with open(detail_path) as fh:
            detail = json.load(fh)
        if not isinstance(detail, dict):
            detail = {}
    except (OSError, ValueError):
        detail = {}
    detail["probe_ctrlplane"] = probe
    try:
        tmp = f"{detail_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(detail, fh, indent=1)
        os.replace(tmp, detail_path)
    except OSError as e:
        notes["detail_error"] = str(e)[:120]
    return notes


if __name__ == "__main__":
    doc = run_probe()
    json.dump(doc, sys.stdout, indent=1)
    print()
    sys.exit(0 if doc["within_budget"] else 1)
