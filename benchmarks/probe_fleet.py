"""--probe-fleet microbench: the overload-robust serving control
plane (ISSUE 12), proven against a live in-process pool:

1. **Priority under 2x overload.**  Four low-priority preemptible
   submitters offer twice the pool's rank capacity in attach/run/
   detach cycles; a high-priority client preempts its way to the
   whole pool and pumps a burst of runs through it.  The claim:
   high-priority p99 stays within PRIORITY_FACTOR (2x) of the
   unloaded baseline p99, while the dvm_preemptions / dvm_sheds
   pvars show the low tier actually paid for it — and every
   low-priority job still completes or sheds, none fail.

2. **Preemption resumes from checkpoint, byte-identical.**  A
   checkpointing victim is preempted mid-run by a high-priority
   attach; its single (slower) run must return rc 0 with the same
   digest as an unpreempted baseline, and its STEPS line must show
   a nonzero resume point.

3. **Live resize under traffic.**  Grow 4->8, shrink 8->4 while
   submitters stream jobs: zero failed jobs, both pool epochs
   recorded, and every ScopedPvar holds global == sum(bands)
   (attribution exactness across resize epochs).

4. **N-host mode (ISSUE 16, DESIGN.md §21).**  A 2-host fleet with
   two REAL ``tpud --fleet`` host-agent subprocesses.  One attach
   commands a world spanning both domains; host 1's daemon is then
   SIGKILLed mid-collective so the pool's heartbeat-silence detector
   (not an RPC shortcut) marks the whole domain lost — the ULFM
   survivors shrink around ONE atomic failure set and the job still
   exits 0.  ``host_kill_mttr_ms`` (daemon SIGKILL -> domain
   respawned) is the --regress-tracked recovery metric.  Then
   host-granularity resize under traffic: submitters stream
   DCN-spanning jobs while host 1 is killed and respawned under
   them — ZERO failed jobs (in-flight runs replay transparently on
   the rehydrated fleet), and a fresh agent re-registers under the
   same fleet incarnation.

Results land in BENCH_DETAIL.json under ``probe_fleet``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List

CAPACITY = 4             # pool rank capacity, parts 1 and 3
LOW_SUBMITTERS = 4       # 4 x np2 = 2x the pool's capacity offered
LOW_NP = 2
LOW_CYCLES = 5           # attach/run/detach cycles per low submitter
HI_NP = 4                # the high tier claims the whole pool
HI_RUNS = 10
BASELINE_RUNS = 10
PRIORITY_FACTOR = 2.0    # hi p99 under overload vs unloaded p99
CKPT_STEPS = 10
CKPT_SLEEP_S = 0.2

HOSTS = 2                # fleet width of the N-host probe
HOST_STEPS = 120         # shrink-arm workload loop bound
HOST_TRAFFIC_RUNS = 8    # per streaming submitter, part 4
HOST_TRAFFIC_PACE_S = 0.08  # inter-run pacing so the kill lands
                            # under live traffic, not after it

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROG = os.path.join(REPO, "tests", "_dvm_prog.py")
CKPT_PROG = os.path.join(REPO, "tests", "_fleet_ckpt_prog.py")
HOST_PROG = os.path.join(REPO, "tests", "_fleet_host_prog.py")


def _pct(sorted_vals: List[float], p: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[i]


def _pv(name: str) -> int:
    from ompi_tpu.mca.params import registry
    return int(registry._pvars[name].read())


def _digest_line(stdout: str, kind: str, tag: str) -> str:
    for line in stdout.splitlines():
        parts = line.split()
        if len(parts) == 3 and parts[0] == kind and parts[1] == tag:
            return parts[2]
    raise RuntimeError(f"no {kind} {tag} line in session stdout")


def _new_pool(tmpdir: str, capacity: int):
    import jax

    from ompi_tpu.tools.dvm import DVMServer
    uri = os.path.join(tmpdir, f"dvm-{capacity}-{time.time_ns()}.uri")
    srv = DVMServer(capacity, devices=jax.devices(), uri_file=uri)
    srv.start()
    return srv, uri


# -- part 1: priority under 2x overload -------------------------------------


def _probe_overload(tmpdir: str) -> Dict:
    from ompi_tpu.tools.dvm import DvmBusy, DvmClient, DvmDeadline

    srv, uri = _new_pool(tmpdir, CAPACITY)
    try:
        # unloaded baseline: one resident high-style session, alone
        base_s: List[float] = []
        c = DvmClient(uri)
        sid = c.attach(HI_NP)["sid"]
        for i in range(BASELINE_RUNS + 1):
            t0 = time.perf_counter()
            r = c.run(sid, PROG, timeout=120)
            if r["code"] != 0:
                raise RuntimeError(f"baseline rc={r['code']}: "
                                   f"{r['stderr'][-200:]}")
            if i > 0:  # rep 0 warms the pool
                base_s.append(time.perf_counter() - t0)
        c.detach(sid)
        c.close()
        base_s.sort()
        base_p99 = _pct(base_s, 99.0)
        base_med_ms = _pct(base_s, 50.0) * 1e3

        p0, s0 = _pv("dvm_preemptions"), _pv("dvm_sheds")
        lock = threading.Lock()
        low_done: List[float] = []
        low_shed = [0]
        errs: List[str] = []

        low_deadline_ms = max(50, int(base_med_ms * 20))

        def low_submitter(idx: int) -> None:
            # one-shot overload traffic: paced attach/run/detach
            # cycles with a finite deadline — under deep backlog the
            # widened shed margin rejects infeasible cycles up front
            try:
                for _ in range(LOW_CYCLES):
                    with DvmClient(uri) as cli:
                        try:
                            lsid = cli.attach(
                                LOW_NP, timeout=180,
                                preemptible=True)["sid"]
                        except DvmBusy:
                            continue  # overloaded; that IS the point
                        t0 = time.perf_counter()
                        try:
                            lr = cli.run(
                                lsid, PROG, timeout=180,
                                deadline_ms=low_deadline_ms)
                            if lr["code"] != 0:
                                raise RuntimeError(
                                    f"low job rc={lr['code']}: "
                                    f"{lr['stderr'][-200:]}")
                            with lock:
                                low_done.append(
                                    time.perf_counter() - t0)
                        except DvmDeadline:
                            with lock:
                                low_shed[0] += 1
                        cli.detach(lsid)
                    time.sleep(0.05)
            except Exception as e:  # noqa: BLE001
                with lock:
                    errs.append(f"low {idx}: {e}")

        # one long-running preemptible tenant holds ranks through the
        # high-priority attach — the preemption victim, by construction
        victim_res: Dict = {}

        def long_victim() -> None:
            try:
                with DvmClient(uri) as cli:
                    vsid = cli.attach(LOW_NP, timeout=180,
                                      preemptible=True)["sid"]
                    store = os.path.join(tmpdir, "overload_vic")
                    vr = cli.run(vsid, CKPT_PROG,
                                 ["ov", store, "24", "0.15"],
                                 timeout=300)
                    if vr["code"] != 0:
                        raise RuntimeError(
                            f"victim rc={vr['code']}: "
                            f"{vr['stderr'][-200:]}")
                    victim_res.update(vr)
                    cli.detach(vsid)
                with lock:
                    low_done.append(0.0)
            except Exception as e:  # noqa: BLE001
                with lock:
                    errs.append(f"victim: {e}")

        threads = [threading.Thread(target=low_submitter, args=(i,))
                   for i in range(LOW_SUBMITTERS)]
        threads.append(threading.Thread(target=long_victim))
        for t in threads:
            t.start()
        time.sleep(0.5)  # the low tier saturates the pool first

        hi_s: List[float] = []
        hc = DvmClient(uri)
        hsid = hc.attach(HI_NP, timeout=180, priority=9)["sid"]
        for i in range(HI_RUNS + 1):
            t0 = time.perf_counter()
            r = hc.run(hsid, PROG, timeout=120)
            if r["code"] != 0:
                raise RuntimeError(f"hi rc={r['code']}: "
                                   f"{r['stderr'][-200:]}")
            if i > 0:  # rep 0 is session bring-up warm-up, both sides
                hi_s.append(time.perf_counter() - t0)
        hc.detach(hsid)
        hc.close()
        for t in threads:
            t.join(timeout=300)
        # deterministic shed evidence: with the estimator warm, a
        # 1 ms deadline is infeasible by construction
        with DvmClient(uri) as cli:
            lsid = cli.attach(LOW_NP, timeout=60)["sid"]
            try:
                cli.run(lsid, PROG, timeout=60, deadline_ms=1)
                raise RuntimeError("1 ms deadline was not shed")
            except DvmDeadline:
                low_shed[0] += 1
            cli.detach(lsid)
        if errs:
            raise RuntimeError("; ".join(errs[:3]))
        hi_s.sort()
        hi_p99 = _pct(hi_s, 99.0)
        ratio = hi_p99 / base_p99 if base_p99 > 0 else 0.0
        return {
            "capacity": CAPACITY,
            "low_submitters": LOW_SUBMITTERS,
            "low_np": LOW_NP,
            "hi_np": HI_NP,
            "unloaded_p50_ms": round(base_med_ms, 3),
            "unloaded_p99_ms": round(base_p99 * 1e3, 3),
            "hi_runs": len(hi_s),
            "hi_p50_ms": round(_pct(hi_s, 50.0) * 1e3, 3),
            "hi_p99_ms": round(hi_p99 * 1e3, 3),
            "hi_p99_vs_unloaded": round(ratio, 2),
            "low_jobs_done": len(low_done),
            "low_jobs_shed": low_shed[0],
            "victim_preempted": victim_res.get("preempted", 0),
            "preemptions": _pv("dvm_preemptions") - p0,
            "sheds": _pv("dvm_sheds") - s0,
            "priority_factor": PRIORITY_FACTOR,
            "priority_ok": bool(
                ratio <= PRIORITY_FACTOR
                and _pv("dvm_preemptions") - p0 >= 1
                and _pv("dvm_sheds") - s0 >= 1),
        }
    finally:
        srv.stop()


# -- part 2: preempt -> checkpoint resume, byte-identical -------------------


def _probe_preempt_resume(tmpdir: str) -> Dict:
    from ompi_tpu.tools.dvm import DvmClient

    srv, uri = _new_pool(tmpdir, 2)
    try:
        store_a = os.path.join(tmpdir, "store_base")
        cb = DvmClient(uri)
        sb = cb.attach(2)["sid"]
        rb = cb.run(sb, CKPT_PROG,
                    ["base", store_a, str(CKPT_STEPS)], timeout=240)
        if rb["code"] != 0:
            raise RuntimeError(f"ckpt baseline rc={rb['code']}: "
                               f"{rb['stderr'][-200:]}")
        base_dig = _digest_line(rb["stdout"], "DIGEST", "base")
        cb.detach(sb)
        cb.close()

        store_v = os.path.join(tmpdir, "store_vic")
        cv = DvmClient(uri)
        sv = cv.attach(2, preemptible=True)["sid"]
        res: Dict = {}

        def victim() -> None:
            res["r"] = cv.run(
                sv, CKPT_PROG,
                ["vic", store_v, str(CKPT_STEPS), str(CKPT_SLEEP_S)],
                timeout=240)

        t0 = time.perf_counter()
        th = threading.Thread(target=victim)
        th.start()
        time.sleep(1.0)  # a few steps checkpointed by now
        hi = DvmClient(uri)
        rh = hi.attach(2, priority=9, timeout=120)
        rr = hi.run(rh["sid"], PROG, timeout=120)
        hi.detach(rh["sid"])
        hi.close()
        th.join(timeout=240)
        wall = time.perf_counter() - t0
        r = res["r"]
        resumed_at = int(_digest_line(r["stdout"], "STEPS", "vic"))
        dig = _digest_line(r["stdout"], "DIGEST", "vic")
        ok = (r["code"] == 0 and rr["code"] == 0
              and r.get("preempted", 0) >= 1
              and resumed_at > 0 and dig == base_dig)
        return {
            "steps": CKPT_STEPS,
            "victim_rc": r["code"],
            "victim_preempted": r.get("preempted", 0),
            "resumed_at_step": resumed_at,
            "digest_matches_baseline": bool(dig == base_dig),
            "victim_wall_s": round(wall, 3),
            "resume_ok": bool(ok),
        }
    finally:
        srv.stop()


# -- part 3: live resize under traffic --------------------------------------


def _probe_resize(tmpdir: str) -> Dict:
    from ompi_tpu import obs as _obs
    from ompi_tpu.tools.dvm import DvmClient

    srv, uri = _new_pool(tmpdir, CAPACITY)
    try:
        z0 = _pv("dvm_resizes")
        lock = threading.Lock()
        done = [0]
        errs: List[str] = []

        def worker(idx: int, nruns: int) -> None:
            try:
                with DvmClient(uri) as c:
                    sid = c.attach(2, timeout=180)["sid"]
                    for _ in range(nruns):
                        r = c.run(sid, PROG, timeout=120)
                        if r["code"] != 0:
                            raise RuntimeError(
                                f"rc={r['code']}: "
                                f"{r['stderr'][-200:]}")
                        with lock:
                            done[0] += 1
                    c.detach(sid)
            except Exception as e:  # noqa: BLE001
                with lock:
                    errs.append(f"worker {idx}: {e}")

        threads = [threading.Thread(target=worker, args=(i, 4))
                   for i in range(2)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        admin = DvmClient(uri)
        admin.resize(CAPACITY * 2)
        extra = threading.Thread(target=worker, args=(2, 3))
        extra.start()  # rides the grown headroom
        threads.append(extra)
        time.sleep(0.3)
        admin.resize(CAPACITY)
        for t in threads:
            t.join(timeout=300)
        st = admin.stats()
        admin.close()
        exact = []
        for sp in _obs.scoped_items():
            g, s = sp.pvar.read(), sum(sp.bands)
            if g != s:
                exact.append(f"{sp.pvar.full_name}: {g} != {s}")
        ok = (not errs and done[0] == 11
              and st["capacity"] == CAPACITY and st["epoch"] == 2
              and not exact)
        return {
            "capacity": CAPACITY,
            "grow_to": CAPACITY * 2,
            "jobs_done": done[0],
            "jobs_failed": len(errs),
            "failures": errs[:3],
            "resizes": _pv("dvm_resizes") - z0,
            "final_capacity": st["capacity"],
            "pool_epoch": st["epoch"],
            "band_sum_violations": exact[:5],
            "band_sums_exact": bool(not exact),
            "resize_ok": bool(ok),
        }
    finally:
        srv.stop()


# -- part 4: N-host fleet — whole-host death under ULFM + traffic -----------


def _spawn_agent(uri: str, host: int) -> subprocess.Popen:
    """One REAL tpud host-agent process per failure domain: its PID is
    the liveness signal the pool's silence detector watches."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-m", "ompi_tpu.tools.tpud",
         "--fleet", uri, "--host", str(host)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def _wait(pred, timeout: float, what: str) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise RuntimeError(f"timed out waiting for {what}")


def _host_lines(stdout: str, kind: str, tag: str) -> List[List[str]]:
    out = []
    for line in stdout.splitlines():
        parts = line.split()
        if len(parts) >= 3 and parts[0] == kind and parts[1] == tag:
            out.append(parts[2:])
    return out


def _probe_hosts(tmpdir: str) -> Dict:
    import jax

    from ompi_tpu.mca.params import registry
    from ompi_tpu.tools.dvm import DVMServer, DvmClient

    # tighten the beat so the silence horizon (3 beats + host grace)
    # is probe-sized; the agents pace themselves off the grace the
    # pool hands back at registration
    hb0 = registry.get("dvm_heartbeat_s")
    registry.set("dvm_heartbeat_s", 0.2)
    uri = os.path.join(tmpdir, f"fleet-{time.time_ns()}.uri")
    srv = DVMServer(CAPACITY, devices=jax.devices(), uri_file=uri,
                    hosts=HOSTS)
    srv.start()
    agents: Dict[int, subprocess.Popen] = {}
    try:
        for h in range(HOSTS):
            agents[h] = _spawn_agent(uri, h)
        _wait(lambda: all(b > 0 for b in srv._host_beat), 120,
              "both tpud host agents to register")

        # -- multi-host attach + SIGKILL a daemon mid-collective ----
        # control ops ride their own client: `c`'s socket is busy
        # inside the blocking run RPC when the respawn lands
        admin = DvmClient(uri)
        c = DvmClient(uri)
        r = c.attach(CAPACITY, timeout=180)
        attach_hosts = int(r.get("hosts", 1))
        sid = r["sid"]
        res: Dict = {}

        def chaos_run() -> None:
            res.update(c.run(sid, HOST_PROG,
                             ["pf", str(HOST_STEPS)], timeout=300))

        th = threading.Thread(target=chaos_run)
        th.start()
        _wait(lambda: srv.sessions[sid].running, 60, "chaos session")
        time.sleep(0.6)  # mid-loop, far from step HOST_STEPS
        t_kill = time.perf_counter()
        agents[1].send_signal(signal.SIGKILL)  # a real dead daemon
        _wait(lambda: srv._host_dead[1] == 1, 60,
              "heartbeat silence to mark host 1 lost")
        detect_ms = (time.perf_counter() - t_kill) * 1e3
        respawn_ms = float(admin.respawn_host(1)["mttr_ms"])
        th.join(timeout=300)
        code = res.get("code", -1)
        shrinks = _host_lines(res.get("stdout", ""), "SHRINKS", "pf")
        digs = _host_lines(res.get("stdout", ""), "DIGEST", "pf")
        survivors = sorted(int(s[0]) for s in shrinks)
        one_set = bool(survivors == [0, 1]
                       and all(int(s[1]) == 1 for s in shrinks))
        identical = bool(len(digs) == 2 and digs[0] == digs[1])
        c.detach(sid)

        # the replacement daemon re-registers under the SAME fleet
        # incarnation (respawn_host reset the domain's beat slot)
        agents[1].wait(timeout=30)
        agents[1] = _spawn_agent(uri, 1)
        _wait(lambda: srv._host_beat[1] > 0, 120,
              "replacement agent to rejoin host 1")

        # -- host-granularity resize under streaming traffic --------
        # np=2 sessions span both domains (rank banding), so killing
        # host 1 poisons every in-flight run; with ULFM off they must
        # REPLAY on the rehydrated fleet — zero failed jobs, the
        # client never sees more than latency
        ulfm0 = registry.get("mpi_ft_ulfm")
        registry.set("mpi_ft_ulfm", 0)
        lock = threading.Lock()
        done = [0]
        errs: List[str] = []
        try:
            def submitter(idx: int) -> None:
                try:
                    with DvmClient(uri) as cli:
                        tsid = cli.attach(2, timeout=180)["sid"]
                        for _ in range(HOST_TRAFFIC_RUNS):
                            tr = cli.run(tsid, PROG, timeout=180)
                            if tr["code"] != 0:
                                raise RuntimeError(
                                    f"rc={tr['code']}: "
                                    f"{tr['stderr'][-200:]}")
                            with lock:
                                done[0] += 1
                            time.sleep(HOST_TRAFFIC_PACE_S)
                        cli.detach(tsid)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errs.append(f"submitter {idx}: {e}")

            threads = [threading.Thread(target=submitter, args=(i,))
                       for i in range(2)]
            for t in threads:
                t.start()
            time.sleep(0.4)  # mid-stream
            admin.kill_host(1)
            # how many live DCN-spanning sessions the domain loss
            # actually took ranks from (respawn pops the record)
            hit = len(srv._host_lost_sids.get(1, []))
            time.sleep(0.3)  # a measurable dead window under traffic
            admin.respawn_host(1)
            for t in threads:
                t.join(timeout=300)
        finally:
            registry.set("mpi_ft_ulfm", ulfm0)
        st = admin.stats()
        admin.close()
        c.close()
        zero_failed = bool(not errs
                           and done[0] == 2 * HOST_TRAFFIC_RUNS)
        mttr_ms = detect_ms + respawn_ms
        ok = bool(attach_hosts == HOSTS and code == 0 and one_set
                  and identical and zero_failed and hit >= 1
                  and st["hosts"] == HOSTS and st["hosts_lost"] == 0
                  and st["hosts_rehydrating"] == 0)
        return {
            "hosts": HOSTS,
            "agent": "tpud --fleet subprocess",
            "attach_hosts": attach_hosts,
            "chaos_rc": code,
            "single_failure_set": one_set,
            "survivor_digests_identical": identical,
            "silence_detect_ms": round(detect_ms, 3),
            "respawn_ms": round(respawn_ms, 3),
            "host_kill_mttr_ms": round(mttr_ms, 3),
            "traffic_jobs_done": done[0],
            "traffic_jobs_failed": len(errs),
            "traffic_sessions_hit": hit,
            "failures": errs[:3],
            "hosts_lost_final": st["hosts_lost"],
            "hosts_ok": ok,
        }
    finally:
        for p in agents.values():
            if p.poll() is None:
                p.kill()
        srv.stop()
        registry.set("dvm_heartbeat_s", hb0)


def run_probe() -> Dict:
    import tempfile

    tmpdir = tempfile.mkdtemp(prefix="probe_fleet_")
    try:
        overload = _probe_overload(tmpdir)
        resume = _probe_preempt_resume(tmpdir)
        resize = _probe_resize(tmpdir)
        hosts = _probe_hosts(tmpdir)
    finally:
        import shutil
        shutil.rmtree(tmpdir, ignore_errors=True)
    return {
        "overload": overload,
        "preempt_resume": resume,
        "resize": resize,
        "hosts": hosts,
        "within_budget": bool(overload["priority_ok"]
                              and resume["resume_ok"]
                              and resize["resize_ok"]
                              and hosts["hosts_ok"]),
    }


def persist(probe: Dict, detail_path: str) -> Dict:
    """Merge under 'probe_fleet' in BENCH_DETAIL.json, preserving
    every other section (the probe_dispatch/trace_overhead pattern)."""
    notes: Dict = {}
    try:
        with open(detail_path) as fh:
            detail = json.load(fh)
        if not isinstance(detail, dict):
            detail = {}
    except (OSError, ValueError):
        detail = {}
    detail["probe_fleet"] = probe
    try:
        tmp = f"{detail_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(detail, fh, indent=1)
        os.replace(tmp, detail_path)
    except OSError as e:
        notes["detail_error"] = str(e)[:120]
    return notes
