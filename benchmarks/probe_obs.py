"""--probe-obs microbench: the fleet telemetry plane's cost and truth.

Three acceptance questions for the observability layer
(ompi_tpu/obs, docs/DESIGN.md §16), answered in one run:

1. **What does the scrape tick cost on the hot path?**  The Scraper
   rides the progress sweep's SAMPLED tracer-timing reads (1 in 16
   sweeps, reusing the timestamp already taken — zero clock reads of
   its own), with a whole-histogram integer copy only when
   ``obs_scrape_interval_ms`` elapses.  Methodology is
   trace_overhead's: ONE 4-rank thread-rank
   world, the scrape tick flipped between INTERLEAVED blocks (off,
   on, off, on, ...) so scheduler/placement modes cancel, judged on
   the MEDIAN over block pairs.  The measured op is a small ring
   sendrecv — p2p waits spin on the progress engine, so every op
   drives many sweeps (the sweep IS the instrumented path; device
   collectives rendezvous without sweeping and would measure
   nothing).  The interval is pinned to 1 ms — far hotter than the
   100 ms default — so the budget is enforced against the worst
   configured cadence.

2. **Does per-session attribution add up?**  A live pool (capacity 8)
   serves 4 concurrent sessions; a ``metrics`` RPC scrape taken while
   the pool is resident must show, for EVERY ScopedPvar, the global
   counter equal to the sum over all session bands (band 0 =
   unattributed included).  No tolerance: these are integer counters
   on one path.

3. **Does the flight recorder round-trip?**  At least one recorded
   event must come back through BOTH operator surfaces: live via
   ``ompi_tpu-attach --events`` (the metrics RPC), and after halt via
   the persisted ``<uri>.events.json`` ring merged by traceview onto
   the perfetto timeline.

``within_budget`` requires all three: median scrape overhead <= 5%%,
attribution exact, and the event round-trip intact.  Results land in
BENCH_DETAIL.json under ``probe_obs``; ``bench.py --probe-obs`` exits
nonzero when any leg fails.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import statistics
import sys
import threading
import time
from typing import Dict, List

NRANKS = 4
WARMUP = 50        # untimed warm ops before anything else
RAMP_OPS = 2000    # traced ops to settle the adaptive sampler
BLOCK_OPS = 1500   # ring sendrecvs per measured block
BLOCKS = 5         # interleaved off/on block pairs
BUDGET_PCT = 5.0   # acceptance bound for the scrape-on path (median)
SCRAPE_MS = 1      # worst-cadence interval under test (default: 100)

CAPACITY = 8       # pool rank capacity for the attribution leg
SESSIONS = 4       # concurrent sessions (the acceptance bar)
SESSION_NP = 2     # 4 x 2 = 8 ranks resident at once

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROG = os.path.join(REPO, "tests", "_dvm_session_prog.py")


# -- leg 1: scrape-tick overhead on the progress sweep ----------------------

def _overhead_world() -> Dict:
    """One thread-rank world alternating scrape-off/scrape-on blocks;
    returns rank 0's per-block timings plus the scraper's own
    refresh count (proof the on-side actually scraped)."""
    import numpy as np

    from ompi_tpu.testing import run_ranks

    def fn(comm):
        sbuf = np.ones(8, dtype=np.float32)
        rbuf = np.zeros(8, dtype=np.float32)
        nxt = (comm.rank + 1) % comm.size
        prv = (comm.rank - 1) % comm.size
        st = comm.state
        sc = st.progress.obs
        assert sc is not None  # trace on + interval > 0 => attached

        def op(tag):
            rq = comm.Irecv(rbuf, prv, tag=tag)
            comm.Send(sbuf, nxt, tag=tag)
            rq.wait()

        for _ in range(WARMUP):
            op(1)
        for _ in range(RAMP_OPS):
            op(1)
        ticks0 = sc.ticks
        off_blocks, on_blocks = [], []
        for b in range(BLOCKS * 2):
            scraping = bool(b & 1)
            comm.Barrier()
            # every rank flips ITS OWN progress engine's obs slot:
            # None is exactly the scrape-off contract (one is-None
            # check per sweep — the tracer-slot model)
            st.progress.obs = sc if scraping else None
            comm.Barrier()
            t0 = time.perf_counter()
            for _ in range(BLOCK_OPS):
                op(2)
            dt = time.perf_counter() - t0
            (on_blocks if scraping else off_blocks).append(
                dt / BLOCK_OPS * 1e6)
        st.progress.obs = sc
        comm.Barrier()
        return {"off_us_blocks": off_blocks,
                "on_us_blocks": on_blocks,
                "scrapes": sc.ticks - ticks0,
                "gen": sc.buf[0]}

    return run_ranks(NRANKS, fn, timeout=600)[0]


def _measure_overhead() -> Dict:
    from ompi_tpu.mca.params import registry

    registry.set("trace_enable", "1")
    registry.set("trace_buffer_events", "16384")
    # measure the scrape tick alone: no autotune callback riding the
    # sweep on either side
    registry.set("coll_autotune_enable", "0")
    registry.set("obs_scrape_interval_ms", str(SCRAPE_MS))
    try:
        snap = _overhead_world()
    finally:
        registry.set("trace_enable", "0")
        registry.set("obs_scrape_interval_ms", "100")
    off_times = snap["off_us_blocks"]
    on_times = snap["on_us_blocks"]
    off_med = statistics.median(off_times)
    on_med = statistics.median(on_times)
    overhead_best = ((min(on_times) - min(off_times))
                     / min(off_times) * 100.0)
    overhead_med = (on_med - off_med) / off_med * 100.0
    gil = getattr(sys, "_is_gil_enabled", lambda: True)()
    return {
        "nranks": NRANKS,
        "ops_per_block": BLOCK_OPS,
        "blocks_per_side": BLOCKS,
        "ramp_ops": RAMP_OPS,
        "scrape_interval_ms": SCRAPE_MS,
        "host_cores": os.cpu_count(),
        "gil_enabled": bool(gil),
        "off_us_median": round(off_med, 2),
        "on_us_median": round(on_med, 2),
        "off_us_all": [round(x, 2) for x in off_times],
        "on_us_all": [round(x, 2) for x in on_times],
        "overhead_pct_best": round(overhead_best, 2),
        "overhead_pct": round(overhead_med, 2),
        "scrapes_on_side": snap["scrapes"],
    }


# -- legs 2+3: attribution + event round-trip on a live pool ----------------

def _serve_and_scrape() -> Dict:
    import tempfile

    import jax

    from ompi_tpu import obs
    from ompi_tpu.tools import traceview
    from ompi_tpu.tools.attach import show_events
    from ompi_tpu.tools.dvm import DvmClient, DVMServer

    tmpdir = tempfile.mkdtemp(prefix="probe_obs_")
    uri = os.path.join(tmpdir, "dvm.uri")
    srv = DVMServer(CAPACITY, devices=jax.devices(), uri_file=uri)
    srv.start()
    live_metrics: List[dict] = []
    errs: List[str] = []
    out: Dict = {}
    try:
        barrier = threading.Barrier(SESSIONS + 1, timeout=120)

        def submitter(idx: int) -> None:
            try:
                with DvmClient(uri) as c:
                    sid = c.attach(SESSION_NP, timeout=120)["sid"]
                    barrier.wait()   # all 4 sessions resident at once
                    for _ in range(2):
                        r = c.run(sid, PROG, [f"s{idx}"], timeout=120)
                        if r["code"] != 0:
                            raise RuntimeError(
                                f"job rc={r['code']}: "
                                f"{r['stderr'][-200:]}")
                    barrier.wait()   # hold residency for the scrape
                    c.detach(sid)
            except Exception as e:  # noqa: BLE001
                errs.append(f"submitter {idx}: {e}")

        threads = [threading.Thread(target=submitter, args=(i,))
                   for i in range(SESSIONS)]
        for t in threads:
            t.start()
        barrier.wait()               # 4 sessions attached
        # the LIVE scrape, taken while jobs run — the ranks are never
        # stopped; then release the hold and join
        with DvmClient(uri) as c:
            live_metrics.append(c.metrics(events=64))
        barrier.wait()
        for t in threads:
            t.join()
        if errs:
            raise RuntimeError("; ".join(errs[:3]))
        with DvmClient(uri) as c:
            live_metrics.append(c.metrics(events=64))

        m = live_metrics[-1]
        # attribution: exact for EVERY scoped counter
        bad = []
        for name, ent in m["scoped"].items():
            tot = sum(int(v) for v in ent["bands"].values())
            if tot != ent["global"]:
                bad.append(f"{name}: global {ent['global']} != "
                           f"sum(bands) {tot}")
        session_jobs = {b: v
                        for b, v in m["scoped"]["dvm_jobs"]["bands"]
                        .items() if b != "0" and v}
        out["attribution_ok"] = not bad
        out["attribution_errors"] = bad[:5]
        out["sessions_attributed"] = len(session_jobs)
        out["jobs_by_session"] = session_jobs
        out["pool_jobs"] = m["jobs"]
        out["scraped_ranks_live"] = live_metrics[0]["scraped_ranks"]
        out["percentiles"] = m["percentiles"]
        out["events_recorded"] = m["events_recorded"]
        out["prometheus_lines"] = len(
            m.get("prometheus", "").splitlines())

        # round-trip leg A: live through the attach --events tool
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc_live = show_events(uri, 32)
        live_text = buf.getvalue()
        live_ok = rc_live == 0 and "dvm_attach" in live_text

        # halt persists the ring next to the uri file
        with DvmClient(uri) as c:
            c.halt()
        srv.stop()
        persisted = f"{uri}.events.json"
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc_post = show_events(uri, 32)
        post_text = buf.getvalue()
        post_ok = (rc_post == 0 and "dvm_halt" in post_text
                   and persisted in post_text)

        # round-trip leg B: the persisted ring merges in traceview
        dumps = traceview.load_dumps([persisted])
        doc = traceview.chrome_trace(dumps, [])
        flight = [e for e in doc["traceEvents"]
                  if e.get("cat") == "flight"]
        out["events_roundtrip_ok"] = bool(live_ok and post_ok
                                          and flight)
        out["events_live_tool"] = live_ok
        out["events_persisted_tool"] = post_ok
        out["events_in_traceview_merge"] = len(flight)
        out["flight_ring"] = {"recorded": dumps[0]["recorded"],
                              "dropped": dumps[0]["dropped"],
                              "capacity": dumps[0]["capacity"]}
        assert obs.recorder().recorded >= out["events_recorded"]
    finally:
        try:
            srv.stop()
        except Exception:  # noqa: BLE001
            pass
        import shutil
        shutil.rmtree(tmpdir, ignore_errors=True)
    return out


def run_probe() -> Dict:
    overhead = _measure_overhead()
    serve = _serve_and_scrape()
    within = bool(overhead["overhead_pct"] <= BUDGET_PCT
                  and serve["attribution_ok"]
                  and serve["events_roundtrip_ok"])
    probe: Dict = {
        "budget_pct": BUDGET_PCT,
        "capacity": CAPACITY,
        "sessions": SESSIONS,
        "session_np": SESSION_NP,
        "within_budget": within,
    }
    probe.update(overhead)
    probe.update(serve)
    return probe


def persist(probe: Dict, detail_path: str) -> Dict:
    """Merge under 'probe_obs' in BENCH_DETAIL.json, preserving every
    other section (the probe_dispatch/trace_overhead pattern)."""
    notes: Dict = {}
    try:
        with open(detail_path) as fh:
            detail = json.load(fh)
        if not isinstance(detail, dict):
            detail = {}
    except (OSError, ValueError):
        detail = {}
    detail["probe_obs"] = probe
    try:
        tmp = f"{detail_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(detail, fh, indent=1)
        os.replace(tmp, detail_path)
    except OSError as e:
        notes["detail_error"] = str(e)[:120]
    return notes
