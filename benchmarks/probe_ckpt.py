"""--probe-ckpt microbench: tiered checkpoint stall, steady-state
overhead, restore bandwidth, and buddy-vs-filesystem MTTR.

Four questions, answered on a 4-rank thread-rank world (same harness
and conventions as probe_respawn):

1. **What does a checkpoint cost the application?**  The async tier's
   contract is that ``ckpt.checkpoint`` stalls the app only for the
   *enqueue* (residue pickle + numpy snapshot + epoch agreement +
   collective open) while the device drain and pwrites ride later
   progress ticks.  Measured directly as the checkpoint call's wall
   time at two state sizes.

2. **What does the rest of the loop pay?**  Per-op time of the same
   allreduce loop with periodic async checkpoints interleaved (call
   durations excluded — they are the stall, reported separately)
   vs a loop that never checkpoints.  This *includes* the drain work
   riding the loop's progress ticks and is gated against the 5%
   steady-state budget, the same acceptance bar as trace_overhead and
   the probe_respawn degree-0 check.  Methodology follows
   trace_overhead: ONE world, INTERLEAVED off/on blocks, judged on
   the MEDIAN over block pairs — separate worlds land in different
   scheduler modes and the mode spread buries a 5% effect.

3. **How fast does a filesystem restore come back?**  Aggregate
   restore bandwidth (all ranks' bytes / wall time) of the fs rung of
   the ladder, with buddy off so the ladder cannot shortcut.

4. **What MTTR does each tier buy?**  Kill rank 1 (buddy restores it
   from its partner — the fast path) vs kill rank 1 AND its only
   partner rank 2 in one window (every buddy copy of rank 1's state is
   gone; the ladder degrades to filesystem replay).  Timed from the
   kill to the first full-size collective, at both state sizes.

Results land in BENCH_DETAIL.json under ``probe_ckpt``.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time
from typing import Dict

NRANKS = 4
VICTIM = 1
PARTNER = 2        # (VICTIM + 1) % NRANKS at cr_buddy_degree 1
BLOCK_OPS = 2000   # allreduces per measured block (~0.2s: one block
                   # is one checkpoint interval, the cadence the 5%
                   # budget is judged at — tighter cadences cost
                   # proportionally more drain time by construction)
BLOCKS = 8         # interleaved off/on block pairs
WARMUP = 20
REPS = 3           # best-of reps for the bandwidth and MTTR runs
BUDGET_PCT = 5.0   # steady-state bound for the checkpointing loop

# two state sizes (float64 elements per rank): the buddy tier's
# headline regime and a multi-MiB model-state regime
SIZES = {"64KiB": 8 * 1024, "2MiB": 256 * 1024}


def _payload(rank: int, nelems: int) -> Dict:
    import numpy as np
    return {"step": 0, "w": np.arange(nelems, dtype=np.float64) + rank}


def _measure_overhead(root: str, nelems: int) -> Dict:
    """Interleaved off/on blocks in ONE world.  "On" blocks take one
    async checkpoint at block start (the block IS the checkpoint
    interval); the call duration is the stall (excluded here,
    reported separately) and the epoch is flushed between blocks so
    drain work never leaks into an "off" block.  Returns per-block
    us/op for both sides plus the worst steady-state stall."""
    import statistics

    import numpy as np

    from ompi_tpu.cr import ckpt
    from ompi_tpu.op.op import SUM
    from ompi_tpu.testing import run_ranks

    def fn(comm):
        sbuf = np.ones(8, dtype=np.float32)
        rbuf = np.zeros(8, dtype=np.float32)
        payload = _payload(comm.rank, nelems)
        # one full epoch cycle outside the timed region: first-call
        # costs (imports, registries, file-open plumbing) are not the
        # steady-state story
        ckpt.checkpoint(comm, payload, store_dir=root)
        ckpt.flush(comm)
        for _ in range(WARMUP):
            comm.Allreduce(sbuf, rbuf, SUM)
        off_blocks, on_blocks = [], []
        stall_max = 0.0
        for b in range(BLOCKS * 2):
            with_c = bool(b & 1)
            comm.Barrier()
            stall = 0.0
            t0 = time.perf_counter()
            if with_c:
                ckpt.checkpoint(comm, payload, store_dir=root)
                stall = time.perf_counter() - t0
                stall_max = max(stall_max, stall)
            for i in range(BLOCK_OPS):
                comm.Allreduce(sbuf, rbuf, SUM)
            dt = time.perf_counter() - t0 - stall
            (on_blocks if with_c else off_blocks).append(
                dt / BLOCK_OPS * 1e6)
            if with_c:
                ckpt.flush(comm)  # outside timing; see docstring
        return {"off": off_blocks, "on": on_blocks,
                "stall_max_ms": stall_max * 1e3}

    out = run_ranks(NRANKS, fn, timeout=300)[0]
    # medians of each side, not pairwise ratios: adjacent blocks do
    # not share a scheduler mode reliably enough for pairing to cancel
    # the noise, but the medians of 8 interleaved blocks do
    off_med = statistics.median(out["off"])
    on_med = statistics.median(out["on"])
    return {
        "off_us_blocks": [round(x, 2) for x in out["off"]],
        "on_us_blocks": [round(x, 2) for x in out["on"]],
        "median_overhead_pct": (on_med - off_med) / off_med * 100.0,
        "stall_max_ms": out["stall_max_ms"],
    }


def _measure_restore_bw(root: str, nelems: int) -> Dict:
    """Aggregate fs-restore bandwidth (buddy off, so the ladder must
    replay the committed epoch from disk)."""
    import numpy as np

    from ompi_tpu.cr import ckpt
    from ompi_tpu.testing import run_ranks

    def fn(comm):
        ckpt.checkpoint(comm, _payload(comm.rank, nelems),
                        store_dir=root)
        ckpt.flush(comm)
        comm.Barrier()
        t0 = time.perf_counter()
        out = ckpt.restore(comm, store_dir=root)
        dt = time.perf_counter() - t0
        assert out is not None and out["step"] == 0
        np.testing.assert_array_equal(
            out["w"], _payload(comm.rank, nelems)["w"])
        return dt

    dt = max(run_ranks(NRANKS, fn, timeout=300))
    total_bytes = nelems * 8 * NRANKS
    return {"restore_ms": dt * 1e3,
            "bw_MBps": total_bytes / dt / 1e6}


def _measure_mttr(root: str, nelems: int, kill_partner: bool) -> Dict:
    """Kill → detect → rejoin → tiered restore → first full-size
    collective.  kill_partner=False leaves rank 1's buddy copy alive
    (tier-1 restore); True kills rank 2 in the same window so the
    ladder must fall to the filesystem epoch."""
    import numpy as np

    from ompi_tpu.cr import ckpt
    from ompi_tpu.errhandler import MPIException
    from ompi_tpu.ft import respawn, ulfm
    from ompi_tpu.op.op import SUM
    from ompi_tpu.testing import run_ranks

    victims = (VICTIM, PARTNER) if kill_partner else (VICTIM,)
    t0 = [0.0]

    def fn(comm):
        sbuf = np.ones(16, dtype=np.float64)
        rbuf = np.zeros(16, dtype=np.float64)
        if respawn.joining(comm.state):
            comm = respawn.rejoin(comm)
            st = ckpt.restore(comm, store_dir=root)
            assert st is not None and st["step"] == 0
            comm.Allreduce(sbuf, rbuf, SUM)
            return None
        ckpt.checkpoint(comm, _payload(comm.rank, nelems),
                        store_dir=root)
        ckpt.flush(comm)
        if comm.rank in victims:
            # both victims sleep outside any collective, then die in
            # the same window — the correlated multi-kill shape
            time.sleep(0.05)
            t0[0] = time.perf_counter()
            ulfm.kill_now(comm.state)
        try:
            while True:
                comm.Allreduce(sbuf, rbuf, SUM)
        except MPIException as e:
            t_detect = time.perf_counter()
            assert e.code in (75, 76, 77), e.code
        comm = respawn.rejoin(comm)
        t_rejoin = time.perf_counter()
        st = ckpt.restore(comm, store_dir=root)
        t_restore = time.perf_counter()
        assert st is not None and st["step"] == 0
        comm.Allreduce(sbuf, rbuf, SUM)
        t_first = time.perf_counter()
        assert comm.size == NRANKS
        assert rbuf[0] == float(comm.size)
        return {
            "detect_ms": (t_detect - t0[0]) * 1e3,
            "restore_ms": (t_restore - t_rejoin) * 1e3,
            "total_ms": (t_first - t0[0]) * 1e3,
        }

    out = run_ranks(NRANKS, fn, respawn=True, timeout=120)
    return out[0]


def run_probe() -> Dict:
    from ompi_tpu.cr import ckpt
    from ompi_tpu.mca.params import registry

    prior_ulfm = registry.get("mpi_ft_ulfm", "1")
    prior_deg = registry.get("cr_buddy_degree", "0")
    out: Dict = {"nranks": NRANKS, "reps": REPS,
                 "block_ops": BLOCK_OPS, "blocks": BLOCKS,
                 "ckpt_interval": "one per block",
                 "budget_pct": BUDGET_PCT, "sizes": {}}
    base = tempfile.mkdtemp(prefix="probe_ckpt_")
    worst_overhead = 0.0
    try:
        registry.set("mpi_ft_ulfm", "1")
        for label, nelems in SIZES.items():
            sec: Dict = {"state_bytes_per_rank": nelems * 8}

            # 1+2: stall + steady-state overhead (buddy off: the
            # filesystem tier's own cost, not buddy replication's).
            # Best-of-REPS like the other probes: a run that collides
            # with a page-cache writeback storm or a scheduler mode
            # switch inflates every on-block at once, and the median
            # cannot reject a whole-run shift — the best run is the
            # intrinsic cost
            registry.set("cr_buddy_degree", "0")
            ovs = []
            for r in range(REPS):
                root = os.path.join(base, f"ov_{label}_{r}")
                ovs.append(_measure_overhead(root, nelems))
                shutil.rmtree(root, ignore_errors=True)
            ov = min(ovs, key=lambda o: o["median_overhead_pct"])
            overhead = ov["median_overhead_pct"]
            sec["steady_overhead_pct_all"] = [
                round(o["median_overhead_pct"], 2) for o in ovs]
            worst_overhead = max(worst_overhead, overhead)
            sec["off_us_blocks"] = ov["off_us_blocks"]
            sec["on_us_blocks"] = ov["on_us_blocks"]
            sec["steady_overhead_pct"] = round(overhead, 2)
            sec["stall_max_ms"] = round(ov["stall_max_ms"], 3)

            # 3: fs restore bandwidth (buddy off forces the fs rung)
            bws = []
            for r in range(REPS):
                root = os.path.join(base, f"bw_{label}_{r}")
                bws.append(_measure_restore_bw(root, nelems))
                shutil.rmtree(root, ignore_errors=True)
            best = max(bws, key=lambda b: b["bw_MBps"])
            sec["fs_restore_ms"] = round(best["restore_ms"], 3)
            sec["fs_restore_MBps"] = round(best["bw_MBps"], 1)

            # 4: MTTR per tier (buddy on for both; the kill set picks
            # the rung)
            registry.set("cr_buddy_degree", "1")
            for key, kp in (("mttr_buddy", False), ("mttr_fs", True)):
                recs = []
                for r in range(REPS):
                    root = os.path.join(base, f"{key}_{label}_{r}")
                    recs.append(_measure_mttr(root, nelems, kp))
                    shutil.rmtree(root, ignore_errors=True)
                b = min(recs, key=lambda x: x["total_ms"])
                sec[key] = {
                    "detect_ms": round(b["detect_ms"], 3),
                    "restore_ms": round(b["restore_ms"], 3),
                    "total_ms": round(b["total_ms"], 3),
                    "total_ms_all": [round(x["total_ms"], 3)
                                     for x in recs],
                }
            out["sizes"][label] = sec
    finally:
        registry.set("mpi_ft_ulfm", prior_ulfm)
        registry.set("cr_buddy_degree", prior_deg)
        shutil.rmtree(base, ignore_errors=True)
    out["stall_us_pvar_high"] = int(ckpt._pv_stall.read())
    out["worst_steady_overhead_pct"] = round(worst_overhead, 2)
    out["within_budget"] = bool(worst_overhead <= BUDGET_PCT)
    return out


def persist(probe: Dict, detail_path: str) -> Dict:
    """Merge under 'probe_ckpt' in BENCH_DETAIL.json, preserving every
    other section (the probe_dispatch/trace_overhead pattern)."""
    notes: Dict = {}
    try:
        with open(detail_path) as fh:
            detail = json.load(fh)
        if not isinstance(detail, dict):
            detail = {}
    except (OSError, ValueError):
        detail = {}
    detail["probe_ckpt"] = probe
    try:
        tmp = f"{detail_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(detail, fh, indent=1)
        os.replace(tmp, detail_path)
    except OSError as e:
        notes["detail_error"] = str(e)[:120]
    return notes
