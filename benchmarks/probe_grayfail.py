"""--probe-grayfail microbench: the gray-failure plane (ISSUE 19,
DESIGN.md §24), proven against a live in-process 2-host pool with
thread-driven host agents (exact control of beat pacing — the probe
IS the clock):

1. **Healthy arm (false-positive gate).**  Both hosts beat crisply at
   the agent's own grace/6 pacing while a submitter streams jobs.
   The claim: ZERO quarantines (the ``fleet_quarantines`` pvar does
   not move), no host ever reaches `quarantined`, and every job
   completes — the plane must cost nothing on a healthy fleet.

2. **Slow-host arm, unmitigated (the baseline the plane must beat).**
   ``health_enable=0``: host 1 beats slow AND its resident ranks
   crawl (the ``host_slow`` ft_inject class delays every device-
   collective deposit by ``delay_ms*(factor-1)``), exactly the
   alive-but-10x-slow gray failure.  Every np-2 job spans both
   domains (static banding), so the whole pool runs at the
   straggler's speed — goodput over a fixed window is the denominator.

3. **Slow-host arm, mitigated.**  Same fault, health plane armed.
   The beat-interval score trips the hysteresis ladder (healthy ->
   degraded -> quarantined), the quarantine drains the resident
   session through the park/resume machinery, and the replay brings
   it up banded onto host 0 only — after MTTM the pool runs at full
   speed again.  Gates: mitigated goodput >= RATIO_FLOOR (2x) of
   unmitigated, MTTM <= 4x the health tick period, zero failed jobs,
   and the slow host is never declared DEAD (``_host_dead[1] == 0``
   throughout — the liveness plane must not fire on a gray failure).

Results land in BENCH_DETAIL.json under ``probe_grayfail``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List

HOSTS = 2
CAPACITY = 2              # one np-2 session spans both domains
HB_S = 0.2                # dvm_heartbeat_s: hb-loop period
HOST_GRACE_S = 0.1        # oob_host_grace_s: static floor = 0.7 s
TICK_MS = 150             # health_tick_ms: below the hb-loop period,
                          # so the tick fires on EVERY loop wake and
                          # the effective period is the loop's 200 ms
TRIP_TICKS = 1            # probe-sized hysteresis (2 rungs = 2 ticks)
CLEAR_TICKS = 4
DELAY_MS = 40             # ft_inject_delay_ms: slow rank stalls
SLOW_FACTOR = 10          # ft_inject_host_slow_factor
CRISP_BEAT_S = 0.1       # healthy agent pacing (~grace/6)
SLOW_BEAT_S = 0.5        # slow-but-alive: < grace (0.7 s), > 3x expect
MEASURE_S = 6.0           # goodput window per slow arm
HEALTHY_S = 2.5           # healthy-arm traffic window
RATIO_FLOOR = 2.0         # mitigated/unmitigated goodput gate
MTTM_TICKS = 4            # MTTM budget in health tick periods

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROG = os.path.join(REPO, "tests", "_dvm_prog.py")

# every knob the probe tightens, with its probe value; saved/restored
# around the whole run so nothing leaks into the caller's registry
_KNOBS = {
    "dvm_heartbeat_s": HB_S,
    "oob_host_grace_s": HOST_GRACE_S,
    "health_tick_ms": TICK_MS,
    "health_trip_ticks": TRIP_TICKS,
    "health_clear_ticks": CLEAR_TICKS,
    "ft_inject_delay_ms": DELAY_MS,
    "ft_inject_host_slow_factor": SLOW_FACTOR,
    "ft_inject_victim_host": 1,
    # the arms flip these; listed here so the caller's values are
    # restored even if an arm dies mid-flight
    "health_enable": 1,
    "ft_inject_plan": "",
}


def _pv(name: str) -> int:
    from ompi_tpu.mca.params import registry
    return int(registry._pvars[name].read())


class _Beater(threading.Thread):
    """One in-process host agent: registers its domain on the pool
    port and beats at ``interval_s`` — the probe flips the interval
    to turn a crisp host into a slow-but-alive one at a precise
    instant (a real tpud subprocess would add scheduler noise to the
    MTTM measurement)."""

    def __init__(self, uri: str, host: int, interval_s: float) -> None:
        super().__init__(daemon=True, name=f"grayfail-beat-{host}")
        self.uri = uri
        self.host = host
        self.interval_s = interval_s
        self.stop_ev = threading.Event()
        self.registered = threading.Event()

    def run(self) -> None:
        from ompi_tpu.tools.dvm import DvmClient, DvmDisconnect, \
            DvmError
        try:
            with DvmClient(self.uri, connect_timeout=10.0) as cli:
                cli._rpc({"op": "host_register", "host": self.host,
                          "pid": os.getpid()})
                self.registered.set()
                while not self.stop_ev.wait(self.interval_s):
                    cli._rpc({"op": "host_beat", "host": self.host})
        except (DvmError, DvmDisconnect, OSError):
            pass  # pool stopping under us ends the beat stream

    def halt(self) -> None:
        self.stop_ev.set()


def _new_pool(tmpdir: str, tag: str):
    import jax

    from ompi_tpu.tools.dvm import DVMServer
    uri = os.path.join(tmpdir, f"grayfail-{tag}-{time.time_ns()}.uri")
    srv = DVMServer(CAPACITY, devices=jax.devices(), uri_file=uri,
                    hosts=HOSTS)
    srv.start()
    return srv, uri


def _pool_up(tmpdir: str, tag: str):
    """Pool + both thread agents beating crisply, ready for attach."""
    srv, uri = _new_pool(tmpdir, tag)
    beaters = [_Beater(uri, h, CRISP_BEAT_S) for h in range(HOSTS)]
    for b in beaters:
        b.start()
    for b in beaters:
        if not b.registered.wait(timeout=30):
            raise RuntimeError(f"host {b.host} agent never registered")
    return srv, uri, beaters


def _pool_down(srv, beaters) -> None:
    for b in beaters:
        b.halt()
    srv.stop()
    for b in beaters:
        b.join(timeout=10)


def _stream_jobs(uri: str, stop_at: List[float],
                 done_ts: List[float], errs: List[str]) -> None:
    """One submitter: a resident np-2 session re-running PROG until
    told to stop.  Run failures are collected, never swallowed — the
    zero-failed-jobs gate reads ``errs``."""
    from ompi_tpu.tools.dvm import DvmClient
    try:
        with DvmClient(uri) as cli:
            sid = cli.attach(2, timeout=120)["sid"]
            while time.monotonic() < stop_at[0]:
                r = cli.run(sid, PROG, timeout=180)
                if r["code"] != 0:
                    raise RuntimeError(f"rc={r['code']}: "
                                       f"{r['stderr'][-200:]}")
                done_ts.append(time.monotonic())
            cli.detach(sid)
    except Exception as e:  # noqa: BLE001
        errs.append(str(e))


# -- arm 1: healthy fleet, plane armed — zero false quarantines -------------


def _arm_healthy(tmpdir: str) -> Dict:
    q0 = _pv("fleet_quarantines")
    srv, uri, beaters = _pool_up(tmpdir, "healthy")
    try:
        stop_at = [time.monotonic() + 3600.0]
        done_ts: List[float] = []
        errs: List[str] = []
        th = threading.Thread(target=_stream_jobs,
                              args=(uri, stop_at, done_ts, errs))
        th.start()
        time.sleep(HEALTHY_S)
        stop_at[0] = 0.0
        th.join(timeout=300)
        hp = srv.health
        worst = max(hp.state) if hp is not None else -1
        false_q = _pv("fleet_quarantines") - q0
        return {
            "window_s": HEALTHY_S,
            "jobs_done": len(done_ts),
            "jobs_failed": len(errs),
            "failures": errs[:3],
            "false_quarantines": false_q,
            "worst_state": worst,
            "healthy_ok": bool(not errs and done_ts
                               and false_q == 0 and worst < 2),
        }
    finally:
        _pool_down(srv, beaters)


# -- arms 2+3: slow host, unmitigated vs mitigated --------------------------


def _arm_slow(tmpdir: str, mitigated: bool) -> Dict:
    """Host 1 turns gray at t0 (slow beats + slow resident ranks);
    goodput is the completed-job count in [t0, t0 + MEASURE_S].  With
    the plane armed the MTTM clock runs t0 -> quarantine applied."""
    from ompi_tpu.mca.params import registry

    registry.set("health_enable", 1 if mitigated else 0)
    # host_slow armed for the whole arm: the per-state injector cache
    # is built at world bring-up, so arming must precede the attach.
    # Rank stalls before t0 only slow the warm-up run.
    registry.set("ft_inject_plan", "host_slow")
    try:
        tag = "mit" if mitigated else "unmit"
        srv, uri, beaters = _pool_up(tmpdir, tag)
        try:
            stop_at = [time.monotonic() + 3600.0]
            done_ts: List[float] = []
            errs: List[str] = []
            th = threading.Thread(target=_stream_jobs,
                                  args=(uri, stop_at, done_ts, errs))
            th.start()
            # warm-up: the session world is up and the crisp beat
            # EWMA is established before the fault begins
            deadline = time.monotonic() + 60
            while not done_ts and time.monotonic() < deadline:
                time.sleep(0.02)
            if not done_ts:
                raise RuntimeError("warm-up run never completed: "
                                   + "; ".join(errs[:1]))
            time.sleep(3 * CRISP_BEAT_S)

            t0 = time.monotonic()
            beaters[1].interval_s = SLOW_BEAT_S  # the gray failure
            mttm_ms = -1.0
            if mitigated:
                while time.monotonic() < t0 + 30:
                    if srv._health_applied[1] >= 2:
                        mttm_ms = (time.monotonic() - t0) * 1e3
                        break
                    time.sleep(0.005)
            stop_at[0] = t0 + MEASURE_S
            th.join(timeout=300)
            goodput = sum(1 for ts in done_ts if ts >= t0)
            never_dead = bool(srv._host_dead[1] == 0)
            out = {
                "window_s": MEASURE_S,
                "goodput_jobs": goodput,
                "jobs_failed": len(errs),
                "failures": errs[:3],
                "slow_host_never_dead": never_dead,
            }
            if mitigated:
                hp = srv.health
                out["mttm_ms"] = round(mttm_ms, 1)
                out["quarantined"] = bool(srv._health_applied[1] >= 2)
                out["migrations"] = _pv("fleet_migrations")
                out["final_state"] = (hp.state[1]
                                      if hp is not None else -1)
            return out
        finally:
            _pool_down(srv, beaters)
    finally:
        registry.set("ft_inject_plan", "")
        registry.set("health_enable", 1)


def run_probe() -> Dict:
    import tempfile

    # the save/restore below needs every touched knob REGISTERED
    # (an unregistered knob reads back None, which would then be
    # "restored" as a None override): import the registering modules
    import ompi_tpu.ft_inject  # noqa: F401
    import ompi_tpu.obs.health  # noqa: F401
    import ompi_tpu.runtime.oob  # noqa: F401
    import ompi_tpu.tools.dvm  # noqa: F401
    from ompi_tpu.mca.params import registry

    saved = {k: registry.get(k) for k in _KNOBS}
    for k, v in _KNOBS.items():
        registry.set(k, v)
    tmpdir = tempfile.mkdtemp(prefix="probe_grayfail_")
    try:
        healthy = _arm_healthy(tmpdir)
        unmit = _arm_slow(tmpdir, mitigated=False)
        mit = _arm_slow(tmpdir, mitigated=True)
    finally:
        import shutil
        shutil.rmtree(tmpdir, ignore_errors=True)
        for k, v in saved.items():
            registry.set(k, v)
    ratio = (mit["goodput_jobs"] / unmit["goodput_jobs"]
             if unmit["goodput_jobs"] > 0 else 0.0)
    # the detector's latency contract has two terms: the overdue-beat
    # horizon (a beat must be 3x late before the score can move — a
    # floor set by the expected beat interval, not the tick), then at
    # most MTTM_TICKS effective tick periods for the hysteresis ladder
    # to walk healthy -> degraded -> quarantined.  The tick rides the
    # pool heartbeat loop, so its effective period is the larger of
    # the two knobs.
    expect_ms = max(50.0, (3 * HB_S + HOST_GRACE_S) / 6 * 1000)
    mttm_budget_ms = int(3 * expect_ms
                         + MTTM_TICKS * max(TICK_MS, HB_S * 1000))
    failed = (healthy["jobs_failed"] + unmit["jobs_failed"]
              + mit["jobs_failed"])
    ok = bool(
        healthy["healthy_ok"]
        and ratio >= RATIO_FLOOR
        and 0 < mit["mttm_ms"] <= mttm_budget_ms
        and mit["quarantined"]
        and unmit["slow_host_never_dead"]
        and mit["slow_host_never_dead"]
        and failed == 0)
    return {
        "hosts": HOSTS,
        "agent": "in-process thread beaters (host_register/host_beat)",
        "slow_factor": SLOW_FACTOR,
        "healthy": healthy,
        "unmitigated": unmit,
        "mitigated": mit,
        "goodput_ratio": round(ratio, 2),
        "ratio_floor": RATIO_FLOOR,
        "mttm_ms": mit["mttm_ms"],
        "mttm_budget_ms": mttm_budget_ms,
        "false_quarantines": healthy["false_quarantines"],
        "failed_jobs": failed,
        "within_budget": ok,
    }


def persist(probe: Dict, detail_path: str) -> Dict:
    """Merge under 'probe_grayfail' in BENCH_DETAIL.json, preserving
    every other section (the probe_fleet pattern)."""
    notes: Dict = {}
    try:
        with open(detail_path) as fh:
            detail = json.load(fh)
        if not isinstance(detail, dict):
            detail = {}
    except (OSError, ValueError):
        detail = {}
    detail["probe_grayfail"] = probe
    try:
        tmp = f"{detail_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(detail, fh, indent=1)
        os.replace(tmp, detail_path)
    except OSError as e:
        notes["detail_error"] = str(e)[:120]
    return notes
