"""--probe-reqtrace microbench: request-scoped tracing + the hang
doctor (DESIGN.md §23), proven against a live in-process pool:

1. **Waterfall fidelity.**  A 4-session Poisson workload on a 2-host
   fleet (real ``tpud --fleet`` agents) with ``obs_reqtrace_enable``
   on: every attach mints a trace id, every run carries it, and the
   pool's flight recorder accumulates the request's events.  The
   claim: ``traceview --job`` reduces those events to a per-request
   waterfall whose additive span sum (queue wait + run walls + resume
   bringups) matches the CLIENT-measured run wall within
   FIDELITY_PCT (10%%) for every request — the numbers an operator
   reads are the numbers the client paid.

2. **Hang doctor MTTD + verdict.**  With the watchdog armed
   (``obs_watchdog_ms``) and the EWMA estimator warmed, a job is
   deliberately wedged via the ``rdv_sever`` fault class (victim
   rank silently stops arriving at its device-collective
   rendezvous).  The claim: the watchdog fires within
   2 x obs_watchdog_ms of the threshold crossing (``doctor_mttd_ms``,
   the --regress sentry), exactly ONE capture is taken for the job,
   and ``tools/doctor.py`` reduces it to a verdict NAMING the absent
   rank and its rendezvous — from the persisted
   ``<uri>.doctor.s*.json`` alone, no live pool required.

3. **Tagging overhead.**  The trace_overhead methodology's reqtrace
   rotation arm (off / on / on+phase / on+req_mark, micro-interleaved
   in one world): request tagging at the serving plane's per-run
   ``req_mark`` bracket cadence must stay within the same 5%% budget
   as tracing itself.

Results land in BENCH_DETAIL.json under ``probe_reqtrace``;
``queue_wait_p99_us`` and ``doctor_mttd_ms`` feed the --regress
sentry.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Dict, List

from benchmarks.probe_fleet import _spawn_agent, _wait

CAPACITY = 4
HOSTS = 2
SESSIONS = 4            # concurrent Poisson submitters, part 1
RUNS_PER_SESSION = 3
RUN_REPS = 80           # collective-mix reps per run: a warm run's
                        # wall must dwarf the ms-granular server wall
                        # rounding and the client RPC round-trip, or
                        # the fidelity comparison measures THOSE
POISSON_MEAN_S = 0.05   # mean think time between a session's runs
FIDELITY_PCT = 10.0     # waterfall span sum vs client wall

WD_MS = 250             # obs_watchdog_ms for the doctor arm
WD_FACTOR = 2           # stall threshold: 2x the EWMA estimate
WARM_RUNS = 6           # pull the EWMA down past the jit-compile run

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROG = os.path.join(REPO, "tests", "_dvm_session_prog.py")


# -- part 1: 4-session Poisson workload -> per-request waterfalls -----------


def _probe_waterfall(tmpdir: str) -> Dict:
    import jax

    from ompi_tpu import obs as _obs
    from ompi_tpu.mca.params import registry
    from ompi_tpu.tools.dvm import DVMServer, DvmClient
    from ompi_tpu.tools.traceview import job_report

    hb0 = registry.get("dvm_heartbeat_s")
    rq0 = registry.get("obs_reqtrace_enable")
    registry.set("dvm_heartbeat_s", 0.2)
    registry.set("obs_reqtrace_enable", 1)
    uri = os.path.join(tmpdir, f"reqtrace-{time.time_ns()}.uri")
    srv = DVMServer(CAPACITY, devices=jax.devices(), uri_file=uri,
                    hosts=HOSTS)
    srv.start()
    agents = {}
    try:
        for h in range(HOSTS):
            agents[h] = _spawn_agent(uri, h)
        _wait(lambda: all(b > 0 for b in srv._host_beat), 120,
              "tpud host agents to register")

        lock = threading.Lock()
        reqs: List[Dict] = []
        errs: List[str] = []

        def submitter(idx: int) -> None:
            rng = random.Random(1000 + idx)  # replayable arrivals
            try:
                with DvmClient(uri) as cli:
                    t0 = time.perf_counter()
                    resp = cli.attach(2, timeout=180)
                    attach_us = int((time.perf_counter() - t0) * 1e6)
                    sid, tid = resp["sid"], int(resp.get("tid") or 0)
                    run_us = 0
                    for n in range(RUNS_PER_SESSION):
                        time.sleep(rng.expovariate(1 / POISSON_MEAN_S))
                        t0 = time.perf_counter()
                        r = cli.run(sid, PROG,
                                    [f"w{idx}", str(RUN_REPS)],
                                    timeout=300)
                        run_us += int((time.perf_counter() - t0) * 1e6)
                        if r["code"] != 0:
                            raise RuntimeError(
                                f"run rc={r['code']}: "
                                f"{r['stderr'][-200:]}")
                    with lock:
                        reqs.append({"sid": sid, "tid": tid,
                                     "attach_us": attach_us,
                                     "client_run_us": run_us})
                    cli.detach(sid)
            except Exception as e:  # noqa: BLE001
                with lock:
                    errs.append(f"submitter {idx}: {e}")

        threads = [threading.Thread(target=submitter, args=(i,))
                   for i in range(SESSIONS)]
        for t in threads:
            t.start()
        # the per-session SLI surface, observed mid-stream: rows must
        # carry the request tid and the banded queue-wait p99 gauge
        sli_rows = 0
        admin = DvmClient(uri)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            m = admin.metrics()
            rows = m.get("sessions", {})
            sli_rows = max(sli_rows, sum(
                1 for row in rows.values()
                if row.get("tid") and "queue_wait_p99_us" in row))
            if not any(t.is_alive() for t in threads):
                break
            time.sleep(0.1)
        admin.close()
        for t in threads:
            t.join(timeout=300)
        if errs:
            raise RuntimeError("; ".join(errs[:3]))

        # reduce the pool's flight ring exactly as traceview --job
        # does (the dump document IS the persisted-events format)
        dump = _obs.recorder().trace_dump()
        waterfalls = []
        worst_err = 0.0
        for rq in reqs:
            lines, info = job_report([dump], [], rq["tid"])
            if not info:
                waterfalls.append({"tid": rq["tid"], "found": False})
                worst_err = 1e9
                continue
            span_us = info["run_us"] + info["resume_us"]
            err = (abs(span_us - rq["client_run_us"])
                   / max(1, rq["client_run_us"]) * 100.0)
            worst_err = max(worst_err, err)
            waterfalls.append({
                "tid": rq["tid"], "found": True,
                "runs": info["runs"],
                "queued_us": info["queued_us"],
                "span_sum_us": span_us,
                "client_run_us": rq["client_run_us"],
                "err_pct": round(err, 2),
                "queue_wait_le_attach": bool(
                    info["queued_us"] <= rq["attach_us"] + 50_000),
            })
        qwaits = sorted(w.get("queued_us", 0) for w in waterfalls)
        fidelity_ok = bool(
            len(waterfalls) == SESSIONS
            and all(w["found"] for w in waterfalls)
            and all(w["runs"] == RUNS_PER_SESSION for w in waterfalls)
            and all(w["queue_wait_le_attach"] for w in waterfalls)
            and worst_err <= FIDELITY_PCT)
        return {
            "sessions": SESSIONS,
            "runs_per_session": RUNS_PER_SESSION,
            "hosts": HOSTS,
            "poisson_mean_s": POISSON_MEAN_S,
            "waterfalls": waterfalls,
            "worst_err_pct": round(worst_err, 2),
            "fidelity_pct": FIDELITY_PCT,
            "queue_wait_p99_us": qwaits[-1] if qwaits else 0,
            "sli_rows_seen": sli_rows,
            "events_recorded": dump.get("recorded", 0),
            "events_dropped": dump.get("dropped", 0),
            "fidelity_ok": fidelity_ok,
        }
    finally:
        for p in agents.values():
            if p.poll() is None:
                p.kill()
        srv.stop()
        registry.set("dvm_heartbeat_s",
                     "2.0" if hb0 is None else hb0)
        registry.set("obs_reqtrace_enable",
                     "0" if rq0 is None else rq0)


# -- part 2: wedge a job, let the doctor name the absent rank ---------------


def _probe_doctor(tmpdir: str) -> Dict:
    import jax

    from ompi_tpu.mca.params import registry
    from ompi_tpu.tools import doctor as doctor_tool
    from ompi_tpu.tools.dvm import DVMServer, DvmClient

    saved = {k: registry.get(k) for k in
             ("obs_watchdog_ms", "obs_watchdog_factor",
              "obs_reqtrace_enable", "ft_inject_plan",
              "ft_inject_victim_rank", "ft_inject_seed",
              "coll_device_rendezvous_timeout",
              "coll_device_rendezvous_poll")}
    registry.set("obs_watchdog_ms", WD_MS)   # before start(): the
    registry.set("obs_watchdog_factor", WD_FACTOR)  # thread arms in _setup
    registry.set("obs_reqtrace_enable", 1)
    registry.set("coll_device_rendezvous_poll", 0.05)
    uri = os.path.join(tmpdir, f"doctor-{time.time_ns()}.uri")
    srv = DVMServer(2, devices=jax.devices(), uri_file=uri)
    srv.start()
    try:
        # warm the EWMA estimator past the jit-compile first run so
        # the stall threshold reflects steady-state wall time
        with DvmClient(uri) as cli:
            wsid = cli.attach(2, timeout=180)["sid"]
            for n in range(WARM_RUNS):
                r = cli.run(wsid, PROG, ["warm"], timeout=300)
                if r["code"] != 0:
                    raise RuntimeError(f"warm rc={r['code']}: "
                                       f"{r['stderr'][-200:]}")
            cli.detach(wsid)
        limit_s = srv.est_wall_us * WD_FACTOR / 1e6
        # the wedge must outlive the watchdog but not the probe: give
        # the rendezvous stall raise a horizon safely past detection
        registry.set("coll_device_rendezvous_timeout",
                     max(10.0, limit_s * 4 + 5 * WD_MS / 1000.0))
        # arm the sever AFTER warm-up: the wedge session's fresh rank
        # states pick the injector up at world bring-up
        registry.set("ft_inject_seed", 7)
        registry.set("ft_inject_victim_rank", "1")
        registry.set("ft_inject_plan", "rdv_sever:1")

        res: Dict = {}
        cli = DvmClient(uri)
        resp = cli.attach(2, timeout=180)
        sid, tid = resp["sid"], int(resp.get("tid") or 0)

        def wedged() -> None:
            try:
                res.update(cli.run(sid, PROG, ["wedge"], timeout=300))
            except Exception as e:  # noqa: BLE001
                res["error"] = str(e)

        th = threading.Thread(target=wedged)
        t0 = time.perf_counter()
        th.start()
        _wait(lambda: len(srv.doctor_reports) >= 1,
              limit_s * 3 + 60, "the watchdog to capture the stall")
        detect_wall_ms = (time.perf_counter() - t0) * 1e3
        th.join(timeout=300)  # the rendezvous stall raise unwedges it
        cli.detach(sid)
        cli.close()
        registry.set("ft_inject_plan", "")

        doc = srv.doctor_reports[0]
        # the verdict, reduced from the PERSISTED capture (the 3am
        # path: the pool may be gone) by the real tool
        docs = doctor_tool.load_captures(uri)
        verdict = doctor_tool.verdict(docs[0]) if docs else []
        vtext = "\n".join(verdict)
        absent_named = any(
            1 in [rv.get("group", [""] * len(rv.get("absent", [])))[s]
                  for s in rv.get("absent", [])
                  if s < len(rv.get("group", []))]
            for rv in doc.get("rendezvous", []))
        mttd_ms = float(doc.get("mttd_ms", 1e9))
        ok = bool(
            len(srv.doctor_reports) == 1       # one capture per job
            and doc.get("sid") == sid and doc.get("tid") == tid
            and absent_named                    # rank 1 absent, named
            and "ABSENT" in vtext and "rendezvous" in vtext
            and len(doc.get("stacks") or {}) >= 1
            and 0 <= mttd_ms <= 2 * WD_MS       # the MTTD contract
            and res.get("code", 0) != 0)        # the wedge DID fail
        return {
            "watchdog_ms": WD_MS,
            "watchdog_factor": WD_FACTOR,
            "est_wall_ms": round(srv.est_wall_us / 1000.0, 3),
            "wedged_rc": res.get("code"),
            "captures": len(srv.doctor_reports),
            "doctor_mttd_ms": round(mttd_ms, 3),
            "mttd_budget_ms": 2 * WD_MS,
            "detect_wall_ms": round(detect_wall_ms, 3),
            "absent_rank_named": absent_named,
            "stacks_captured": len(doc.get("stacks") or {}),
            "rendezvous_captured": len(doc.get("rendezvous") or []),
            "verdict_head": verdict[:6],
            "doctor_ok": ok,
        }
    finally:
        srv.stop()
        # registry.get returns None for never-resolved vars; restore
        # those to their documented defaults (the test_obs idiom)
        defaults = {"obs_watchdog_ms": "0", "obs_watchdog_factor": "4",
                    "obs_reqtrace_enable": "0", "ft_inject_plan": "",
                    "ft_inject_victim_rank": "1", "ft_inject_seed": "0",
                    "coll_device_rendezvous_timeout": "300",
                    "coll_device_rendezvous_poll": "0.25"}
        for k, v in saved.items():
            registry.set(k, defaults[k] if v is None else v)


def run_probe() -> Dict:
    import shutil
    import tempfile

    tmpdir = tempfile.mkdtemp(prefix="probe_reqtrace_")
    try:
        waterfall = _probe_waterfall(tmpdir)
        hangdoc = _probe_doctor(tmpdir)
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)
    # part 3: the tagging-overhead arm rides the trace_overhead
    # methodology (same interleaved-block world, same budget)
    from benchmarks.trace_overhead import run_probe as _trace_probe
    tp = _trace_probe()
    overhead = {
        "off_us_median": tp["off_us_median"],
        "reqtrace_us_median": tp["reqtrace_us_median"],
        "reqtrace_overhead_pct": tp["reqtrace_overhead_pct"],
        "budget_pct": tp["budget_pct"],
        "reqtrace_within_budget": tp["reqtrace_within_budget"],
    }
    return {
        "waterfall": waterfall,
        "doctor": hangdoc,
        "overhead": overhead,
        "queue_wait_p99_us": waterfall["queue_wait_p99_us"],
        "doctor_mttd_ms": hangdoc["doctor_mttd_ms"],
        "within_budget": bool(waterfall["fidelity_ok"]
                              and hangdoc["doctor_ok"]
                              and overhead["reqtrace_within_budget"]),
    }


def persist(probe: Dict, detail_path: str) -> Dict:
    """Merge under 'probe_reqtrace' in BENCH_DETAIL.json, preserving
    every other section (the probe_dispatch/probe_fleet pattern)."""
    notes: Dict = {}
    try:
        with open(detail_path) as fh:
            detail = json.load(fh)
        if not isinstance(detail, dict):
            detail = {}
    except (OSError, ValueError):
        detail = {}
    detail["probe_reqtrace"] = probe
    try:
        tmp = f"{detail_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(detail, fh, indent=1)
        os.replace(tmp, detail_path)
    except OSError as e:
        notes["detail_error"] = str(e)[:120]
    return notes
