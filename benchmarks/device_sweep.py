"""Device-path sweep: the coll/tpu / coll/hbm side of BASELINE.md.

Runs thread-ranks in-process (the TPU-host execution model) and times
allreduce/bcast/alltoall/reduce_scatter on device-resident arrays
through the XLA collective path.  Used by bench.py; also runnable
directly:  python benchmarks/device_sweep.py --max-ar 1048576

Timing methodology (forced completion + chained dependency — r4):
on the tunneled TPU backend ``jax.Array.block_until_ready()`` returns
WITHOUT awaiting execution (measured: 10 dispatched 8-MiB 8-way sums
"complete" in 0.37 ms), so any timing that relies on it reports the
dispatch floor, not the op.  And N dispatches of the same op on the
SAME input carry no data dependency, so XLA/the runtime may alias or
elide them (r3's failure: a stacked bcast is near-free metadata).
Every timed point here instead:

  1. warms up the op AND a tiny per-shape probe read (first read
     compiles; ~1 s on the tunnel), verifying the numeric result;
  2. measures the tunnel-RPC read constant (min of several 4-byte
     d2h reads, ~100 ms on the tunnel);
  3. runs N CHAINED iterations  x -> op(x) -> chain(x) -> op -> ...
     where ``chain`` is a jitted materializing step (multiply/add by
     a RUNTIME device scalar, so XLA cannot constant-fold it away)
     that feeds each op's output into the next op's input: the device
     must fully execute op k before op k+1 can start, and no op can
     be aliased out.  chain also keeps values in steady state
     (allreduce rescales by 1/P) so long runs never overflow.
     N is chosen so N*op >= max(0.3 s, 4x read constant), never < 30;
     completion is forced with ONE 4-byte d2h read of the LAST result
     (in-order device execution awaits the whole chain);
  4. reports (elapsed - read_const) / N, rank 0 as the timekeeper
     (concurrent per-rank reads would serialize on the tunnel).

A physical sanity gate then checks each point's implied bandwidth
against the chip's HBM peak, using a PER-COLLECTIVE minimal-traffic
model (a bcast must move ~n bytes, not P*n — r3's model overcharged
it).  A violating point is recorded as null with the violation in
``gated`` — one bad point never discards the sweep (r3 raised away
every measurement).
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

MIB = 1024 * 1024

# HBM peak bytes/s by device kind (generous: judge-gate, not a claim)
_HBM_PEAK = {
    "TPU v5 lite": 0.82e12,
    "TPU v5e": 0.82e12,
    "TPU v4": 1.23e12,
    "TPU v5p": 2.77e12,
    "TPU v6 lite": 1.64e12,
    "TPU v6e": 1.64e12,
}
_HBM_PEAK_DEFAULT = 3.5e12


def _rank_devices(nranks: int):
    import jax

    ndev = len(jax.devices())
    if ndev >= nranks:
        return None, True
    return (lambda r: jax.devices()[r % ndev]), False


def sizes_upto(max_bytes: int, start: int = 4):
    s = start
    while s <= max_bytes:
        yield s
        s *= 2


def should_continue(comm, deadline: float) -> bool:
    """Collectively-agreed deadline check: rank 0 decides, everyone
    follows — ranks must never diverge on whether the next size's
    collectives run."""
    flag = np.array(
        [1 if (deadline <= 0 or time.perf_counter() < deadline) else 0],
        dtype=np.int32)
    comm.Bcast(flag, root=0)
    return bool(flag[0])


def _measure_read_const(probe) -> float:
    """Tunnel-RPC constant of one tiny d2h read (min of 5)."""
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        probe()
        best = min(best, time.perf_counter() - t0)
    return best


def _forced_time(comm, x0, make_op, chain, read_token,
                 read_const: float, deadline: float) -> float:
    """One timed point: N chained op+chain iterations + ONE forced read.

    All ranks iterate (the collective requires it); rank 0 is the
    timekeeper and performs the single completion-forcing read, then
    broadcasts the per-op seconds.  The chain step's data dependency
    makes elision impossible; its cost (one elementwise op over the
    rank's buffer) is included in the reported time — an honest upper
    bound on the collective alone.
    """
    target = max(0.3, 4.0 * read_const)
    max_iters = 1_000_000
    iters = 64 if read_const > 1e-3 else 30  # fast local backends: small N
    while True:
        comm.Barrier()
        t0 = time.perf_counter()
        x = x0
        for _ in range(iters):
            x = chain(make_op(x))
        if comm.rank == 0:
            read_token(x)
            work = time.perf_counter() - t0 - read_const
            over_deadline = (deadline > 0
                             and time.perf_counter() >= deadline)
            if work >= target or iters >= max_iters or over_deadline:
                # deadline-forced acceptance of a jitter-dominated
                # point is reported as unmeasurable, never as a number
                per_op = (work / iters
                          if work > max(0.0, 0.2 * read_const)
                          else -1.0)
                ctl = np.array([1.0, per_op])
            else:
                # project N from the measured round (clamped growth)
                grow = target / max(work, 0.01)
                iters = int(min(max_iters, max(iters * 2, iters * grow)))
                ctl = np.array([0.0, float(iters)])
        else:
            ctl = np.empty(2)
        comm.Bcast(ctl, root=0)
        if ctl[0] == 1.0:
            comm.Barrier()
            return float(ctl[1])
        iters = int(ctl[1])


def _min_traffic_factor(kind: str, nranks: int, single_chip: bool) -> float:
    """Bytes the device MUST move per iteration, as a multiple of the
    point's size key — a LOWER bound per collective, so the gate can
    only catch physically-impossible timings, never flag honest ones.

    Single chip (stacked coll/hbm; every rank's shard lives in the
    one HBM): an allreduce/reduce_scatter must READ all P distinct
    input shards (they are distinct buffers — each rank's chain step
    produced its own).  A bcast's outputs may legally alias the root
    shard (zero-copy is a correct win of the shared-HBM model), but
    each of the P ranks' mandatory chain step still reads+writes its
    n bytes, so >= P*n moves.  An alltoall's size key is the per-pair
    block; each rank holds P blocks, so the chain alone moves
    >= P*(P*b).  On a real mesh the OSU busbw factors apply."""
    if single_chip:
        return {"allreduce": float(nranks),
                "bcast": float(nranks),
                "alltoall": float(nranks * nranks),
                "reduce_scatter": float(nranks)}[kind]
    return {"allreduce": 2.0 * (nranks - 1) / nranks,
            "bcast": 1.0,
            "alltoall": float(nranks - 1),
            "reduce_scatter": (nranks - 1) / nranks}[kind]


def run_device_sweep(nranks: int, max_ar: int, max_bcast: int,
                     max_a2a: int, max_rsb: int,
                     budget_s: float = 0.0) -> dict:
    import jax
    import jax.numpy as jnp

    from ompi_tpu.op import op as mpi_op
    from ompi_tpu.testing import run_ranks

    device_map, devices = _rank_devices(nranks)
    deadline = time.perf_counter() + budget_s if budget_s else 0.0
    single_chip = not devices

    if jax.default_backend() == "tpu":
        kind0 = jax.devices()[0].device_kind
        hbm_peak = _HBM_PEAK.get(kind0, _HBM_PEAK_DEFAULT)
    else:
        hbm_peak = None  # virtual CPU meshes: no physical model

    def fn(comm):
        out = {"allreduce": {}, "bcast": {}, "alltoall": {},
               "reduce_scatter": {}, "truncated": False,
               "read_const_us": None, "gated": []}

        # per-shape probe reads (compiled at warmup); the token is the
        # first element of the flattened result
        token_fns = {}

        def read_token(arr) -> float:
            key = (arr.shape, str(arr.dtype))
            f = token_fns.get(key)
            if f is None:
                f = jax.jit(lambda a: a.reshape(-1)[:1])
                token_fns[key] = f
            return float(np.asarray(f(arr))[0])

        # tunnel-RPC read constant, measured on a warmed tiny read
        read_const = 0.0
        if comm.rank == 0:
            tiny = jnp.zeros((1,), jnp.float32)
            read_token(tiny)  # compile the probe
            read_const = _measure_read_const(lambda: read_token(tiny))
            out["read_const_us"] = round(read_const * 1e6, 1)
        rc = np.array([read_const])
        comm.Bcast(rc, root=0)
        read_const = float(rc[0])

        def one(kind, size_key, x0, make_op, chain, expect0):
            # warmup: compile op + chain + probe, verify the numeric
            # result on BOTH the first and the last rank (a collective
            # broken only on its final ring/tree step passes a
            # rank-0-only check); reads staggered so the tunnel RPCs
            # serialize
            r = make_op(x0)
            if comm.rank == 0:
                got = read_token(r)
                assert abs(got - expect0) < 1e-3, \
                    (kind, size_key, got, expect0)
            comm.Barrier()
            if comm.rank == nranks - 1:
                got = read_token(r)
                assert abs(got - expect0) < 1e-3, \
                    (kind, size_key, got, expect0)
            comm.Barrier()
            c = chain(r)  # compile the chain step outside the timed loop
            if comm.rank == 0:
                # also compile the probe for the CHAIN output's shape:
                # the timed loop's completion read is on a chain
                # result, which for reduce_scatter has a different
                # shape than the op result — an unwarmed probe would
                # put its ~1 s compile inside the measured window
                read_token(c)
            comm.Barrier()
            t = _forced_time(comm, x0, make_op, chain, read_token,
                             read_const, deadline)
            # min-of-2: tunnel RPC jitter on a shared bench host can
            # inflate a single measurement 2-3x (observed: 9.6 ms vs
            # a 2.2 ms repeat at 4 B); every point gets one repeat and
            # keeps the minimum — both runs are full forced-completion
            # measurements, so the min is still an honest upper bound
            # on the op time.  A >5x-the-neighbor outlier earns a
            # third attempt.
            if t > 0 and should_continue(comm, deadline):
                t2 = _forced_time(comm, x0, make_op, chain, read_token,
                                  read_const, deadline)
                if t2 > 0:
                    t = min(t, t2)
            prev = out[kind].get(getattr(one, "_prev_key", None))
            if (t > 0 and prev and t * 1e6 > 5 * prev
                    and should_continue(comm, deadline)):
                t3 = _forced_time(comm, x0, make_op, chain, read_token,
                                  read_const, deadline)
                if t3 > 0:
                    t = min(t, t3)
            one._prev_key = size_key
            # -1 = deadline hit before the point could be amortized
            # past the read-constant jitter: unmeasurable, not a number
            if t <= 0:
                out[kind][size_key] = None
                return
            # physical sanity gate, PER POINT: a time implying more
            # HBM traffic than the chip can move is a measurement
            # artifact — null THIS point with the violation recorded,
            # keep the rest of the sweep (r3 raised away everything)
            if hbm_peak is not None:
                factor = _min_traffic_factor(kind, nranks, single_chip)
                implied = factor * int(size_key) / t
                if implied > 1.05 * hbm_peak:
                    out["gated"].append({
                        "kind": kind, "bytes": int(size_key),
                        "us": round(t * 1e6, 2),
                        "implied_GBs": round(implied / 1e9, 1),
                        "peak_GBs": round(hbm_peak / 1e9, 1),
                        "reason": "implied bandwidth exceeds HBM peak "
                                  "(timing artifact)"})
                    out[kind][size_key] = None
                    return
            out[kind][size_key] = round(t * 1e6, 2)

        # runtime device scalars for the chain steps: values XLA only
        # sees at execution time, so the dependency can never be
        # constant-folded into an identity
        inv_p = jax.device_put(jnp.asarray(1.0 / nranks, jnp.float32),
                               comm.device)
        eps32 = jax.device_put(jnp.asarray(0.0, jnp.float32),
                               comm.device)
        scale_f = jax.jit(lambda a, s: a * s)
        shift_f = jax.jit(lambda a, e: a + e)

        expect_sum = float(sum(range(1, nranks + 1)))
        for nbytes in sizes_upto(max_ar):
            if not should_continue(comm, deadline):
                out["truncated"] = True
                break
            n = max(1, nbytes // 4)
            x = jax.device_put(
                jnp.full((n,), comm.rank + 1.0, jnp.float32), comm.device)
            # steady state: sum(1..P) -> *1/P -> mean -> sum = P*mean
            one("allreduce", str(n * 4), x,
                lambda v: comm.allreduce_arr(v, mpi_op.SUM),
                lambda r: scale_f(r, inv_p), expect_sum)
        if not out["truncated"]:
            for nbytes in sizes_upto(max_bcast):
                if not should_continue(comm, deadline):
                    out["truncated"] = True
                    break
                n = max(1, nbytes // 4)
                x = jax.device_put(
                    jnp.full((n,), 7.0 if comm.rank == 0 else 0.0,
                             jnp.float32), comm.device)
                one("bcast", str(n * 4), x,
                    lambda v: comm.bcast_arr(v, root=0),
                    lambda r: shift_f(r, eps32), 7.0)
        if not out["truncated"]:
            for nbytes in sizes_upto(max_a2a):
                if not should_continue(comm, deadline):
                    out["truncated"] = True
                    break
                per = max(1, nbytes // 4)
                x = jax.device_put(
                    jnp.full((per * nranks,), comm.rank + 1.0,
                             jnp.float32), comm.device)
                one("alltoall", str(per * 4), x,
                    lambda v: comm.alltoall_arr(v),
                    lambda r: shift_f(r, eps32), 1.0)
        if not out["truncated"]:
            # BASELINE config 5 as specified: MPI_MAX on MPI_DOUBLE
            # sourced through a derived VECTOR datatype, with the
            # datatype pack running ON DEVICE (datatype/device.py: the
            # run descriptors become one XLA gather fused into the
            # collective).  float64 needs jax x64; when the backend
            # cannot compile f64 (some TPU generations) the sweep
            # falls back to float32 and RECORDS the substitution
            # instead of silently benching a different config.
            from ompi_tpu.datatype import engine as dtmod
            from ompi_tpu.datatype.device import device_pack
            rs_dtype = jnp.float64
            x64_before = bool(jax.config.jax_enable_x64)
            try:
                jax.config.update("jax_enable_x64", True)
                probe = jax.device_put(jnp.zeros((2,), jnp.float64),
                                       comm.device)
                _ = (probe + 1).dtype
                if np.dtype(probe.dtype) != np.dtype("float64"):
                    rs_dtype = jnp.float32  # x64 unavailable: silent
            except Exception:
                rs_dtype = jnp.float32
            if rs_dtype is jnp.float32:
                # process-global switch: never leave it flipped when
                # the section runs f32 anyway
                jax.config.update("jax_enable_x64", x64_before)
            out["config5_dtype"] = str(np.dtype(rs_dtype))
            itemsize = np.dtype(rs_dtype).itemsize
            base_dt = dtmod.from_numpy_dtype(np.dtype(rs_dtype))
            neg1 = jax.device_put(jnp.asarray(-1.0, rs_dtype),
                                  comm.device)
            for nbytes in sizes_upto(max_rsb, start=64):
                if not should_continue(comm, deadline):
                    out["truncated"] = True
                    break
                per = max(1, nbytes // itemsize // nranks)
                n = per * nranks
                # vector: n blocks of 1 element, stride 2 elements —
                # the packed stream is the even-indexed elements
                vec = dtmod.vector(n, 1, 2, base_dt).commit()
                raw = jax.device_put(
                    jnp.stack([jnp.full((n,), comm.rank + 1.0,
                                        rs_dtype),
                               jnp.full((n,), -1.0, rs_dtype)],
                              axis=1).reshape(-1), comm.device)
                packed_fn = jax.jit(
                    lambda a: device_pack(vec, 1, a))
                packed_fn(raw)  # warm the gather

                # chain: re-interleave the (n/P)-element result back
                # into the strided raw layout — the device_pack gather
                # stays INSIDE the timed loop (it is part of config 5)
                # and every iteration's raw input depends on the
                # previous collective's output
                def reinterleave(prev, filler, _n=n, _p=nranks,
                                 _dt=rs_dtype):
                    main = jnp.tile(prev, _p)[:_n]
                    pad = jnp.broadcast_to(filler, (_n,))
                    return jnp.stack([main, pad], axis=1).reshape(-1)

                chain_fn = jax.jit(reinterleave)
                one("reduce_scatter", str(n * itemsize), raw,
                    lambda v: comm.reduce_scatter_arr(
                        packed_fn(v), mpi_op.MAX),
                    lambda r: chain_fn(r, neg1),
                    float(nranks))

        if "config5_dtype" in out:
            jax.config.update("jax_enable_x64", x64_before)
        comm.Barrier()
        return out

    res = run_ranks(nranks, fn, devices=devices, device_map=device_map,
                    timeout=3600)
    return res[0]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nranks", type=int, default=8)
    ap.add_argument("--max-ar", type=int, default=256 * 1024 * 1024)
    ap.add_argument("--max-bcast", type=int, default=64 * 1024 * 1024)
    ap.add_argument("--max-a2a", type=int, default=4 * 1024 * 1024)
    ap.add_argument("--max-rsb", type=int, default=16 * 1024 * 1024)
    ap.add_argument("--budget", type=float, default=0.0)
    opts = ap.parse_args()
    print(json.dumps(run_device_sweep(
        opts.nranks, opts.max_ar, opts.max_bcast, opts.max_a2a,
        opts.max_rsb, budget_s=opts.budget)), flush=True)


if __name__ == "__main__":
    main()
