"""Device-path sweep: the coll/tpu / coll/hbm side of BASELINE.md.

Runs thread-ranks in-process (the TPU-host execution model) and times
allreduce/bcast/alltoall/reduce_scatter on device-resident arrays
through the XLA collective path.  Used by bench.py; also runnable
directly:  python benchmarks/device_sweep.py --max-ar 1048576

Timing methodology (forced completion — r3 redesign):
on the tunneled TPU backend ``jax.Array.block_until_ready()`` returns
WITHOUT awaiting execution (measured: 10 dispatched 8-MiB 8-way sums
"complete" in 0.37 ms), so any timing that relies on it reports the
dispatch floor, not the op.  Every timed point here instead:

  1. warms up the op AND a tiny per-shape probe read (first read
     compiles; ~1 s on the tunnel), verifying the numeric result;
  2. measures the tunnel-RPC read constant (min of several 4-byte
     d2h reads, ~100 ms on the tunnel);
  3. dispatches N back-to-back collectives (N chosen so
     N*op >= max(0.3 s, 4x read constant), never < 30) and forces
     completion with ONE 4-byte d2h read of the LAST result —
     in-order device execution makes that await all N;
  4. reports (elapsed - read_const) / N, rank 0 as the timekeeper
     (concurrent per-rank reads would serialize on the tunnel).

A physical sanity gate then aborts the sweep if any implied bandwidth
exceeds the chip's HBM peak — a number faster than the hardware is a
measurement bug, not a result.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

MIB = 1024 * 1024

# HBM peak bytes/s by device kind (generous: judge-gate, not a claim)
_HBM_PEAK = {
    "TPU v5 lite": 0.82e12,
    "TPU v5e": 0.82e12,
    "TPU v4": 1.23e12,
    "TPU v5p": 2.77e12,
    "TPU v6 lite": 1.64e12,
    "TPU v6e": 1.64e12,
}
_HBM_PEAK_DEFAULT = 3.5e12


def _rank_devices(nranks: int):
    import jax

    ndev = len(jax.devices())
    if ndev >= nranks:
        return None, True
    return (lambda r: jax.devices()[r % ndev]), False


def sizes_upto(max_bytes: int, start: int = 4):
    s = start
    while s <= max_bytes:
        yield s
        s *= 2


def should_continue(comm, deadline: float) -> bool:
    """Collectively-agreed deadline check: rank 0 decides, everyone
    follows — ranks must never diverge on whether the next size's
    collectives run."""
    flag = np.array(
        [1 if (deadline <= 0 or time.perf_counter() < deadline) else 0],
        dtype=np.int32)
    comm.Bcast(flag, root=0)
    return bool(flag[0])


def _measure_read_const(probe) -> float:
    """Tunnel-RPC constant of one tiny d2h read (min of 5)."""
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        probe()
        best = min(best, time.perf_counter() - t0)
    return best


def _forced_time(comm, make_op, read_token, read_const: float,
                 deadline: float) -> float:
    """One timed point: N back-to-back dispatches + ONE forced read.

    All ranks dispatch (the collective requires it); rank 0 is the
    timekeeper and performs the single completion-forcing read, then
    broadcasts the per-op seconds.  N is picked from a small forced
    probe so N*op >= max(0.3 s, 4x read_const): the read constant's
    jitter (~20 ms on the tunnel) must be amortized into the noise.
    """
    target = max(0.3, 4.0 * read_const)
    max_iters = 1_000_000
    iters = 64 if read_const > 1e-3 else 30  # fast local backends: small N
    while True:
        comm.Barrier()
        t0 = time.perf_counter()
        r = None
        for _ in range(iters):
            r = make_op()
        if comm.rank == 0:
            read_token(r)
            work = time.perf_counter() - t0 - read_const
            over_deadline = (deadline > 0
                             and time.perf_counter() >= deadline)
            if work >= target or iters >= max_iters or over_deadline:
                # deadline-forced acceptance of a jitter-dominated
                # point is reported as unmeasurable, never as a number
                per_op = (work / iters
                          if work > max(0.0, 0.2 * read_const)
                          else -1.0)
                ctl = np.array([1.0, per_op])
            else:
                # project N from the measured round (clamped growth)
                grow = target / max(work, 0.01)
                iters = int(min(max_iters, max(iters * 2, iters * grow)))
                ctl = np.array([0.0, float(iters)])
        else:
            ctl = np.empty(2)
        comm.Bcast(ctl, root=0)
        if ctl[0] == 1.0:
            comm.Barrier()
            return float(ctl[1])
        iters = int(ctl[1])


def _sanity_gate(out: dict, nranks: int, single_chip: bool) -> None:
    """Abort if any implied bandwidth beats the hardware: on a single
    chip every stacked collective must READ all P input shards from
    HBM, so P*n/t <= HBM peak; on a mesh the OSU busbw
    2(P-1)/P * n/t cannot beat HBM peak either (ICI is slower).
    A violation means the timing is a dispatch artifact."""
    import jax

    if jax.default_backend() != "tpu":
        return  # virtual CPU meshes: no physical model to gate on
    kind = jax.devices()[0].device_kind
    peak = _HBM_PEAK.get(kind, _HBM_PEAK_DEFAULT)
    for kind_name, table in out.items():
        if not isinstance(table, dict):
            continue
        for k, us in table.items():
            if k == "truncated" or us is None:
                continue
            nbytes, t = int(k), us * 1e-6
            if t <= 0:
                raise RuntimeError(
                    f"sanity gate: non-positive time {us} us for "
                    f"{kind_name}/{k}B")
            implied = (nranks * nbytes / t if single_chip
                       else 2 * (nranks - 1) / nranks * nbytes / t)
            if implied > 1.05 * peak:
                raise RuntimeError(
                    f"sanity gate: {kind_name} at {nbytes} B implies "
                    f"{implied / 1e9:.0f} GB/s > {peak / 1e9:.0f} GB/s "
                    f"HBM peak of {kind!r} — timing did not await "
                    f"execution (dispatch-floor artifact)")


def run_device_sweep(nranks: int, max_ar: int, max_bcast: int,
                     max_a2a: int, max_rsb: int,
                     budget_s: float = 0.0) -> dict:
    import jax
    import jax.numpy as jnp

    from ompi_tpu.op import op as mpi_op
    from ompi_tpu.testing import run_ranks

    device_map, devices = _rank_devices(nranks)
    deadline = time.perf_counter() + budget_s if budget_s else 0.0

    def fn(comm):
        out = {"allreduce": {}, "bcast": {}, "alltoall": {},
               "reduce_scatter": {}, "truncated": False,
               "read_const_us": None}

        # per-shape probe reads (compiled at warmup); the token is the
        # first element of the flattened result
        token_fns = {}

        def read_token(arr) -> float:
            key = (arr.shape, str(arr.dtype))
            f = token_fns.get(key)
            if f is None:
                f = jax.jit(lambda a: a.reshape(-1)[:1])
                token_fns[key] = f
            return float(np.asarray(f(arr))[0])

        # tunnel-RPC read constant, measured on a warmed tiny read
        read_const = 0.0
        if comm.rank == 0:
            tiny = jnp.zeros((1,), jnp.float32)
            read_token(tiny)  # compile the probe
            read_const = _measure_read_const(lambda: read_token(tiny))
            out["read_const_us"] = round(read_const * 1e6, 1)
        rc = np.array([read_const])
        comm.Bcast(rc, root=0)
        read_const = float(rc[0])

        def one(kind, size_key, make_op, expect0):
            # warmup: compile op + probe, verify the numeric result on
            # BOTH the first and the last rank (a collective broken
            # only on its final ring/tree step passes a rank-0-only
            # check); reads staggered so the tunnel RPCs serialize
            r = make_op()
            if comm.rank == 0:
                got = read_token(r)
                assert abs(got - expect0) < 1e-3, \
                    (kind, size_key, got, expect0)
            comm.Barrier()
            if comm.rank == nranks - 1:
                got = read_token(r)
                assert abs(got - expect0) < 1e-3, \
                    (kind, size_key, got, expect0)
            comm.Barrier()
            t = _forced_time(comm, make_op, read_token, read_const,
                             deadline)
            # outlier guard: a single scheduler hiccup on a shared
            # host can blow one point by 10-50x (observed: 69 ms
            # between 1.4 ms neighbors).  If this point is >5x the
            # previous size's time — physically times should GROW
            # smoothly — re-measure once and keep the minimum (both
            # measurements are full forced-completion runs, so the
            # min is still an honest upper bound on the op time).
            prev = out[kind].get(getattr(one, "_prev_key", None))
            if (t > 0 and prev and t * 1e6 > 5 * prev
                    and should_continue(comm, deadline)):
                t2 = _forced_time(comm, make_op, read_token,
                                  read_const, deadline)
                if t2 > 0:
                    t = min(t, t2)
            one._prev_key = size_key
            # -1 = deadline hit before the point could be amortized
            # past the read-constant jitter: unmeasurable, not a number
            out[kind][size_key] = round(t * 1e6, 2) if t > 0 else None

        expect_sum = float(sum(range(1, nranks + 1)))
        for nbytes in sizes_upto(max_ar):
            if not should_continue(comm, deadline):
                out["truncated"] = True
                break
            n = max(1, nbytes // 4)
            x = jax.device_put(
                jnp.full((n,), comm.rank + 1.0, jnp.float32), comm.device)
            one("allreduce", str(n * 4),
                lambda: comm.allreduce_arr(x, mpi_op.SUM), expect_sum)
        if not out["truncated"]:
            for nbytes in sizes_upto(max_bcast):
                if not should_continue(comm, deadline):
                    out["truncated"] = True
                    break
                n = max(1, nbytes // 4)
                x = jax.device_put(
                    jnp.full((n,), 7.0 if comm.rank == 0 else 0.0,
                             jnp.float32), comm.device)
                one("bcast", str(n * 4),
                    lambda: comm.bcast_arr(x, root=0), 7.0)
        if not out["truncated"]:
            for nbytes in sizes_upto(max_a2a):
                if not should_continue(comm, deadline):
                    out["truncated"] = True
                    break
                per = max(1, nbytes // 4)
                x = jax.device_put(
                    jnp.full((per * nranks,), comm.rank + 1.0,
                             jnp.float32), comm.device)
                one("alltoall", str(per * 4),
                    lambda: comm.alltoall_arr(x), 1.0)
        if not out["truncated"]:
            # BASELINE config 5 as specified: MPI_MAX on MPI_DOUBLE
            # sourced through a derived VECTOR datatype, with the
            # datatype pack running ON DEVICE (datatype/device.py: the
            # run descriptors become one XLA gather fused into the
            # collective).  float64 needs jax x64; when the backend
            # cannot compile f64 (some TPU generations) the sweep
            # falls back to float32 and RECORDS the substitution
            # instead of silently benching a different config.
            from ompi_tpu.datatype import engine as dtmod
            from ompi_tpu.datatype.device import device_pack
            rs_dtype = jnp.float64
            x64_before = bool(jax.config.jax_enable_x64)
            try:
                jax.config.update("jax_enable_x64", True)
                probe = jax.device_put(jnp.zeros((2,), jnp.float64),
                                       comm.device)
                _ = (probe + 1).dtype
                if np.dtype(probe.dtype) != np.dtype("float64"):
                    rs_dtype = jnp.float32  # x64 unavailable: silent
            except Exception:
                rs_dtype = jnp.float32
            if rs_dtype is jnp.float32:
                # process-global switch: never leave it flipped when
                # the section runs f32 anyway
                jax.config.update("jax_enable_x64", x64_before)
            out["config5_dtype"] = str(np.dtype(rs_dtype))
            itemsize = np.dtype(rs_dtype).itemsize
            base_dt = dtmod.from_numpy_dtype(np.dtype(rs_dtype))
            for nbytes in sizes_upto(max_rsb, start=64):
                if not should_continue(comm, deadline):
                    out["truncated"] = True
                    break
                per = max(1, nbytes // itemsize // nranks)
                n = per * nranks
                # vector: n blocks of 1 element, stride 2 elements —
                # the packed stream is the even-indexed elements
                vec = dtmod.vector(n, 1, 2, base_dt).commit()
                raw = jax.device_put(
                    jnp.stack([jnp.full((n,), comm.rank + 1.0,
                                        rs_dtype),
                               jnp.full((n,), -1.0, rs_dtype)],
                              axis=1).reshape(-1), comm.device)
                packed_fn = jax.jit(
                    lambda a: device_pack(vec, 1, a))
                packed_fn(raw)  # warm the gather
                one("reduce_scatter", str(n * itemsize),
                    lambda: comm.reduce_scatter_arr(
                        packed_fn(raw), mpi_op.MAX),
                    float(nranks))

        if "config5_dtype" in out:
            jax.config.update("jax_enable_x64", x64_before)
        comm.Barrier()
        return out

    res = run_ranks(nranks, fn, devices=devices, device_map=device_map,
                    timeout=3600)
    out = res[0]
    import jax as _jax
    single_chip = len(_jax.devices()) < nranks
    _sanity_gate(out, nranks, single_chip)
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nranks", type=int, default=8)
    ap.add_argument("--max-ar", type=int, default=256 * 1024 * 1024)
    ap.add_argument("--max-bcast", type=int, default=64 * 1024 * 1024)
    ap.add_argument("--max-a2a", type=int, default=4 * 1024 * 1024)
    ap.add_argument("--max-rsb", type=int, default=16 * 1024 * 1024)
    ap.add_argument("--budget", type=float, default=0.0)
    opts = ap.parse_args()
    print(json.dumps(run_device_sweep(
        opts.nranks, opts.max_ar, opts.max_bcast, opts.max_a2a,
        opts.max_rsb, budget_s=opts.budget)), flush=True)


if __name__ == "__main__":
    main()
