"""Device-path sweep: the coll/tpu / coll/hbm side of BASELINE.md.

Runs thread-ranks in-process (the TPU-host execution model) and times
allreduce/bcast/alltoall/reduce_scatter on device-resident arrays
through the XLA collective path.  Used by bench.py; also runnable
directly:  python benchmarks/device_sweep.py --max-ar 1048576

Timing methodology (forced completion + chained dependency — r4;
quiet-gated reads + dual-mode allreduce — r5):
on the tunneled TPU backend ``jax.Array.block_until_ready()`` returns
WITHOUT awaiting execution (measured: 10 dispatched 8-MiB 8-way sums
"complete" in 0.37 ms), so any timing that relies on it reports the
dispatch floor, not the op.  And N dispatches of the same op on the
SAME input carry no data dependency, so XLA/the runtime may alias or
elide them (r3's failure: a stacked bcast is near-free metadata).
Every timed point here instead:

  1. warms up the op AND a tiny per-shape probe read (first read
     compiles; ~1 s on the tunnel), verifying the numeric result;
  2. measures the tunnel-RPC read constant (min of several 4-byte
     d2h reads);
  3. runs N CHAINED iterations where each op's input depends on the
     previous op's output (the device must fully execute op k before
     op k+1 can start, and no op can be aliased out), then forces
     completion with ONE 4-byte d2h read of the LAST result
     (in-order device execution awaits the whole chain);
  4. reports (elapsed - read_const) / N, rank 0 as the timekeeper.

Quiet gate (r5): every d2h read — the read-constant probes, warmup
verification, and each timed round's completion read — runs while
the other rank-threads SLEEP on a threading.Event instead of polling
inside a software collective.  The r4 sweep's reads ran against 7
polling peers and cost ~20x the idle read constant; the excess was
charged to the ops, putting a false ~1 ms floor on every device
point.  The subtraction in (4) is only honest when the measured
constant and the in-loop read share a context — now both are quiet.

Allreduce runs in two modes (single-chip):

  * latency mode (< 1 MiB): every rank deposits the SHARED previous
    output; the op->op feedback is the data dependency.  No per-rank
    chain step — the r4 chain cost 8 extra cross-thread dispatches
    per iteration, which the tunneled backend serializes at ~0.5-1 ms
    (cross-thread dependency chains are pathological; see
    coll/device._DeviceDispatcher).  Values stay finite via an EXACT
    power-of-two rescale (one extra dispatch per rank every 32 ops:
    x * 2^-96 after 32 sums of 8 == x, bit-exact in f32).  Inputs
    alias at these sizes, so the HBM-gate traffic factor drops to 2
    (read n + write n) — immaterial: these points are latency-bound
    by ~300 us of tunnel dispatch, three orders of magnitude above
    the HBM time of the payload.
  * bandwidth mode (>= 1 MiB, and always on real meshes): the r4
    methodology — each rank's own chain step (multiply by a runtime
    device scalar) produces P DISTINCT input buffers per iteration,
    so the op must move the full P*n bytes and the reported busbw is
    honest at sizes where traffic, not dispatch, dominates.

A physical sanity gate then checks each point's implied bandwidth
against the chip's HBM peak, using a PER-COLLECTIVE minimal-traffic
model (a LOWER bound, so the gate can only catch physically-
impossible timings).  A violating point is recorded as null with the
violation in ``gated``.

Budget (r5): the wall-clock budget is SPLIT per collective up front
(allreduce 45% — it carries the north-star verdict and sweeps every
power of two >= 4 KiB; bcast/alltoall 15% each and reduce_scatter 25%
on SPARSE size sets — 4/8/16 B tell one story, so non-gating
collectives keep a handful of representative sizes and always reach
their caps).  Leftover budget rolls forward.  The r4 failure mode —
27 allreduce sizes starving reduce_scatter to a 2 KiB toy table —
cannot recur: each collective owns its window.
"""

from __future__ import annotations

import argparse
import json
import threading
import time

import numpy as np

MIB = 1024 * 1024

# HBM peak bytes/s by device kind (generous: judge-gate, not a claim)
_HBM_PEAK = {
    "TPU v5 lite": 0.82e12,
    "TPU v5e": 0.82e12,
    "TPU v4": 1.23e12,
    "TPU v5p": 2.77e12,
    "TPU v6 lite": 1.64e12,
    "TPU v6e": 1.64e12,
}
_HBM_PEAK_DEFAULT = 3.5e12

# latency-mode/bandwidth-mode crossover (single-chip allreduce)
_LAT_MAX = 1 * MIB
_RESCALE_EVERY = 32  # 8^32 = 2^96: rescale by 2^-96 is bit-exact f32

# sparse size sets for the non-north-star collectives (each reaches
# its BASELINE cap; intermediate powers of two tell the same story
# as their neighbors and starved the r4 sweep)
_BCAST_SIZES = (4, 4096, 65536, MIB, 8 * MIB, 64 * MIB)
_A2A_SIZES = (4, 4096, 65536, MIB, 4 * MIB)
_RSB_SIZES = (64, 4096, 65536, MIB, 16 * MIB)
# allreduce: three latency points below the verdict cut, then EVERY
# power of two >= 4 KiB (the north star is per-size there)
_AR_SMALL = (4, 256, 2048)


class _QuietGate:
    """Sleep-parked meeting for the measurement harness itself: the
    reading rank works while every other rank waits on an Event (a
    real futex sleep — no progress sweeps, no GIL churn against the
    tunnel RPC).  Two cyclic-barrier phases bound each round."""

    def __init__(self, n: int) -> None:
        self.barrier = threading.Barrier(n)
        self.ev = threading.Event()
        self.box: dict = {}

    def run(self, rank: int, who: int, fn):
        """All ranks call; ``fn`` runs on rank ``who`` alone while the
        rest sleep.  Returns fn()'s value on every rank."""
        self.barrier.wait()
        if rank == who:
            try:
                self.box["out"] = ("ok", fn())
            except BaseException as e:  # noqa: BLE001
                self.box["out"] = ("err", e)
            self.ev.set()
        else:
            self.ev.wait()
        self.barrier.wait()
        kind, val = self.box["out"]
        self.barrier.wait()
        if rank == who:
            self.ev = threading.Event()  # fresh before the next round
        self.barrier.wait()
        if kind == "err":
            raise RuntimeError(f"quiet-gated read failed: {val}") \
                from (val if rank == who else None)
        return val


def _rank_devices(nranks: int):
    import jax

    ndev = len(jax.devices())
    if ndev >= nranks:
        return None, True
    return (lambda r: jax.devices()[r % ndev]), False


def sizes_upto(max_bytes: int, start: int = 4):
    s = start
    while s <= max_bytes:
        yield s
        s *= 2


def _ar_sizes(max_ar: int):
    for s in _AR_SMALL:
        if s <= max_ar:
            yield s
    for s in sizes_upto(max_ar, start=4096):
        yield s


def _measure_read_const(probe) -> float:
    """Tunnel-RPC constant of one tiny d2h read (min of 5)."""
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        probe()
        best = min(best, time.perf_counter() - t0)
    return best


def _forced_time(comm, gate: _QuietGate, x0, make_op, chain,
                 read_token, read_const: float, deadline: float) -> float:
    """One timed point: N chained op+chain iterations + ONE forced read.

    All ranks iterate (the collective requires it); rank 0 is the
    timekeeper.  Its completion-forcing read runs under the quiet
    gate — peers sleep, so the read costs the same constant the
    harness measured and subtracts.
    """
    target = max(0.3, 4.0 * read_const)
    max_iters = 1_000_000
    iters = 64 if read_const > 1e-3 else 30  # fast local backends: small N
    me = comm.rank
    while True:
        gate.barrier.wait()
        t0 = time.perf_counter()
        x = x0
        for _ in range(iters):
            x = chain(make_op(x))

        def finish():
            read_token(x)
            work = time.perf_counter() - t0 - read_const
            over_deadline = (deadline > 0
                             and time.perf_counter() >= deadline)
            if work >= target or iters >= max_iters or over_deadline:
                # deadline-forced acceptance of a jitter-dominated
                # point is reported as unmeasurable, never as a number
                per_op = (work / iters
                          if work > max(0.0, 0.2 * read_const)
                          else -1.0)
                return (1.0, per_op)
            # project N from the measured round (clamped growth)
            grow = target / max(work, 0.01)
            return (0.0, float(int(min(max_iters,
                                       max(iters * 2, iters * grow)))))

        done, val = gate.run(me, 0, finish)
        if done == 1.0:
            return float(val)
        iters = int(val)


def _min_traffic_factor(kind: str, nranks: int, single_chip: bool,
                        latency_mode: bool = False) -> float:
    """Bytes the device MUST move per iteration, as a multiple of the
    point's size key — a LOWER bound per collective, so the gate can
    only catch physically-impossible timings, never flag honest ones.

    Single chip (stacked coll/hbm; every rank's shard lives in the
    one HBM), bandwidth mode: an allreduce/reduce_scatter must READ
    all P distinct input shards (they are distinct buffers — each
    rank's chain step produced its own).  A bcast's outputs may
    legally alias the root shard (zero-copy is a correct win of the
    shared-HBM model), but each of the P ranks' mandatory chain step
    still reads+writes its n bytes, so >= P*n moves.  An alltoall's
    size key is the per-pair block; each rank holds P blocks, so the
    chain alone moves >= P*(P*b).  Latency-mode allreduce deposits
    alias (see module docstring): the op still must read its input
    and write a fresh output — >= 2n.  On a real mesh the OSU busbw
    factors apply."""
    if single_chip:
        if kind == "allreduce" and latency_mode:
            return 2.0
        return {"allreduce": float(nranks),
                "bcast": float(nranks),
                "alltoall": float(nranks * nranks),
                "reduce_scatter": float(nranks)}[kind]
    return {"allreduce": 2.0 * (nranks - 1) / nranks,
            "bcast": 1.0,
            "alltoall": float(nranks - 1),
            "reduce_scatter": (nranks - 1) / nranks}[kind]


def run_device_sweep(nranks: int, max_ar: int, max_bcast: int,
                     max_a2a: int, max_rsb: int,
                     budget_s: float = 0.0) -> dict:
    import jax
    import jax.numpy as jnp

    from ompi_tpu.op import op as mpi_op
    from ompi_tpu.testing import run_ranks

    device_map, devices = _rank_devices(nranks)
    single_chip = not devices
    gate = _QuietGate(nranks)
    t_start = time.perf_counter()

    # per-collective budget windows (fractions of the total); unused
    # time rolls forward because each deadline is computed when its
    # collective STARTS from the time actually remaining
    shares = {"allreduce": 0.45, "bcast": 0.15, "alltoall": 0.15,
              "reduce_scatter": 0.25}

    if jax.default_backend() == "tpu":
        kind0 = jax.devices()[0].device_kind
        hbm_peak = _HBM_PEAK.get(kind0, _HBM_PEAK_DEFAULT)
    else:
        hbm_peak = None  # virtual CPU meshes: no physical model

    def fn(comm):
        out = {"allreduce": {}, "bcast": {}, "alltoall": {},
               "reduce_scatter": {}, "truncated": False,
               "read_const_us": None, "gated": [],
               "latency_mode_below": _LAT_MAX if single_chip else 0}

        # per-shape probe reads (compiled at warmup); the token is the
        # first element of the flattened result
        token_fns = {}

        def read_token(arr) -> float:
            key = (arr.shape, str(arr.dtype))
            f = token_fns.get(key)
            if f is None:
                f = jax.jit(lambda a: a.reshape(-1)[:1])
                token_fns[key] = f
            return float(np.asarray(f(arr))[0])

        # tunnel-RPC read constant, measured on a warmed tiny read
        # UNDER THE QUIET GATE — the same context as every in-loop
        # completion read it will be subtracted from
        tiny = jnp.zeros((1,), jnp.float32)

        def warm_and_measure():
            read_token(tiny)  # compile the probe
            return _measure_read_const(lambda: read_token(tiny))

        read_const = gate.run(comm.rank, 0, warm_and_measure)
        if comm.rank == 0:
            out["read_const_us"] = round(read_const * 1e6, 1)

        def budget_deadline(kind: str) -> float:
            if not budget_s:
                return 0.0
            remaining = budget_s - (time.perf_counter() - t_start)
            later = {"allreduce": ("bcast", "alltoall",
                                   "reduce_scatter"),
                     "bcast": ("alltoall", "reduce_scatter"),
                     "alltoall": ("reduce_scatter",),
                     "reduce_scatter": ()}[kind]
            frac = shares[kind] / (shares[kind]
                                   + sum(shares[k] for k in later))
            return time.perf_counter() + max(5.0, remaining * frac)

        def should_continue(deadline: float,
                            projected_s: float = 0.0) -> bool:
            # rank 0 decides; the gate distributes — ranks must never
            # diverge on whether the next size's collectives run.
            # ``projected_s`` gates ENTRY into a size whose warmup +
            # rounds alone would blow the window (the r2 starvation
            # pattern: an unbudgeted 128 MiB probe ate the budget)
            return gate.run(
                comm.rank, 0,
                lambda: deadline <= 0
                or time.perf_counter() + projected_s < deadline)

        def trace(msg: str) -> None:
            if comm.rank == 0:
                import sys as _sys
                print(f"[sweep +{time.perf_counter() - t_start:6.1f}s] "
                      f"{msg}", file=_sys.stderr, flush=True)

        def one(kind, size_key, x0, make_op, chain, expect0,
                deadline, latency_mode=False, min_of=2):
            # warmup: compile op + chain + probe, verify the numeric
            # result on BOTH the first and the last rank (a collective
            # broken only on its final ring/tree step passes a
            # rank-0-only check); all reads quiet-gated
            r = make_op(x0)
            got = gate.run(comm.rank, 0, lambda: read_token(r))
            if comm.rank == 0:
                assert abs(got - expect0) < 1e-3, \
                    (kind, size_key, got, expect0)
            got = gate.run(comm.rank, nranks - 1,
                           lambda: read_token(r))
            if comm.rank == nranks - 1:
                assert abs(got - expect0) < 1e-3, \
                    (kind, size_key, got, expect0)
            c = chain(r)  # compile the chain step outside the timed loop
            # also compile the probe for the CHAIN output's shape: the
            # timed loop's completion read is on a chain result, which
            # for reduce_scatter has a different shape than the op
            # result — an unwarmed probe would put its ~1 s compile
            # inside the measured window
            gate.run(comm.rank, 0, lambda: read_token(c))
            ts = []
            for _ in range(min_of):
                t = _forced_time(comm, gate, x0, make_op, chain,
                                 read_token, read_const, deadline)
                if t > 0:
                    ts.append(t)
                if not should_continue(deadline):
                    break
            if not ts:
                # deadline hit before the point could be amortized
                # past the read-constant jitter: unmeasurable, not a
                # number
                out[kind][size_key] = None
                return
            t = min(ts)
            # physical sanity gate, PER POINT: a time implying more
            # HBM traffic than the chip can move is a measurement
            # artifact — null THIS point with the violation recorded,
            # keep the rest of the sweep (r3 raised away everything)
            if hbm_peak is not None:
                factor = _min_traffic_factor(kind, nranks, single_chip,
                                             latency_mode)
                implied = factor * int(size_key) / t
                if implied > 1.05 * hbm_peak:
                    out["gated"].append({
                        "kind": kind, "bytes": int(size_key),
                        "us": round(t * 1e6, 2),
                        "implied_GBs": round(implied / 1e9, 1),
                        "peak_GBs": round(hbm_peak / 1e9, 1),
                        "reason": "implied bandwidth exceeds HBM peak "
                                  "(timing artifact)"})
                    out[kind][size_key] = None
                    return
            out[kind][size_key] = round(t * 1e6, 2)

        # runtime device scalars for the chain steps: values XLA only
        # sees at execution time, so the dependency can never be
        # constant-folded into an identity
        inv_p = jax.device_put(jnp.asarray(1.0 / nranks, jnp.float32),
                               comm.device)
        eps32 = jax.device_put(jnp.asarray(0.0, jnp.float32),
                               comm.device)
        # 8 ranks x 32 feedback sums multiply values by 8^32 = 2^96
        # exactly; the rescale restores them bit-for-bit (powers of
        # two are exact in f32 and 36*2^96 ~ 2.9e30 < f32 max)
        descale = jax.device_put(
            jnp.asarray(float(nranks) ** -_RESCALE_EVERY, jnp.float32),
            comm.device)
        scale_f = jax.jit(lambda a, s: a * s)
        shift_f = jax.jit(lambda a, e: a + e)

        expect_sum = float(sum(range(1, nranks + 1)))
        ar_deadline = budget_deadline("allreduce")
        last_cost = [0.0]
        for nbytes in _ar_sizes(max_ar):
            # a size costs ~2x its predecessor (warmup + rounds scale
            # with the payload); entry is gated on that projection
            if not should_continue(ar_deadline, 2.0 * last_cost[0]):
                out["allreduce"]["truncated"] = True
                break
            t_size = time.perf_counter()
            trace(f"allreduce {nbytes}B start")
            n = max(1, nbytes // 4)
            x = jax.device_put(
                jnp.full((n,), comm.rank + 1.0, jnp.float32), comm.device)
            latency_mode = single_chip and nbytes < _LAT_MAX
            if latency_mode:
                # feedback chain: the op's own output is the next
                # input (shared across ranks); exact rescale every
                # _RESCALE_EVERY ops keeps values finite.  The chain
                # closure carries the op counter — per-rank state,
                # advanced identically on every rank.
                ctr = [0]

                def chain_lat(r):
                    ctr[0] += 1
                    if ctr[0] % _RESCALE_EVERY == 0:
                        return scale_f(r, descale)
                    return r

                one("allreduce", str(n * 4), x,
                    lambda v: comm.allreduce_arr(v, mpi_op.SUM),
                    chain_lat, expect_sum, ar_deadline,
                    latency_mode=True, min_of=2)
            else:
                # steady state: sum(1..P) -> *1/P -> mean -> sum
                one("allreduce", str(n * 4), x,
                    lambda v: comm.allreduce_arr(v, mpi_op.SUM),
                    lambda r: scale_f(r, inv_p), expect_sum,
                    ar_deadline)
            last_cost[0] = time.perf_counter() - t_size
            trace(f"allreduce {nbytes}B done in {last_cost[0]:.1f}s -> "
                  f"{out['allreduce'].get(str(max(1, nbytes // 4) * 4))}")
        bc_deadline = budget_deadline("bcast")
        for nbytes in _BCAST_SIZES:
            if nbytes > max_bcast:
                break
            if not should_continue(bc_deadline):
                out["bcast"]["truncated"] = True
                break
            n = max(1, nbytes // 4)
            x = jax.device_put(
                jnp.full((n,), 7.0 if comm.rank == 0 else 0.0,
                         jnp.float32), comm.device)
            one("bcast", str(n * 4), x,
                lambda v: comm.bcast_arr(v, root=0),
                lambda r: shift_f(r, eps32), 7.0, bc_deadline)
            trace(f"bcast {nbytes}B -> "
                  f"{out['bcast'].get(str(max(1, nbytes // 4) * 4))}")
        a2a_deadline = budget_deadline("alltoall")
        for nbytes in _A2A_SIZES:
            if nbytes > max_a2a:
                break
            if not should_continue(a2a_deadline):
                out["alltoall"]["truncated"] = True
                break
            per = max(1, nbytes // 4)
            x = jax.device_put(
                jnp.full((per * nranks,), comm.rank + 1.0,
                         jnp.float32), comm.device)
            one("alltoall", str(per * 4), x,
                lambda v: comm.alltoall_arr(v),
                lambda r: shift_f(r, eps32), 1.0, a2a_deadline)
            trace(f"alltoall {nbytes}B -> "
                  f"{out['alltoall'].get(str(max(1, nbytes // 4) * 4))}")
        if max_rsb:
            # BASELINE config 5 as specified: MPI_MAX on MPI_DOUBLE
            # sourced through a derived VECTOR datatype, with the
            # datatype pack running ON DEVICE (datatype/device.py: the
            # run descriptors become one XLA gather fused into the
            # collective).  float64 needs jax x64; when the backend
            # cannot compile f64 (some TPU generations) the sweep
            # falls back to float32 and RECORDS the substitution
            # instead of silently benching a different config.
            from ompi_tpu.datatype import engine as dtmod
            from ompi_tpu.datatype.device import device_pack
            rs_dtype = jnp.float64
            x64_before = bool(jax.config.jax_enable_x64)
            try:
                jax.config.update("jax_enable_x64", True)
                probe = jax.device_put(jnp.zeros((2,), jnp.float64),
                                       comm.device)
                _ = (probe + 1).dtype
                if np.dtype(probe.dtype) != np.dtype("float64"):
                    rs_dtype = jnp.float32  # x64 unavailable: silent
            except Exception:
                rs_dtype = jnp.float32
            if rs_dtype is jnp.float32:
                # process-global switch: never leave it flipped when
                # the section runs f32 anyway
                jax.config.update("jax_enable_x64", x64_before)
            out["config5_dtype"] = str(np.dtype(rs_dtype))
            itemsize = np.dtype(rs_dtype).itemsize
            base_dt = dtmod.from_numpy_dtype(np.dtype(rs_dtype))
            neg1 = jax.device_put(jnp.asarray(-1.0, rs_dtype),
                                  comm.device)
            rsb_deadline = budget_deadline("reduce_scatter")
            for nbytes in _RSB_SIZES:
                if nbytes > max_rsb:
                    break
                if not should_continue(rsb_deadline):
                    out["reduce_scatter"]["truncated"] = True
                    break
                per = max(1, nbytes // itemsize // nranks)
                n = per * nranks
                # vector: n blocks of 1 element, stride 2 elements —
                # the packed stream is the even-indexed elements
                vec = dtmod.vector(n, 1, 2, base_dt).commit()
                raw = jax.device_put(
                    jnp.stack([jnp.full((n,), comm.rank + 1.0,
                                        rs_dtype),
                               jnp.full((n,), -1.0, rs_dtype)],
                              axis=1).reshape(-1), comm.device)
                packed_fn = jax.jit(
                    lambda a: device_pack(vec, 1, a))
                packed_fn(raw)  # warm the gather

                # chain: re-interleave the (n/P)-element result back
                # into the strided raw layout — the device_pack gather
                # stays INSIDE the timed loop (it is part of config 5)
                # and every iteration's raw input depends on the
                # previous collective's output
                def reinterleave(prev, filler, _n=n, _p=nranks,
                                 _dt=rs_dtype):
                    main = jnp.tile(prev, _p)[:_n]
                    pad = jnp.broadcast_to(filler, (_n,))
                    return jnp.stack([main, pad], axis=1).reshape(-1)

                chain_fn = jax.jit(reinterleave)
                one("reduce_scatter", str(n * itemsize), raw,
                    lambda v: comm.reduce_scatter_arr(
                        packed_fn(v), mpi_op.MAX),
                    lambda r: chain_fn(r, neg1),
                    float(nranks), rsb_deadline)
                trace(f"reduce_scatter {nbytes}B -> "
                      f"{out['reduce_scatter'].get(str(n * itemsize))}")
            if "config5_dtype" in out:
                jax.config.update("jax_enable_x64", x64_before)
        out["truncated"] = any(
            isinstance(v, dict) and v.get("truncated")
            for v in out.values())
        comm.Barrier()
        return out

    res = run_ranks(nranks, fn, devices=devices, device_map=device_map,
                    timeout=3600)
    return res[0]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nranks", type=int, default=8)
    ap.add_argument("--max-ar", type=int, default=256 * 1024 * 1024)
    ap.add_argument("--max-bcast", type=int, default=64 * 1024 * 1024)
    ap.add_argument("--max-a2a", type=int, default=4 * 1024 * 1024)
    ap.add_argument("--max-rsb", type=int, default=16 * 1024 * 1024)
    ap.add_argument("--budget", type=float, default=0.0)
    opts = ap.parse_args()
    print(json.dumps(run_device_sweep(
        opts.nranks, opts.max_ar, opts.max_bcast, opts.max_a2a,
        opts.max_rsb, budget_s=opts.budget)), flush=True)


if __name__ == "__main__":
    main()
