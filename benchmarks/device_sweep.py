"""Device-path sweep: the coll/tpu / coll/hbm side of BASELINE.md.

Runs thread-ranks in-process (the TPU-host execution model) and times
allreduce/bcast/alltoall/reduce_scatter on device-resident arrays
through the XLA collective path.  Used by bench.py; also runnable
directly:  python benchmarks/device_sweep.py --max-ar 1048576

Two-phase structure — TIME EVERYTHING FIRST, VERIFY AT THE END:
on tunneled-TPU backends (the CI axon plugin) any device->host
transfer permanently degrades subsequent dispatch latency by ~3
orders of magnitude, so the timing phase performs zero host reads;
results are held as device arrays and asserted afterwards (a
fast-but-wrong bench is still worthless, the check just moves).
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _rank_devices(nranks: int):
    import jax

    ndev = len(jax.devices())
    if ndev >= nranks:
        return None, True
    return (lambda r: jax.devices()[r % ndev]), False


def sizes_upto(max_bytes: int, start: int = 4):
    s = start
    while s <= max_bytes:
        yield s
        s *= 2


def should_continue(comm, deadline: float) -> bool:
    """Collectively-agreed deadline check: rank 0 decides, everyone
    follows — ranks must never diverge on whether the next size's
    collectives run."""
    flag = np.array(
        [1 if (deadline <= 0 or time.perf_counter() < deadline) else 0],
        dtype=np.int32)
    comm.Bcast(flag, root=0)
    return bool(flag[0])


def _time_arr(comm, make_op, probe_s: float) -> float:
    """Iteration count decided by rank 0 and broadcast — every rank
    must run the same number of collectives; capped so one slow size
    can never eat the whole budget."""
    from ompi_tpu.op import op as mpi_op

    it = np.array([max(2, min(50, int(0.2 / max(probe_s, 1e-6))))],
                  dtype=np.int32)
    comm.Bcast(it, root=0)
    iters = int(it[0])
    comm.Barrier()
    t0 = time.perf_counter()
    r = None
    for _ in range(iters):
        r = make_op()
    r.block_until_ready()
    mine = np.array([(time.perf_counter() - t0) / iters])
    worst = np.empty_like(mine)
    comm.Allreduce(mine, worst, mpi_op.MAX)
    return float(worst[0])


def run_device_sweep(nranks: int, max_ar: int, max_bcast: int,
                     max_a2a: int, max_rsb: int,
                     budget_s: float = 0.0) -> dict:
    import jax
    import jax.numpy as jnp

    from ompi_tpu.op import op as mpi_op
    from ompi_tpu.testing import run_ranks

    device_map, devices = _rank_devices(nranks)
    deadline = time.perf_counter() + budget_s if budget_s else 0.0

    def fn(comm):
        out = {"allreduce": {}, "bcast": {}, "alltoall": {},
               "reduce_scatter": {}, "truncated": False}
        # deferred correctness checks: (kind, size_key, result,
        # expected first element) — read ONLY in the verify phase
        checks = []

        def one(kind, size_key, make_op, expect0):
            r = make_op()
            r.block_until_ready()  # compile
            t0 = time.perf_counter()
            r = make_op()
            r.block_until_ready()  # probe
            probe = time.perf_counter() - t0
            out[kind][size_key] = round(
                _time_arr(comm, make_op, probe) * 1e6, 2)
            checks.append((kind, size_key, r, expect0))

        expect_sum = float(sum(range(1, nranks + 1)))
        for nbytes in sizes_upto(max_ar):
            if not should_continue(comm, deadline):
                out["truncated"] = True
                break
            n = max(1, nbytes // 4)
            x = jax.device_put(
                jnp.full((n,), comm.rank + 1.0, jnp.float32), comm.device)
            one("allreduce", str(n * 4),
                lambda: comm.allreduce_arr(x, mpi_op.SUM), expect_sum)
        if not out["truncated"]:
            for nbytes in sizes_upto(max_bcast):
                if not should_continue(comm, deadline):
                    out["truncated"] = True
                    break
                n = max(1, nbytes // 4)
                x = jax.device_put(
                    jnp.full((n,), 7.0 if comm.rank == 0 else 0.0,
                             jnp.float32), comm.device)
                one("bcast", str(n * 4),
                    lambda: comm.bcast_arr(x, root=0), 7.0)
        if not out["truncated"]:
            for nbytes in sizes_upto(max_a2a):
                if not should_continue(comm, deadline):
                    out["truncated"] = True
                    break
                per = max(1, nbytes // 4)
                x = jax.device_put(
                    jnp.full((per * nranks,), comm.rank + 1.0,
                             jnp.float32), comm.device)
                one("alltoall", str(per * 4),
                    lambda: comm.alltoall_arr(x), 1.0)
        if not out["truncated"]:
            for nbytes in sizes_upto(max_rsb, start=64):
                if not should_continue(comm, deadline):
                    out["truncated"] = True
                    break
                per = max(1, nbytes // 4 // nranks)
                x = jax.device_put(
                    jnp.full((per * nranks,), comm.rank + 1.0,
                             jnp.float32), comm.device)
                # SUM: the op with a native scatter-reduce lowering on
                # both device paths (psum_scatter / stacked sum); the
                # software sweep keeps BASELINE config 5's exact
                # MAX-on-DOUBLE-via-vector form
                one("reduce_scatter", str(per * nranks * 4),
                    lambda: comm.reduce_scatter_arr(x, mpi_op.SUM),
                    expect_sum)

        # verify phase: first host reads of the whole run.  Two ranks
        # suffice (results are either identical across ranks or
        # per-rank with identical element 0) and keep the slow
        # post-read path off the other threads.
        comm.Barrier()
        if comm.rank in (0, nranks - 1):
            for kind, size_key, r, expect0 in checks:
                got = float(np.asarray(r).ravel()[0])
                assert abs(got - expect0) < 1e-3, \
                    (kind, size_key, got, expect0)
        comm.Barrier()
        return out

    res = run_ranks(nranks, fn, devices=devices, device_map=device_map,
                    timeout=3600)
    return res[0]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nranks", type=int, default=8)
    ap.add_argument("--max-ar", type=int, default=256 * 1024 * 1024)
    ap.add_argument("--max-bcast", type=int, default=64 * 1024 * 1024)
    ap.add_argument("--max-a2a", type=int, default=4 * 1024 * 1024)
    ap.add_argument("--max-rsb", type=int, default=16 * 1024 * 1024)
    ap.add_argument("--budget", type=float, default=0.0)
    opts = ap.parse_args()
    print(json.dumps(run_device_sweep(
        opts.nranks, opts.max_ar, opts.max_bcast, opts.max_a2a,
        opts.max_rsb, budget_s=opts.budget)), flush=True)


if __name__ == "__main__":
    main()
