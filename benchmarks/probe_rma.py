"""--probe-rma microbench: OSU-style one-sided ladders for BOTH osc
components — put/get busbw over the 64 KiB .. 64 MiB size ladder
(CI default caps at 4 MiB), accumulate rate, fetch_and_op latency —
device (HBM shards, whole-mesh kernels) versus pt2pt (host AM over
the pml).

One thread-rank device world runs both components: the pt2pt side is
forced with ``--mca osc pt2pt`` (``registry.set``) plus a per-comm
``_osc_pick`` drop, exactly the override path users have, so the
probe measures the same selection machinery it benchmarks.  Rank 0
is the origin; every other rank is parked in a Barrier whose wait
loop drives progress, so the pt2pt target still applies AMs — and
the device side needs no target participation at all, which is the
point.

put/get busbw is the unidirectional OSU convention nbytes*reps/t,
with OSU's windowed issue (osu_put_bw posts a window of 64 ops per
sync; we use 32) — the flush that completes the window is inside the
timed region, so deferred-completion paths pay their copy where OSU
would charge it.  Each window is timed individually and the MEDIAN
is reported, as in probe_pipeline.  Results persist under ``probe_rma`` in
BENCH_DETAIL.json (read-modify-write) and feed --regress through
``rma_*`` metrics.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List

SIZES = tuple((64 << 10) * 4 ** k for k in range(6))  # 64K .. 64M
DEFAULT_MAX_BYTES = 4 << 20

COMPONENTS = ("device", "pt2pt")


def _median_us(samples: List[float]) -> float:
    samples = sorted(samples)
    mid = len(samples) // 2
    med = samples[mid] if len(samples) % 2 else \
        (samples[mid - 1] + samples[mid]) / 2
    return med * 1e6


def _page_aligned(nbytes: int, seed: int):
    """Random payload in a page-aligned buffer — the OSU benchmark
    convention (posix_memalign to page size), and what lets the
    device component's zero-copy put path engage."""
    import numpy as np
    raw = np.empty(nbytes + 4096, dtype=np.uint8)
    off = (-raw.ctypes.data) % 4096
    buf = raw[off: off + nbytes]
    buf[:] = np.random.default_rng(seed).integers(
        0, 256, nbytes, dtype=np.uint8)
    return buf


def _force(comm, comp: str) -> None:
    """Restrict osc selection to one component and drop the cached
    per-comm verdict (the --mca osc override path)."""
    from ompi_tpu.mca.params import registry
    registry.set("osc", "" if comp == "device" else comp)
    comm.__dict__.pop("_osc_pick", None)


def run_probe(nranks: int = 4, reps: int = 32,
              max_bytes: int = DEFAULT_MAX_BYTES) -> Dict:
    # the device component needs DISTINCT devices per rank (a window
    # commits to the comm's mesh): fan the host platform out before
    # jax initializes.  bench.py never imports jax itself, so a
    # standalone --probe-rma run always gets here first.
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            f" --xla_force_host_platform_device_count={nranks}")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from ompi_tpu.testing import run_ranks

    sizes = [nb for nb in SIZES if nb <= max_bytes] or [SIZES[0]]

    def fn(comm):
        import numpy as np
        from ompi_tpu import osc
        from ompi_tpu.mca.params import registry
        from ompi_tpu.op.op import SUM

        me = comm.rank
        out: Dict[str, Dict] = {
            c: {"put_us": {}, "get_us": {},
                "put_busbw_gbs": {}, "get_busbw_gbs": {}}
            for c in COMPONENTS}
        try:
            for comp in COMPONENTS:
                for nb in sizes:
                    _force(comm, comp)
                    win = osc.allocate(comm, nb, name=f"rma-{comp}")
                    assert type(win).__name__ == (
                        "DeviceWindow" if comp == "device" else
                        "Window"), type(win)
                    blob = _page_aligned(nb, seed=nb)
                    r = max(4, min(reps, (256 << 20) // nb))
                    if me == 0:
                        win.lock(1, osc.LOCK_SHARED)
                        for _ in range(2):  # warm: compile + route
                            win.put(blob, 1)
                            win.flush(1)
                        ps: List[float] = []
                        for _ in range(3):
                            t0 = time.perf_counter()
                            for _ in range(r):
                                win.put(blob, 1)
                            win.flush(1)
                            ps.append((time.perf_counter() - t0) / r)
                        back = np.empty(nb, dtype=np.uint8)
                        win.get(back, 1)  # warm
                        gs: List[float] = []
                        for _ in range(3):
                            t0 = time.perf_counter()
                            for _ in range(r):
                                win.get(back, 1)
                            gs.append((time.perf_counter() - t0) / r)
                        win.unlock(1)
                        assert bytes(back) == bytes(blob), \
                            f"{comp} {nb}B roundtrip corrupt"
                        s = str(nb)
                        pu, gu = _median_us(ps), _median_us(gs)
                        out[comp]["put_us"][s] = round(pu, 1)
                        out[comp]["get_us"][s] = round(gu, 1)
                        out[comp]["put_busbw_gbs"][s] = round(
                            nb / (pu * 1e-6) / 1e9, 3)
                        out[comp]["get_busbw_gbs"][s] = round(
                            nb / (gu * 1e-6) / 1e9, 3)
                    comm.Barrier()
                    win.free()

                # small-op ladder: accumulate rate + fetch_and_op
                # latency (int32: the device component's jitted
                # typed-kernel path)
                _force(comm, comp)
                win = osc.allocate(comm, 64, disp_unit=4,
                                   name=f"acc-{comp}")
                one = np.ones(8, dtype=np.int32)
                res = np.empty(1, dtype=np.int32)
                if me == 0:
                    win.lock(1, osc.LOCK_SHARED)
                    for _ in range(4):
                        win.accumulate(one, 1, op=SUM)
                    t0 = time.perf_counter()
                    for _ in range(200):
                        win.accumulate(one, 1, op=SUM)
                    win.flush(1)
                    dt = time.perf_counter() - t0
                    out[comp]["acc_rate_kops"] = round(0.2 / dt, 2)
                    for _ in range(4):
                        win.fetch_and_op(1, res, 1, op=SUM)
                    fs = []
                    for _ in range(64):
                        t0 = time.perf_counter()
                        win.fetch_and_op(1, res, 1, op=SUM)
                        fs.append(time.perf_counter() - t0)
                    out[comp]["fao_us"] = round(_median_us(fs), 1)
                    win.unlock(1)
                comm.Barrier()
                win.free()
        finally:
            registry.set("osc", "")
            comm.__dict__.pop("_osc_pick", None)
        return out if me == 0 else None

    res = run_ranks(nranks, fn, devices=True, timeout=1800)
    data = res[0]
    probe: Dict = {"nranks": nranks, "sizes": sizes,
                   "components": data}
    # the ISSUE acceptance ratios: device over pt2pt busbw per size,
    # for put and get.  The gate takes the worst of put/get at the
    # 1 MiB tier (the name says exactly what it checks); the full
    # curves stay in the JSON — above cache residency a single-stream
    # host memcpy converges toward DRAM bandwidth and the ratio
    # honestly narrows.
    gate: List[float] = []
    for kind in ("put", "get"):
        ratios = {}
        for s in map(str, sizes):
            p = data["pt2pt"][f"{kind}_busbw_gbs"].get(s)
            d = data["device"][f"{kind}_busbw_gbs"].get(s)
            if p and d:
                ratios[s] = round(d / p, 2)
                if int(s) == (1 << 20):
                    gate.append(ratios[s])
        probe[f"{kind}_ratio_device_over_pt2pt"] = ratios
    probe["device_5x_at_1mib"] = bool(gate) and min(gate) >= 5.0
    return probe


def persist(probe: Dict, detail_path: str) -> Dict:
    """Merge under 'probe_rma' in BENCH_DETAIL.json."""
    notes: Dict = {}
    try:
        with open(detail_path) as fh:
            detail = json.load(fh)
        if not isinstance(detail, dict):
            detail = {}
    except (OSError, ValueError):
        detail = {}
    detail["probe_rma"] = probe
    try:
        tmp = f"{detail_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(detail, fh, indent=1)
        os.replace(tmp, detail_path)
    except OSError as e:
        notes["detail_error"] = str(e)[:120]
    return notes
