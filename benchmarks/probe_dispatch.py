"""--probe-dispatch microbench: the measured dispatch constant, the
device-vs-host crossover per collective, and the fusion amortization
ratio (ISSUE 2 acceptance: a batch of 8 fused small allreduces must
land under 3x the single-op dispatch constant, vs ~8x unfused).

Thread-rank worlds (ompi_tpu.testing.run_ranks): the device world maps
ranks onto jax devices (coll/tpu or coll/hbm, whichever the layout
makes eligible); the host world runs the same collectives through the
arr_host staging path (coll/tuned over the inproc btl) — the seg-path
proxy of the 4-64 KiB band.  Each rep is timed individually and the
MEDIAN is reported: blocking collectives synchronize the world each
call, so a rep measures exactly the dispatch + rendezvous cost a
program pays, and the median rejects scheduler-preemption outliers.

Results are persisted under ``probe_dispatch`` in BENCH_DETAIL.json
(read-modify-write: the sweep data of a prior full run is preserved)
and the swept crossovers refresh the coll/calibrate per-host profile,
so ``--mca coll_tuned_use_measured_rules 1`` consumes *measured* data.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict

import numpy as np

SIZES = (4096, 16384, 65536)
FUSED_OPS = 8
FUSED_BYTES = 16384
_CAP = 4 << 20  # mirror calibrate._CROSSOVER_CAP


def _time_loop(comm, call, reps: int) -> float:
    """Median us/op over individually-timed reps (every rank loops;
    the collective itself synchronizes each rep).  Median, not mean:
    on an oversubscribed host a single scheduler preemption inflates
    one rep by milliseconds, and the dispatch constant being probed is
    the typical-rep cost, not the tail."""
    call()  # warm: compile + first-dispatch costs stay out
    call()
    comm.Barrier()
    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        call()
        samples.append(time.perf_counter() - t0)
    comm.Barrier()
    samples.sort()
    mid = len(samples) // 2
    med = samples[mid] if len(samples) % 2 else \
        (samples[mid - 1] + samples[mid]) / 2
    return med * 1e6


def _payload(comm, kind: str, nbytes: int, device: bool):
    n = max(comm.size, nbytes // 4)
    if kind == "alltoall":
        n -= n % comm.size
    if device:
        import jax.numpy as jnp
        return jnp.arange(n, dtype=jnp.float32) + comm.rank
    return np.arange(n, dtype=np.float32) + comm.rank


def _call(comm, kind: str, x):
    from ompi_tpu.op.op import SUM
    if kind == "allreduce":
        return lambda: comm.allreduce_arr(x, SUM)
    if kind == "bcast":
        return lambda: comm.bcast_arr(x, 0)
    return lambda: comm.alltoall_arr(x)


def _world_sweep(device: bool, nranks: int, reps: int) -> Dict:
    """One world: per-kind latency at each probe size (+ fusion batch
    timings in the device world)."""
    from ompi_tpu.testing import run_ranks

    def fn(comm):
        out: Dict = {"lat_us": {}}
        for kind in ("allreduce", "bcast", "alltoall"):
            out["lat_us"][kind] = {
                str(nb): round(_time_loop(
                    comm, _call(comm, kind, _payload(comm, kind, nb,
                                                     device)), reps), 1)
                for nb in SIZES}
        if device:
            import jax.numpy as jnp
            from ompi_tpu.op.op import SUM
            xs = [jnp.arange(FUSED_BYTES // 4, dtype=jnp.float32) * (i + 1)
                  for i in range(FUSED_OPS)]

            def fused():
                reqs = [comm.iallreduce_arr(x, SUM) for x in xs]
                comm.flush_arr()
                return reqs

            def sequential():
                return [comm.allreduce_arr(x, SUM) for x in xs]

            out["fused_batch_us"] = round(
                _time_loop(comm, fused, reps), 1)
            out["sequential_us"] = round(
                _time_loop(comm, sequential, reps), 1)
        return out

    res = run_ranks(nranks, fn, devices=device, timeout=600)
    return res[0]  # rank 0's medians (each rep is world-synchronized)


def _crossover(dev_lat: Dict[str, float], host_lat: Dict[str, float]) -> int:
    """Smallest probed size where the device path wins; 0 when it
    always wins, capped when it never does."""
    for nb in SIZES:
        d, h = dev_lat.get(str(nb)), host_lat.get(str(nb))
        if d is not None and h is not None and d <= h:
            return 0 if nb == SIZES[0] else nb
    return _CAP


def run_probe(nranks: int = 8, reps: int = 20) -> Dict:
    dev = _world_sweep(True, nranks, reps)
    host = _world_sweep(False, nranks, reps)
    probe: Dict = {
        "nranks": nranks,
        "sizes": list(SIZES),
        "device_us": dev["lat_us"],
        "host_us": host["lat_us"],
        # the per-op dispatch constant: smallest-payload device
        # latency (the op itself is ~free there — BENCH_NOTES r5)
        "dispatch_us": {k: dev["lat_us"][k][str(SIZES[0])]
                        for k in dev["lat_us"]},
        "crossover_bytes": {k: _crossover(dev["lat_us"][k],
                                          host["lat_us"][k])
                            for k in dev["lat_us"]},
    }
    single = probe["dispatch_us"]["allreduce"]
    fused_us = dev.get("fused_batch_us")
    seq_us = dev.get("sequential_us")
    if fused_us and single:
        probe["fused"] = {
            "batch_ops": FUSED_OPS,
            "payload_bytes": FUSED_BYTES,
            "fused_batch_us": fused_us,
            "sequential_us": seq_us,
            "single_op_us": single,
            "ratio_vs_single": round(fused_us / single, 2),
            "meets_3x_target": bool(fused_us < 3 * single),
        }
    return probe


def persist(probe: Dict, detail_path: str) -> Dict:
    """Merge under 'probe_dispatch' in BENCH_DETAIL.json (preserving
    sweep data from prior rounds) and refresh the calibrate profile
    with the swept crossovers."""
    notes = {}
    try:
        with open(detail_path) as fh:
            detail = json.load(fh)
        if not isinstance(detail, dict):
            detail = {}
    except (OSError, ValueError):
        detail = {}
    detail["probe_dispatch"] = probe
    try:
        tmp = f"{detail_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(detail, fh, indent=1)
        os.replace(tmp, detail_path)
    except OSError as e:
        notes["detail_error"] = str(e)[:120]

    try:
        from ompi_tpu.coll import calibrate
        prof = calibrate.get_profile(create=True) or {}
        prof = dict(prof)
        prof["source"] = "probe_dispatch_sweep"
        prof["dispatch_us"] = probe["dispatch_us"]["allreduce"]
        prof["crossover_bytes"] = probe["crossover_bytes"]
        notes["profile_path"] = calibrate.save_profile(prof)
    except Exception as e:  # noqa: BLE001
        notes["profile_error"] = str(e)[:120]
    return notes
