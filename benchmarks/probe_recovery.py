"""--probe-recovery microbench: ULFM forward-recovery latency + cost.

Two questions, answered on a 4-rank thread-rank world (the TPU-host
execution model, same harness as the other probes):

1. **How fast is recovery?**  Rank 1 dies deterministically
   (ulfm.kill_now, no timer race) while the survivors are parked in a
   host Allreduce.  Each survivor times the forward-recovery pipeline
   from the instant of death: detect (ERR_PROC_FAILED raised out of
   the parked collective), shrink (survivor comm built, mesh caches
   dropped), and first post-shrink collective completing with the
   right answer.  Reported numbers are rank 0's, best-of-REPS — the
   contamination-free floor, same convention as trace_overhead.

2. **What does the capability cost when nothing fails?**  The ULFM
   entry checks ride every blocking collective and p2p op; when
   ``mpi_ft_ulfm`` is on but no failure has been recorded the cost is
   one attribute load + one ``active`` flag check.  Measured like
   trace_overhead: interleaved off/on reps of small host Allreduces,
   best-of per side, LOUD failure in bench.py when the on-side
   exceeds the budget.

Results land in BENCH_DETAIL.json under ``probe_recovery``.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict

NRANKS = 4
VICTIM = 1
OPS = 400          # allreduces per overhead rep
WARMUP = 20
REPS = 5
BUDGET_PCT = 5.0   # acceptance bound for the ULFM-on healthy path


def _measure_recovery() -> Dict:
    """One kill → detect → shrink → first-collective timeline."""
    import numpy as np

    from ompi_tpu.errhandler import MPIException
    from ompi_tpu.ft import ulfm
    from ompi_tpu.op.op import SUM
    from ompi_tpu.testing import run_ranks

    # the victim stamps t0 the instant before it dies; survivors
    # subtract it from their own perf_counter reads (thread ranks
    # share one clock, so no correction is needed)
    t0 = [0.0]

    def fn(comm):
        sbuf = np.ones(16, dtype=np.float64)
        rbuf = np.zeros(16, dtype=np.float64)
        for _ in range(3):
            comm.Allreduce(sbuf, rbuf, SUM)
        comm.Barrier()
        if comm.rank == VICTIM:
            time.sleep(0.05)  # let survivors park in the Allreduce
            t0[0] = time.perf_counter()
            ulfm.kill_now(comm.state)
        try:
            while True:
                comm.Allreduce(sbuf, rbuf, SUM)
        except MPIException as e:
            t_detect = time.perf_counter()
            assert e.code in (75, 76, 77), e.code
        sub = comm.shrink(name="bench-survivors")
        t_shrink = time.perf_counter()
        sub.Allreduce(sbuf, rbuf, SUM)
        t_first = time.perf_counter()
        assert rbuf[0] == float(sub.size)
        return {
            "detect_ms": (t_detect - t0[0]) * 1e3,
            "shrink_ms": (t_shrink - t_detect) * 1e3,
            "first_coll_ms": (t_first - t_shrink) * 1e3,
            "total_ms": (t_first - t0[0]) * 1e3,
        }

    out = run_ranks(NRANKS, fn, allow_failures=True, timeout=120)
    return out[0]  # rank 0's view; victim's slot is None


def _measure_overhead(enabled: bool) -> float:
    """us/op of the healthy small-Allreduce loop with ULFM on|off."""
    import numpy as np

    from ompi_tpu.mca.params import registry
    from ompi_tpu.op.op import SUM
    from ompi_tpu.testing import run_ranks

    registry.set("mpi_ft_ulfm", "1" if enabled else "0")

    def fn(comm):
        if enabled:
            assert comm.state.ulfm is not None
        else:
            assert comm.state.ulfm is None
        sbuf = np.ones(8, dtype=np.float32)
        rbuf = np.zeros(8, dtype=np.float32)
        for _ in range(WARMUP):
            comm.Allreduce(sbuf, rbuf, SUM)
        comm.Barrier()
        t0 = time.perf_counter()
        for _ in range(OPS):
            comm.Allreduce(sbuf, rbuf, SUM)
        return (time.perf_counter() - t0) / OPS * 1e6

    return run_ranks(NRANKS, fn, timeout=300)[0]


def run_probe() -> Dict:
    from ompi_tpu.mca.params import registry

    prior = registry.get("mpi_ft_ulfm", "1")
    recs = []
    off_times, on_times = [], []
    try:
        registry.set("mpi_ft_ulfm", "1")
        for _ in range(REPS):
            recs.append(_measure_recovery())
        for _ in range(REPS):
            off_times.append(_measure_overhead(False))
            on_times.append(_measure_overhead(True))
    finally:
        registry.set("mpi_ft_ulfm", prior)
    best = min(recs, key=lambda r: r["total_ms"])
    off_us = min(off_times)
    on_us = min(on_times)
    overhead = (on_us - off_us) / off_us * 100.0
    return {
        "nranks": NRANKS,
        "victim": VICTIM,
        "reps": REPS,
        "detect_ms": round(best["detect_ms"], 3),
        "shrink_ms": round(best["shrink_ms"], 3),
        "first_coll_ms": round(best["first_coll_ms"], 3),
        "total_ms": round(best["total_ms"], 3),
        "total_ms_all": [round(r["total_ms"], 3) for r in recs],
        "ops_per_rep": OPS,
        "payload_bytes": 32,
        "off_us_per_op": round(off_us, 2),
        "on_us_per_op": round(on_us, 2),
        "off_us_all": [round(x, 2) for x in off_times],
        "on_us_all": [round(x, 2) for x in on_times],
        "overhead_pct": round(overhead, 2),
        "budget_pct": BUDGET_PCT,
        "within_budget": bool(overhead <= BUDGET_PCT),
    }


def persist(probe: Dict, detail_path: str) -> Dict:
    """Merge under 'probe_recovery' in BENCH_DETAIL.json, preserving
    every other section (the probe_dispatch/trace_overhead pattern)."""
    notes: Dict = {}
    try:
        with open(detail_path) as fh:
            detail = json.load(fh)
        if not isinstance(detail, dict):
            detail = {}
    except (OSError, ValueError):
        detail = {}
    detail["probe_recovery"] = probe
    try:
        tmp = f"{detail_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(detail, fh, indent=1)
        os.replace(tmp, detail_path)
    except OSError as e:
        notes["detail_error"] = str(e)[:120]
    return notes
