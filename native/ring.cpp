// SPSC shared-memory ring: the native data plane of the shm BTL.
//
// TPU-native re-design of the vader btl's fast-box transfer path
// (ref: opal/mca/btl/vader/btl_vader_module.c) with the reference's
// per-arch asm atomics (ref: opal/include/opal/sys/atomic.h:40-308)
// replaced by C++11 std::atomic acquire/release — the layout matches
// ompi_tpu/btl/shm.py exactly:
//
//   [0:8)   head  (producer cursor, monotonic bytes)
//   [8:16)  tail  (consumer cursor, monotonic bytes)
//   [16:)   data  (capacity ring; frames = u32-be length + payload)
//
// Single producer / single consumer.  The producer publishes frames
// with a release store on head; the consumer acquires head before
// reading and releases tail after consuming, giving the cross-process
// happens-before the pure-Python fallback only gets from x86 TSO.

#include <atomic>
#include <cstdint>
#include <cstring>

namespace {

constexpr uint64_t kHdr = 16;

inline std::atomic<uint64_t>* head_of(uint8_t* base) {
    return reinterpret_cast<std::atomic<uint64_t>*>(base);
}
inline std::atomic<uint64_t>* tail_of(uint8_t* base) {
    return reinterpret_cast<std::atomic<uint64_t>*>(base + 8);
}

inline void copy_in(uint8_t* data, uint64_t cap, uint64_t pos,
                    const uint8_t* src, uint64_t n) {
    uint64_t off = pos % cap;
    uint64_t first = n < cap - off ? n : cap - off;
    std::memcpy(data + off, src, first);
    if (first < n) std::memcpy(data, src + first, n - first);
}

inline void copy_out(const uint8_t* data, uint64_t cap, uint64_t pos,
                     uint8_t* dst, uint64_t n) {
    uint64_t off = pos % cap;
    uint64_t first = n < cap - off ? n : cap - off;
    std::memcpy(dst, data + off, first);
    if (first < n) std::memcpy(dst + first, data, n - first);
}

}  // namespace

extern "C" {

// Returns 1 on success, 0 when the ring lacks space.
int tpumpi_ring_push(uint8_t* base, uint64_t cap, const uint8_t* frame,
                     uint64_t len) {
    auto* head = head_of(base);
    auto* tail = tail_of(base);
    uint64_t h = head->load(std::memory_order_relaxed);
    uint64_t t = tail->load(std::memory_order_acquire);
    uint64_t need = 4 + len;
    if (need > cap - (h - t)) return 0;
    uint8_t hdr[4] = {static_cast<uint8_t>(len >> 24),
                      static_cast<uint8_t>(len >> 16),
                      static_cast<uint8_t>(len >> 8),
                      static_cast<uint8_t>(len)};
    uint8_t* data = base + kHdr;
    copy_in(data, cap, h, hdr, 4);
    copy_in(data, cap, h + 4, frame, len);
    head->store(h + need, std::memory_order_release);
    return 1;
}

// Two-part push (frag header + raw payload) so the producer never
// concatenates them host-side.  Returns 1 on success, 0 on no space.
int tpumpi_ring_push2(uint8_t* base, uint64_t cap, const uint8_t* b1,
                      uint64_t l1, const uint8_t* b2, uint64_t l2) {
    auto* head = head_of(base);
    auto* tail = tail_of(base);
    uint64_t h = head->load(std::memory_order_relaxed);
    uint64_t t = tail->load(std::memory_order_acquire);
    uint64_t len = l1 + l2;
    uint64_t need = 4 + len;
    if (need > cap - (h - t)) return 0;
    uint8_t hdr[4] = {static_cast<uint8_t>(len >> 24),
                      static_cast<uint8_t>(len >> 16),
                      static_cast<uint8_t>(len >> 8),
                      static_cast<uint8_t>(len)};
    uint8_t* data = base + kHdr;
    copy_in(data, cap, h, hdr, 4);
    copy_in(data, cap, h + 4, b1, l1);
    if (l2) copy_in(data, cap, h + 4 + l1, b2, l2);
    head->store(h + need, std::memory_order_release);
    return 1;
}

// Returns the length of the next frame, or -1 when the ring is empty.
// Does not consume.
int64_t tpumpi_ring_peek(uint8_t* base, uint64_t cap) {
    auto* head = head_of(base);
    auto* tail = tail_of(base);
    uint64_t h = head->load(std::memory_order_acquire);
    uint64_t t = tail->load(std::memory_order_relaxed);
    if (h - t < 4) return -1;
    uint8_t hdr[4];
    copy_out(base + kHdr, cap, t, hdr, 4);
    uint64_t len = (uint64_t(hdr[0]) << 24) | (uint64_t(hdr[1]) << 16) |
                   (uint64_t(hdr[2]) << 8) | uint64_t(hdr[3]);
    if (h - t < 4 + len) return -1;  // frame still being written
    return static_cast<int64_t>(len);
}

// Consumes the next frame into out (must hold peek() bytes).
// Returns 1 on success, 0 if empty/incomplete.
int tpumpi_ring_pop(uint8_t* base, uint64_t cap, uint8_t* out,
                    uint64_t out_cap) {
    int64_t len = tpumpi_ring_peek(base, cap);
    if (len < 0 || static_cast<uint64_t>(len) > out_cap) return 0;
    auto* tail = tail_of(base);
    uint64_t t = tail->load(std::memory_order_relaxed);
    copy_out(base + kHdr, cap, t + 4, out, static_cast<uint64_t>(len));
    tail->store(t + 4 + static_cast<uint64_t>(len),
                std::memory_order_release);
    return 1;
}

uint64_t tpumpi_ring_readable(uint8_t* base) {
    return head_of(base)->load(std::memory_order_acquire) -
           tail_of(base)->load(std::memory_order_relaxed);
}

}  // extern "C"
