// Shared-segment collectives: the C hot path of ompi_tpu/coll/seg.py.
//
// One reentrant call executes a whole small collective against the
// per-communicator mmap segment (layout v2, defined by coll/seg.py:
// [magic u64][done i64*P][seq i64*P*2][posted i64*2][left i64*2]
// [data u8*P*2*slot]).  The
// Python layer measured ~133 us of CPU per rank per 8-rank op for
// the same protocol (cache-cold interpreter + numpy dispatch under
// process rotation on an oversubscribed host); this path touches a
// few hundred bytes of code and exactly the protocol words, so a
// visit costs the futex syscalls plus a short memcpy/fold.
//
// Re-design counterpart: ompi/mca/coll/sm's shared-segment
// fan-in/fan-out (coll_sm_module.c) with raw futexes standing in for
// its pthread-in-shm synchronisation.
//
// Reentry contract (the caller loops while the return value is 1 and
// sweeps its pml progress engine between calls, so passive-target
// RMA targeting a blocked rank is still serviced):
//   0  -> collective complete (out filled where applicable)
//   1  -> still waiting on peers; call again with identical args
//  -1  -> unsupported (op, dtype) combination; caller must run its
//         fallback BEFORE any segment mutation happened (the probe
//         is the first thing checked)
//
// Phases are recovered from segment state, never from caller state:
//   done[rank] >= gen            -> already complete (idempotent 0)
//   seq[rank][gen&1] >= gen      -> posted; skip to the wait phase
//   otherwise                    -> bank-reuse guard, post, wait
//
// v2 (r5): waiters park on per-bank COMPLETION WORDS instead of
// staggered per-rank flag words.  The staggered scheme woke every
// parked waiter on EVERY post (each recheck re-parks on the next
// laggard): O(P^2) scheduler slices per op on an oversubscribed
// host.  Now each poster stores its own seq flag, scans the P flags
// (cheap loads), and whichever rank's scan first observes them all
// publishes gen into posted[bank] and issues ONE wake; waiters park
// once on that word and wake once.  left[bank] mirrors this for the
// bank-reuse guard over the done flags.  Plain aligned stores of
// monotonically increasing gens — no atomic RMW, so the no-lib
// Python protocol can speak the same segment wordings.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <linux/futex.h>
#include <sys/syscall.h>
#include <unistd.h>

namespace {

inline long futex_wait(volatile int32_t* addr, int32_t expected,
                       long timeout_ns) {
    struct timespec ts;
    ts.tv_sec = timeout_ns / 1000000000L;
    ts.tv_nsec = timeout_ns % 1000000000L;
    return syscall(SYS_futex, (void*)addr, FUTEX_WAIT, expected, &ts,
                   nullptr, 0);
}

inline void futex_wake(volatile int32_t* addr) {
    syscall(SYS_futex, (void*)addr, FUTEX_WAKE, 1 << 30, nullptr,
            nullptr, 0);
}

struct Seg {
    uint8_t* base;
    int64_t P, slot;
    volatile int64_t* done;     // [P]
    volatile int64_t* seq;      // [P][2]
    volatile int64_t* posted;   // [2]  all-posted gen per bank
    volatile int64_t* left;     // [2]  all-done gen per bank
    uint8_t* data;              // [P][2][slot]

    Seg(uint8_t* b, int64_t p, int64_t s) : base(b), P(p), slot(s) {
        done = reinterpret_cast<volatile int64_t*>(base + 8);
        seq = reinterpret_cast<volatile int64_t*>(base + 8 + 8 * P);
        posted = reinterpret_cast<volatile int64_t*>(
            base + 8 + 8 * P + 16 * P);
        left = posted + 2;
        data = base + 8 + 8 * P + 16 * P + 32;
    }
    volatile int64_t* seq_at(int64_t p, int64_t b) const {
        return seq + p * 2 + b;
    }
    uint8_t* slot_at(int64_t p, int64_t b) const {
        return data + (p * 2 + b) * slot;
    }
    static volatile int32_t* word(volatile int64_t* w) {
        return reinterpret_cast<volatile int32_t*>(
            const_cast<int64_t*>(w));  // little-endian low half
    }
};

// Scan the P per-rank flags; when all reached `gen`, publish it into
// the bank's completion word (idempotent: every publisher stores the
// same monotonically-increasing value) and wake its waiters.
template <typename GetWord>
inline bool scan_publish(GetWord f, int64_t P, int64_t gen,
                         volatile int64_t* complete_w) {
    for (int64_t i = 0; i < P; ++i)
        if (__atomic_load_n(f(i), __ATOMIC_ACQUIRE) < gen) return false;
    if (__atomic_load_n(complete_w, __ATOMIC_ACQUIRE) < gen) {
        __atomic_store_n(complete_w, gen, __ATOMIC_RELEASE);
        futex_wake(Seg::word(complete_w));
    }
    return true;
}

// Wait until the completion word reaches `gen`; one park per
// invocation (on timeout the caller sweeps progress and re-enters).
// `f`/`P` name the underlying flags: the waiter re-scans them before
// parking so a missed publication (both scanning ranks raced) can
// never strand the bank — any waiter can become the publisher.
template <typename GetWord>
inline bool wait_complete(GetWord f, int64_t P, int64_t gen,
                          volatile int64_t* complete_w, long park_ns) {
    for (;;) {
        if (__atomic_load_n(complete_w, __ATOMIC_ACQUIRE) >= gen)
            return true;
        if (scan_publish(f, P, gen, complete_w)) return true;
        volatile int32_t* w32 = Seg::word(complete_w);
        int32_t cur = __atomic_load_n(w32, __ATOMIC_ACQUIRE);
        if ((int64_t)cur >= gen) continue;
        futex_wait(w32, cur, park_ns);
        if (__atomic_load_n(complete_w, __ATOMIC_ACQUIRE) >= gen)
            return true;
        return scan_publish(f, P, gen, complete_w);
    }
}

// Single-word generation wait (bcast non-roots watch the root's seq
// flag; exactly one writer, so no herd to avoid).
inline bool wait_word_ge(volatile int64_t* w, int64_t gen,
                         long park_ns) {
    for (;;) {
        if (__atomic_load_n(w, __ATOMIC_ACQUIRE) >= gen) return true;
        volatile int32_t* w32 = Seg::word(w);
        int32_t cur = __atomic_load_n(w32, __ATOMIC_ACQUIRE);
        if ((int64_t)cur >= gen) continue;
        futex_wait(w32, cur, park_ns);
        return __atomic_load_n(w, __ATOMIC_ACQUIRE) >= gen;
    }
}

enum Kind {
    K_BARRIER = 0,
    K_BCAST = 1,
    K_ALLREDUCE = 2,
    K_REDUCE = 3,
    K_ALLGATHER = 4,
    K_ALLTOALL = 5,
    K_REDUCE_SCATTER = 6,
};

enum OpCode {
    OP_SUM = 0, OP_PROD, OP_MAX, OP_MIN,
    OP_BAND, OP_BOR, OP_BXOR, OP_LAND, OP_LOR, OP_LXOR,
    OP_NONE = 99,
};

enum DtCode {
    DT_F32 = 0, DT_F64, DT_I8, DT_U8, DT_I16, DT_U16,
    DT_I32, DT_U32, DT_I64, DT_U64,
};

template <typename T>
inline T op_apply(int op, T a, T b) {
    switch (op) {
        case OP_SUM: return (T)(a + b);
        case OP_PROD: return (T)(a * b);
        case OP_MAX: return a > b ? a : b;
        case OP_MIN: return a < b ? a : b;
        default: return a;
    }
}

template <typename T>
inline T iop_apply(int op, T a, T b) {
    switch (op) {
        case OP_BAND: return (T)(a & b);
        case OP_BOR: return (T)(a | b);
        case OP_BXOR: return (T)(a ^ b);
        case OP_LAND: return (T)((a && b) ? 1 : 0);
        case OP_LOR: return (T)((a || b) ? 1 : 0);
        case OP_LXOR: return (T)(((!!a) ^ (!!b)) ? 1 : 0);
        default: return op_apply(op, a, b);
    }
}

template <typename T, bool INT>
void fold_span(const Seg& seg, int64_t b, int op, int64_t off,
               int64_t len_elems, uint8_t* out) {
    // rank-order left fold (basic_linear order — bit-identical with
    // the Python path and coll/sm)
    const T* s0 = reinterpret_cast<const T*>(seg.slot_at(0, b)) + off;
    T* o = reinterpret_cast<T*>(out);
    std::memcpy(o, s0, len_elems * sizeof(T));
    for (int64_t p = 1; p < seg.P; ++p) {
        const T* sp =
            reinterpret_cast<const T*>(seg.slot_at(p, b)) + off;
        for (int64_t i = 0; i < len_elems; ++i) {
            if constexpr (INT)
                o[i] = iop_apply(op, o[i], sp[i]);
            else
                o[i] = op_apply(op, o[i], sp[i]);
        }
    }
}

bool fold(const Seg& seg, int64_t b, int op, int dt, int64_t off_bytes,
          int64_t nbytes, uint8_t* out) {
    switch (dt) {
        case DT_F32:
            if (op > OP_MIN) return false;
            fold_span<float, false>(seg, b, op, off_bytes / 4,
                                    nbytes / 4, out);
            return true;
        case DT_F64:
            if (op > OP_MIN) return false;
            fold_span<double, false>(seg, b, op, off_bytes / 8,
                                     nbytes / 8, out);
            return true;
        case DT_I8:
            fold_span<int8_t, true>(seg, b, op, off_bytes, nbytes, out);
            return true;
        case DT_U8:
            fold_span<uint8_t, true>(seg, b, op, off_bytes, nbytes, out);
            return true;
        case DT_I16:
            fold_span<int16_t, true>(seg, b, op, off_bytes / 2,
                                     nbytes / 2, out);
            return true;
        case DT_U16:
            fold_span<uint16_t, true>(seg, b, op, off_bytes / 2,
                                      nbytes / 2, out);
            return true;
        case DT_I32:
            fold_span<int32_t, true>(seg, b, op, off_bytes / 4,
                                     nbytes / 4, out);
            return true;
        case DT_U32:
            fold_span<uint32_t, true>(seg, b, op, off_bytes / 4,
                                      nbytes / 4, out);
            return true;
        case DT_I64:
            fold_span<int64_t, true>(seg, b, op, off_bytes / 8,
                                     nbytes / 8, out);
            return true;
        case DT_U64:
            fold_span<uint64_t, true>(seg, b, op, off_bytes / 8,
                                      nbytes / 8, out);
            return true;
    }
    return false;
}

bool supported(int kind, int op, int dt) {
    if (kind == K_BARRIER || kind == K_BCAST || kind == K_ALLGATHER ||
        kind == K_ALLTOALL)
        return true;
    if (dt == DT_F32 || dt == DT_F64) return op <= OP_MIN;
    return op <= OP_LXOR;
}

}  // namespace

extern "C" int tpumpi_seg_coll(
    uint8_t* base, int64_t P, int64_t slot, int64_t rank, int64_t gen,
    int32_t kind, int32_t root, const uint8_t* in, uint8_t* out,
    int64_t nbytes, int32_t dt, int32_t op, int64_t park_us) {
    if (!supported(kind, op, dt)) return -1;
    if (nbytes > slot) return -1;  // never overflow a slot (caller bug)
    Seg seg(base, P, slot);
    const int64_t b = gen & 1;
    const long park_ns = park_us * 1000L;

    if (__atomic_load_n(&seg.done[rank], __ATOMIC_ACQUIRE) >= gen)
        return 0;  // idempotent reentry after completion

    auto sget = [&](int64_t i) { return seg.seq_at(i, b); };
    auto dget = [&](int64_t i) { return &seg.done[i]; };

    // ---- post phase (once) --------------------------------------------
    if (__atomic_load_n(seg.seq_at(rank, b), __ATOMIC_ACQUIRE) < gen) {
        if (gen >= 2) {
            // bank-reuse guard: nobody may still be reading this bank
            // from op gen-2 (their done flags prove they left)
            if (!wait_complete(dget, P, gen - 2, &seg.left[b], park_ns))
                return 1;
        }
        bool writes = !(kind == K_BCAST && rank != root) &&
                      !(kind == K_BARRIER);
        if (writes && in && nbytes > 0)
            std::memcpy(seg.slot_at(rank, b), in, nbytes);
        __atomic_store_n(seg.seq_at(rank, b), gen, __ATOMIC_RELEASE);
        if (kind == K_BCAST && rank == root)
            futex_wake(Seg::word(seg.seq_at(rank, b)));
        scan_publish(sget, P, gen, &seg.posted[b]);
    }

    // ---- wait phase ----------------------------------------------------
    if (kind == K_BCAST) {
        if (rank != root) {
            if (!wait_word_ge(seg.seq_at(root, b), gen, park_ns))
                return 1;
        }
    } else {
        if (!wait_complete(sget, P, gen, &seg.posted[b], park_ns))
            return 1;
    }

    // ---- read/fold phase ------------------------------------------------
    switch (kind) {
        case K_BARRIER:
            break;
        case K_BCAST:
            if (rank != root && out && nbytes > 0)
                std::memcpy(out, seg.slot_at(root, b), nbytes);
            break;
        case K_ALLREDUCE:
            if (!fold(seg, b, op, dt, 0, nbytes, out)) return -1;
            break;
        case K_REDUCE:
            if (rank == root)
                if (!fold(seg, b, op, dt, 0, nbytes, out)) return -1;
            break;
        case K_ALLGATHER:
            for (int64_t p = 0; p < P; ++p)
                std::memcpy(out + p * nbytes, seg.slot_at(p, b), nbytes);
            break;
        case K_ALLTOALL: {
            const int64_t blk = nbytes / P;
            for (int64_t p = 0; p < P; ++p)
                std::memcpy(out + p * blk,
                            seg.slot_at(p, b) + rank * blk, blk);
            break;
        }
        case K_REDUCE_SCATTER: {
            const int64_t blk = nbytes / P;
            if (!fold(seg, b, op, dt, rank * blk, blk, out)) return -1;
            break;
        }
    }

    __atomic_store_n(&seg.done[rank], gen, __ATOMIC_RELEASE);
    scan_publish(dget, P, gen, &seg.left[b]);
    return 0;
}
