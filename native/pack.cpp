// Strided pack/unpack kernels: the native hot path of the datatype
// convertor (ref: opal/datatype pack/unpack loops; our descriptor
// model collapses the reference's loop/element bytecode to strided
// runs, see ompi_tpu/datatype/engine.py).

#include <cstdint>
#include <cstring>

extern "C" {

// Gather nblocks of block_bytes each, stride apart, into dst.
void tpumpi_pack_strided(const uint8_t* src, uint8_t* dst,
                         uint64_t block_bytes, int64_t stride,
                         uint64_t nblocks) {
    for (uint64_t b = 0; b < nblocks; ++b) {
        std::memcpy(dst + b * block_bytes,
                    src + static_cast<int64_t>(b) * stride, block_bytes);
    }
}

// Scatter packed src back into strided dst blocks.
void tpumpi_unpack_strided(uint8_t* dst, const uint8_t* src,
                           uint64_t block_bytes, int64_t stride,
                           uint64_t nblocks) {
    for (uint64_t b = 0; b < nblocks; ++b) {
        std::memcpy(dst + static_cast<int64_t>(b) * stride,
                    src + b * block_bytes, block_bytes);
    }
}

}  // extern "C"
